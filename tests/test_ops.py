"""Op unit tests vs NumPy (reference model: test/legacy_test/ OpTest files)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import op_test


def r(*shape):
    return np.random.randn(*shape).astype(np.float32)


class TestMath:
    def test_binary_ops(self):
        x, y = r(3, 4), r(3, 4)
        op_test.check_output(paddle.add, np.add, [x, y])
        op_test.check_output(paddle.subtract, np.subtract, [x, y])
        op_test.check_output(paddle.multiply, np.multiply, [x, y])
        op_test.check_output(paddle.divide, np.divide, [x, y + 2.0])
        op_test.check_output(paddle.maximum, np.maximum, [x, y])
        op_test.check_output(paddle.minimum, np.minimum, [x, y])

    def test_broadcast(self):
        op_test.check_output(paddle.add, np.add, [r(3, 1, 4), r(2, 1)])

    def test_unary(self):
        x = np.abs(r(5, 3)) + 0.5
        op_test.check_output(paddle.exp, np.exp, [x])
        op_test.check_output(paddle.log, np.log, [x])
        op_test.check_output(paddle.sqrt, np.sqrt, [x])
        op_test.check_output(paddle.tanh, np.tanh, [x], rtol=1e-4)
        op_test.check_output(paddle.abs, np.abs, [r(4)])
        op_test.check_output(paddle.floor, np.floor, [r(4)])
        op_test.check_output(paddle.sin, np.sin, [x])

    def test_matmul(self):
        op_test.check_output(paddle.matmul, np.matmul, [r(3, 4), r(4, 5)],
                             rtol=1e-4)
        a, b = r(2, 3, 4), r(2, 4, 5)
        op_test.check_output(paddle.matmul, np.matmul, [a, b], rtol=1e-4)
        # transpose flags
        out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b.swapaxes(1, 2)),
                            transpose_y=True)
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-4)

    def test_reductions(self):
        x = r(3, 4, 5)
        op_test.check_output(paddle.sum, lambda a: np.sum(a), [x], rtol=1e-4)
        op_test.check_output(lambda t: paddle.sum(t, axis=1),
                             lambda a: a.sum(axis=1), [x], rtol=1e-4)
        op_test.check_output(lambda t: paddle.mean(t, axis=[0, 2], keepdim=True),
                             lambda a: a.mean(axis=(0, 2), keepdims=True), [x],
                             rtol=1e-4)
        op_test.check_output(lambda t: paddle.max(t, axis=1),
                             lambda a: a.max(axis=1), [x])
        op_test.check_output(lambda t: paddle.std(t),
                             lambda a: a.std(ddof=1), [x], rtol=1e-3)
        op_test.check_output(paddle.logsumexp,
                             lambda a: np.log(np.exp(a).sum()), [x], rtol=1e-4)

    def test_cumsum(self):
        x = r(3, 4)
        op_test.check_output(lambda t: paddle.cumsum(t, axis=1),
                             lambda a: np.cumsum(a, axis=1), [x], rtol=1e-4)

    def test_clip_scale(self):
        x = r(4, 4)
        op_test.check_output(lambda t: paddle.clip(t, -0.5, 0.5),
                             lambda a: np.clip(a, -0.5, 0.5), [x])
        out = paddle.scale(paddle.to_tensor(x), scale=2.0, bias=1.0)
        np.testing.assert_allclose(out.numpy(), x * 2 + 1, rtol=1e-5)


class TestGrad:
    def test_matmul_grad(self):
        op_test.check_grad(paddle.matmul, [r(3, 4), r(4, 5)])

    def test_mul_grad(self):
        op_test.check_grad(paddle.multiply, [r(3, 4), r(3, 4)])

    def test_tanh_grad(self):
        op_test.check_grad(paddle.tanh, [r(3, 3)])

    def test_softmax_grad(self):
        import paddle_tpu.nn.functional as F

        op_test.check_grad(F.softmax, [r(4, 6)])

    def test_reduce_grad(self):
        op_test.check_grad(lambda t: paddle.sum(t, axis=0), [r(3, 4)])
        op_test.check_grad(lambda t: paddle.mean(t, axis=1, keepdim=True),
                           [r(3, 4)])

    def test_broadcast_grad(self):
        op_test.check_grad(paddle.add, [r(3, 4), r(4)])

    def test_concat_grad(self):
        op_test.check_grad(lambda a, b: paddle.concat([a, b], axis=1),
                           [r(2, 3), r(2, 4)])

    def test_layernorm_grad(self):
        import paddle_tpu.nn.functional as F

        op_test.check_grad(
            lambda x, w, b: F.layer_norm(x, 8, w, b), [r(4, 8), r(8), r(8)],
            rtol=5e-2, atol=5e-3)


class TestManipulation:
    def test_reshape_transpose(self):
        x = r(2, 3, 4)
        op_test.check_output(lambda t: paddle.reshape(t, [6, 4]),
                             lambda a: a.reshape(6, 4), [x])
        op_test.check_output(lambda t: paddle.transpose(t, [2, 0, 1]),
                             lambda a: a.transpose(2, 0, 1), [x])
        op_test.check_output(lambda t: paddle.flatten(t, 1),
                             lambda a: a.reshape(2, 12), [x])

    def test_concat_stack_split(self):
        x, y = r(2, 3), r(2, 3)
        out = paddle.concat([paddle.to_tensor(x), paddle.to_tensor(y)], axis=0)
        np.testing.assert_allclose(out.numpy(), np.concatenate([x, y], 0))
        out = paddle.stack([paddle.to_tensor(x), paddle.to_tensor(y)], axis=1)
        np.testing.assert_allclose(out.numpy(), np.stack([x, y], 1))
        parts = paddle.split(paddle.to_tensor(x), 3, axis=1)
        assert len(parts) == 3 and parts[0].shape == [2, 1]
        parts = paddle.split(paddle.to_tensor(r(2, 7)), [2, 5], axis=1)
        assert parts[1].shape == [2, 5]

    def test_gather_scatter(self):
        x = r(5, 3)
        idx = np.array([0, 2, 4], np.int32)
        op_test.check_output(paddle.gather, lambda a, i: a[i], [x, idx])
        nd_idx = np.array([[0, 1], [2, 2]], np.int32)
        op_test.check_output(paddle.gather_nd,
                             lambda a, i: a[tuple(i.T)], [x, nd_idx])

    def test_where_topk_sort(self):
        x = r(3, 5)
        cond = x > 0
        out = paddle.where(paddle.to_tensor(cond), paddle.to_tensor(x),
                           paddle.to_tensor(-x))
        np.testing.assert_allclose(out.numpy(), np.where(cond, x, -x))
        vals, idx = paddle.topk(paddle.to_tensor(x), k=2, axis=1)
        ref = np.sort(x, axis=1)[:, ::-1][:, :2]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)
        out = paddle.sort(paddle.to_tensor(x), axis=1, descending=True)
        np.testing.assert_allclose(out.numpy(), np.sort(x, 1)[:, ::-1])

    def test_indexing(self):
        x = r(4, 5, 6)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(t[1].numpy(), x[1])
        np.testing.assert_allclose(t[1:3, ::2].numpy(), x[1:3, ::2])
        np.testing.assert_allclose(t[..., -1].numpy(), x[..., -1])
        np.testing.assert_allclose(t[:, None, 0].numpy(), x[:, None, 0])
        idx = paddle.to_tensor(np.array([0, 2], np.int32))
        np.testing.assert_allclose(t[idx].numpy(), x[[0, 2]])
        # boolean mask (eager-only)
        mask = x[:, 0, 0] > 0
        np.testing.assert_allclose(t[paddle.to_tensor(mask)].numpy(), x[mask])

    def test_setitem(self):
        x = r(4, 5)
        t = paddle.to_tensor(x.copy())
        t[1:3, 0] = 7.0
        x[1:3, 0] = 7.0
        np.testing.assert_allclose(t.numpy(), x)

    def test_pad_tile_flip(self):
        x = r(2, 3)
        op_test.check_output(lambda t: paddle.tile(t, [2, 1]),
                             lambda a: np.tile(a, (2, 1)), [x])
        op_test.check_output(lambda t: paddle.flip(t, [0]),
                             lambda a: np.flip(a, 0).copy(), [x])

    def test_argmax_unique(self):
        x = r(3, 4)
        assert paddle.argmax(paddle.to_tensor(x)).item() == x.argmax()
        u = paddle.unique(paddle.to_tensor(np.array([3, 1, 2, 1, 3])))
        np.testing.assert_array_equal(u.numpy(), [1, 2, 3])


class TestLogic:
    def test_comparisons(self):
        x, y = r(3, 4), r(3, 4)
        op_test.check_output(paddle.equal, np.equal, [x, x])
        op_test.check_output(paddle.greater_than, np.greater, [x, y])
        assert bool(paddle.allclose(paddle.to_tensor(x),
                                    paddle.to_tensor(x + 1e-9)).item())

    def test_operator_overloads(self):
        x = paddle.to_tensor([1.0, 2.0])
        np.testing.assert_allclose((x + 1).numpy(), [2, 3])
        np.testing.assert_allclose((2 * x).numpy(), [2, 4])
        np.testing.assert_allclose((1 - x).numpy(), [0, -1])
        np.testing.assert_allclose((x ** 2).numpy(), [1, 4])
        np.testing.assert_allclose((-x).numpy(), [-1, -2])
        assert (x > 1.5).numpy().tolist() == [False, True]


class TestLinalg:
    def test_basic(self):
        a = r(4, 4)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        op_test.check_output(paddle.linalg.cholesky, np.linalg.cholesky, [spd],
                             rtol=1e-4, atol=1e-5)
        op_test.check_output(paddle.linalg.inv, np.linalg.inv, [spd],
                             rtol=1e-4, atol=1e-5)
        op_test.check_output(paddle.linalg.det, np.linalg.det, [spd], rtol=1e-4)
        x = r(6)
        assert abs(paddle.linalg.norm(paddle.to_tensor(x)).item()
                   - np.linalg.norm(x)) < 1e-4

    def test_einsum(self):
        a, b = r(3, 4), r(4, 5)
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-4)


class TestCreation:
    def test_creation(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2, 3], "int32").dtype == np.dtype("int32")
        np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                                   np.linspace(0, 1, 5), rtol=1e-6)
        assert paddle.full([2], 7).item(0) == 7
        e = paddle.eye(3).numpy()
        np.testing.assert_array_equal(e, np.eye(3, dtype=np.float32))

    def test_random(self):
        paddle.seed(7)
        a = paddle.randn([100, 100])
        assert abs(a.numpy().mean()) < 0.1
        u = paddle.uniform([1000], min=0.0, max=1.0)
        assert 0 <= u.numpy().min() and u.numpy().max() <= 1
        paddle.seed(7)
        b = paddle.randn([100, 100])
        np.testing.assert_allclose(a.numpy(), b.numpy())

    def test_one_hot(self):
        x = paddle.to_tensor(np.array([0, 2], np.int32))
        oh = paddle.one_hot(x, 3).numpy()
        np.testing.assert_array_equal(oh, [[1, 0, 0], [0, 0, 1]])
