"""Regression tests for round-1 advisor findings (ADVICE.md).

Covers: bf16-safe distributed checkpoint storage, rank-namespaced shard
keys + per-rank metadata merge, GradScaler double-unscale, boolean-mask
indexing staying on the autograd tape, and the Pallas/XLA causal-mask
alignment gate.
"""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class TestCheckpointDtypes:
    def test_bf16_roundtrip(self):
        from paddle_tpu.distributed.checkpoint import (
            load_state_dict,
            save_state_dict,
        )

        t = paddle.to_tensor(
            np.random.randn(8, 4).astype("float32")
        ).astype("bfloat16")
        d = tempfile.mkdtemp()
        save_state_dict({"w": t}, d)
        # npz must not contain void-typed data
        raw = np.load(os.path.join(d, "rank0.npz"))
        for k in raw.files:
            assert raw[k].dtype.kind != "V", f"{k} stored as void"
        out = {"w": paddle.zeros([8, 4], dtype="bfloat16")}
        load_state_dict(out, d)
        np.testing.assert_array_equal(
            np.asarray(out["w"]._data, dtype="float32"),
            np.asarray(t._data, dtype="float32"),
        )

    def test_shard_keys_rank_namespaced_and_merged_metadata(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from paddle_tpu.distributed.checkpoint import (
            load_state_dict,
            save_state_dict,
        )

        mesh = jax.make_mesh((8,), ("x",))
        src = np.arange(64, dtype="float32").reshape(8, 8)
        arr = jax.device_put(src, NamedSharding(mesh, P("x")))
        t = paddle.zeros([8, 8])
        t._rebind(arr)
        d = tempfile.mkdtemp()
        save_state_dict({"s": t}, d)
        raw = np.load(os.path.join(d, "rank0.npz"))
        assert all("@r0s" in k for k in raw.files), raw.files
        assert os.path.exists(os.path.join(d, "rank0.meta.json"))

        # reshard-on-load onto a different mesh/layout
        mesh2 = jax.make_mesh((4, 2), ("a", "b"))
        tgt = jax.device_put(
            np.zeros((8, 8), "float32"), NamedSharding(mesh2, P("b", "a"))
        )
        out = paddle.zeros([8, 8])
        out._rebind(tgt)
        load_state_dict({"s": out}, d)
        np.testing.assert_array_equal(np.asarray(out._data), src)


class TestGradScalerUnscaleOnce:
    def test_unscale_then_step_divides_once(self):
        lin = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(
            learning_rate=0.0, parameters=lin.parameters()
        )
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
        x = paddle.to_tensor(np.ones((2, 4), "float32"))
        loss = lin(x).sum()
        scaled = scaler.scale(loss)
        scaled.backward()
        # reference AMP pattern: unscale -> (clip) -> step -> update
        scaler.unscale_(opt)
        g_after_unscale = np.asarray(lin.weight.grad._data).copy()
        scaler.step(opt)
        scaler.update()
        g_after_step = np.asarray(lin.weight.grad._data)
        # grads must be the true (unscaled-once) gradient: d(sum(xW+b))/dW = 2
        np.testing.assert_allclose(g_after_unscale, 2.0, rtol=1e-5)
        np.testing.assert_allclose(g_after_step, 2.0, rtol=1e-5)

    def test_update_resets_unscaled_flag(self):
        lin = nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(
            learning_rate=0.0, parameters=lin.parameters()
        )
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
        for _ in range(2):
            loss = lin(paddle.to_tensor(np.ones((1, 2), "float32"))).sum()
            scaler.scale(loss).backward()
            scaler.unscale_(opt)
            scaler.step(opt)
            scaler.update()
            np.testing.assert_allclose(
                np.asarray(lin.weight.grad._data), 1.0, rtol=1e-5
            )
            opt.clear_grad()


class TestBoolMaskAutograd:
    def test_getitem_bool_mask_keeps_grad(self):
        x = paddle.to_tensor(
            np.arange(6, dtype="float32"), stop_gradient=False
        )
        mask = paddle.to_tensor(
            np.array([True, False, True, False, True, False])
        )
        y = x[mask]
        assert not y.stop_gradient
        np.testing.assert_array_equal(y.numpy(), [0.0, 2.0, 4.0])
        y.sum().backward()
        np.testing.assert_array_equal(
            x.grad.numpy(), [1.0, 0.0, 1.0, 0.0, 1.0, 0.0]
        )

    def test_getitem_2d_bool_mask(self):
        x = paddle.to_tensor(
            np.arange(12, dtype="float32").reshape(3, 4), stop_gradient=False
        )
        m = np.zeros((3, 4), bool)
        m[0, 1] = m[2, 3] = True
        y = x[paddle.to_tensor(m)]
        np.testing.assert_array_equal(y.numpy(), [1.0, 11.0])
        y.sum().backward()
        expect = np.zeros((3, 4), "float32")
        expect[0, 1] = expect[2, 3] = 1.0
        np.testing.assert_array_equal(x.grad.numpy(), expect)

    def test_setitem_bool_mask(self):
        x = paddle.to_tensor(np.zeros(4, "float32"))
        x[paddle.to_tensor(np.array([True, False, True, False]))] = 5.0
        np.testing.assert_array_equal(x.numpy(), [5.0, 0.0, 5.0, 0.0])


class TestFlashAttnGate:
    def test_pallas_refused_for_kv_prefill(self):
        from paddle_tpu.nn.functional.flash_attention import _use_pallas

        q = np.zeros((1, 128, 8, 64), "float32")
        k = np.zeros((1, 256, 8, 64), "float32")
        # seq_k != seq_q → must take the XLA path regardless of backend
        assert _use_pallas(q, k) is False

    def test_sdpa_causal_bottom_right_aligned(self):
        # seq_k > seq_q: query i attends keys [0, i + (sk - sq)]
        from paddle_tpu.nn.functional import scaled_dot_product_attention

        q = paddle.to_tensor(np.random.randn(1, 2, 1, 8).astype("float32"))
        k = paddle.to_tensor(np.random.randn(1, 4, 1, 8).astype("float32"))
        v = paddle.to_tensor(np.random.randn(1, 4, 1, 8).astype("float32"))
        out = scaled_dot_product_attention(q, k, v, is_causal=True)
        # manual bottom-right-aligned reference
        qn = np.transpose(q.numpy(), (0, 2, 1, 3))
        kn = np.transpose(k.numpy(), (0, 2, 1, 3))
        vn = np.transpose(v.numpy(), (0, 2, 1, 3))
        logits = qn @ kn.transpose(0, 1, 3, 2) / np.sqrt(8.0)
        mask = np.tril(np.ones((2, 4), bool), k=2)
        logits = np.where(mask, logits, -1e30)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        ref = np.transpose(p @ vn, (0, 2, 1, 3))
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-4)


class TestRound3AdviceFixes:
    def test_grad_scaler_single_fused_finite_check(self):
        """unscale_ must detect inf AND only sync the host once (fused
        all-finite accumulator), not once per parameter."""
        model = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
        x = paddle.to_tensor(np.ones((2, 4), "float32"))
        loss = model(x).sum()
        scaler.scale(loss).backward()
        # poison one grad with inf
        p = model.parameters()[0]
        bad = np.array(p.grad.numpy())
        bad[0, 0] = np.inf
        p.grad._rebind(paddle.to_tensor(bad)._data)
        before = model.parameters()[1].numpy().copy()
        scaler.step(opt)
        scaler.update()
        # step skipped on inf
        np.testing.assert_allclose(model.parameters()[1].numpy(), before)
        assert scaler.get_loss_scaling().numpy() < 2.0

    def test_profiler_transit_teardown_on_custom_scheduler(self):
        """A scheduler that drops RECORD -> READY without RECORD_AND_RETURN
        must still finish the window (recorder off, callback fired)."""
        from paddle_tpu import profiler as prof
        from paddle_tpu.profiler.profiler import RECORDER, ProfilerState

        fired = []

        def sched(step):
            return (ProfilerState.RECORD if step < 2
                    else ProfilerState.READY)

        p = prof.Profiler(scheduler=sched,
                          on_trace_ready=lambda pr: fired.append(1))
        p.start()
        p.step()
        p.step()  # transition RECORD -> READY
        assert RECORDER.enabled is False
        assert fired == [1]
        p.stop()

    def test_eager_send_recv_raise_multiprocess(self, monkeypatch):
        import jax
        import paddle_tpu.distributed as dist

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        t = paddle.to_tensor([1.0])
        with pytest.raises(NotImplementedError):
            dist.send(t, dst=1)
        with pytest.raises(NotImplementedError):
            dist.recv(t, src=0)

    def test_fused_step_scheduler_opt_out(self):
        sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=1,
                                              gamma=0.5)
        model = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=sched,
                                   parameters=model.parameters())
        step = paddle.incubate.fused_train_step(
            model, opt, loss_fn=lambda o: o.sum(), step_lr_scheduler=False)
        x = paddle.to_tensor(np.ones((2, 4), "float32"))
        step(x)
        assert sched.get_lr() == pytest.approx(0.1)  # untouched
        sched.step()
        assert sched.get_lr() == pytest.approx(0.05)


class TestRound4AdviceFixes:
    def test_engine_predict_multi_input_unlabeled(self):
        """ADVICE r3: Engine.predict must not drop a real input of a
        multi-input unlabeled dataset (e.g. DeepFM's (ids, dense))."""
        from paddle_tpu.distributed.auto_parallel import Engine
        from paddle_tpu.io import Dataset

        class TwoIn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 1)

            def forward(self, a, b):
                return self.fc(a + b)

        class DS(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                return (np.ones(4, "float32") * i,
                        np.ones(4, "float32"))

        m = TwoIn()
        eng = Engine(model=m, loss=nn.MSELoss(),
                     optimizer=paddle.optimizer.SGD(
                         learning_rate=0.1, parameters=m.parameters()))
        outs = eng.predict(DS(), batch_size=2)
        assert len(outs) == 2 and outs[0].shape == (2, 1)

    def test_engine_predict_labeled_still_drops_label(self):
        from paddle_tpu.distributed.auto_parallel import Engine
        from paddle_tpu.io import Dataset

        class OneIn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 1)

            def forward(self, a):
                return self.fc(a)

        class DS(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                return (np.ones(4, "float32"), np.float32(1.0))

        m = OneIn()
        eng = Engine(model=m, loss=nn.MSELoss(),
                     optimizer=paddle.optimizer.SGD(
                         learning_rate=0.1, parameters=m.parameters()))
        outs = eng.predict(DS(), batch_size=2)
        assert len(outs) == 2 and outs[0].shape == (2, 1)

    def test_fft_numpy_fallback_refuses_live_grad(self, monkeypatch):
        """ADVICE r3: the host fft fallback must raise instead of silently
        detaching a grad-requiring input."""
        import paddle_tpu.fft as pfft

        monkeypatch.setattr(pfft, "_COMPLEX_OK", False)
        x = paddle.to_tensor(np.random.randn(8).astype("float32"))
        x.stop_gradient = False
        with pytest.raises(RuntimeError, match="fallback"):
            pfft.fft(x)
        # detached input still works
        y = paddle.to_tensor(np.random.randn(8).astype("float32"))
        out = pfft.fft(y)
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.fft.fft(np.asarray(y._data)),
                                   rtol=1e-5)

    def test_vjp_none_grad_slot_matches_primal_shape(self):
        """ADVICE r3: float0/None grad slots must carry primal-shaped zeros,
        not 0-d scalars."""
        from paddle_tpu.core.dispatch import _op_vjp_fn
        import jax.numpy as jnp

        # where(cond, a, b): cond is boolean -> float0 grad slot
        cond = jnp.array([True, False, True])
        a = jnp.ones(3, jnp.float32)
        b = jnp.zeros(3, jnp.float32)
        ct = jnp.ones(3, jnp.float32)
        grads = _op_vjp_fn(cond, a, b, ct, op_name="where", n_primals=3,
                           op_kwargs=(), out_tuple=False)
        assert grads[0].shape == cond.shape  # not a 0-d scalar
        assert grads[1].shape == a.shape


class TestAmpDebugging:
    def test_operator_stats_collection(self, capsys):
        """amp.debugging collects a per-op dtype histogram from dispatch
        (VERDICT r4 item 8; reference amp/debugging.py:459)."""
        with paddle.amp.debugging.collect_operator_stats():
            a = paddle.to_tensor(np.ones((4, 4), "float32"))
            b = a.astype("bfloat16")
            _ = b @ b
            _ = a + a
        out = capsys.readouterr().out
        assert "Op Name" in out and "BF16 Calls" in out
        stats = paddle.amp.debugging.operator_stats()
        assert any(v[1] > 0 for v in stats.values())  # a bf16 call counted
        assert any(v[2] > 0 for v in stats.values())  # an fp32 call counted
        # collection is off again
        from paddle_tpu.core import dispatch
        assert dispatch.OP_STATS is None

    def test_compare_accuracy(self, tmp_path):
        model = nn.Linear(8, 8)

        def fn(x):
            return model(x)

        x = paddle.to_tensor(np.random.randn(16, 8).astype("float32"))
        csvf = str(tmp_path / "cmp.csv")
        report = paddle.amp.debugging.compare_accuracy(
            fn, [x], amp_level="O1", dtype="bfloat16", output_filename=csvf)
        assert report[0]["max_rel_err"] < 0.2
        assert report[0]["max_abs_err"] > 0.0  # bf16 really differs
        import os
        assert os.path.exists(csvf)


class TestRound6AdviceFixes:
    def test_row_conv_per_feature_filter(self):
        """row_conv must use the reference [future_context+1, D] filter:
        each feature dim has its own context weights."""
        from paddle_tpu.static import nn as snn
        from paddle_tpu.static.nn import common as snn_common

        snn.reset_parameters()
        B, T, D, fc_size = 2, 6, 4, 2
        x = paddle.to_tensor(np.random.randn(B, T, D).astype("float32"))
        out = snn.row_conv(x, fc_size)
        assert out.shape == [B, T, D]
        params = snn_common.parameters()
        assert len(params) == 1
        w = params[0]
        assert list(w.shape) == [fc_size + 1, D]
        # oracle: out[b, t, d] = sum_i x[b, t+i, d] * w[i, d]
        xn, wn = x.numpy(), w.numpy()
        k = fc_size + 1
        pad = np.concatenate([xn, np.zeros((B, k - 1, D), np.float32)], 1)
        ref = sum(pad[:, i:i + T] * wn[i] for i in range(k))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)
        snn.reset_parameters()

    def test_to_device_preserves_flags(self):
        t = paddle.to_tensor(np.random.randn(3, 3).astype("float32"))
        t.stop_gradient = False
        t.persistable = True
        moved = t.cpu()
        assert moved.stop_gradient is False
        assert moved.persistable is True
        assert moved.name == t.name
        np.testing.assert_array_equal(moved.numpy(), t.numpy())

    def test_fused_mha_keeps_explicit_head_dim(self):
        """Non-transpose qkv layout: head_dim comes from qkv_weight.shape
        and may differ from embed_dim // num_heads."""
        import paddle_tpu.incubate.nn.functional as IF

        b, s, e = 2, 5, 8
        n_heads, head_dim = 2, 6  # != e // n_heads
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(b, s, e).astype("float32"))
        qkv_w = paddle.to_tensor(
            rng.randn(3, n_heads, head_dim, e).astype("float32") * 0.1)
        lin_w = paddle.to_tensor(
            rng.randn(n_heads * head_dim, e).astype("float32") * 0.1)
        out = IF.fused_multi_head_attention(
            x, qkv_w, lin_w, pre_layer_norm=True,
            dropout_rate=0.0, attn_dropout_rate=0.0, training=False)
        assert out.shape == [b, s, e]
        assert np.isfinite(out.numpy()).all()

    def test_builder_registry_distinguishes_attrs(self):
        """Same-shape unnamed builder calls with different initializers
        must NOT share parameters."""
        from paddle_tpu.static import nn as snn
        from paddle_tpu.static.nn import common as snn_common

        snn.reset_parameters()
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        zeros = paddle.ParamAttr(
            initializer=nn.initializer.Constant(0.0))
        ones = paddle.ParamAttr(
            initializer=nn.initializer.Constant(1.0))
        out0 = snn.fc(x, 3, weight_attr=zeros, bias_attr=False)
        out1 = snn.fc(x, 3, weight_attr=ones, bias_attr=False)
        assert len(snn_common.parameters()) == 2
        np.testing.assert_array_equal(out0.numpy(), 0.0)
        np.testing.assert_allclose(out1.numpy(), 4.0, rtol=1e-6)
        # repeat call with the SAME attr config still reuses its layer
        out0b = snn.fc(x, 3, weight_attr=zeros, bias_attr=False)
        assert len(snn_common.parameters()) == 2
        np.testing.assert_array_equal(out0b.numpy(), out0.numpy())
        snn.reset_parameters()
