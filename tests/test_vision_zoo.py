"""Round-4 vision model-zoo additions: forward shapes, one backward, and
the reference vision/models __all__ audit.

Reference: python/paddle/vision/models/__init__.py + test/legacy_test
test_vision_models.py (shape-level checks, same as here).
"""

import ast

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.vision.models as M

# heavyweight module (model zoo / e2e / subprocess): slow tier
pytestmark = pytest.mark.slow


def _img(hw, bs=1):
    return paddle.to_tensor(
        np.random.RandomState(0).randn(bs, 3, hw, hw).astype(np.float32))


class TestNewModelsForward:
    @pytest.mark.parametrize("factory,hw", [
        (lambda: M.alexnet(num_classes=10), 64),
        (lambda: M.squeezenet1_1(num_classes=10), 64),
        (lambda: M.mobilenet_v1(scale=0.25, num_classes=10), 64),
        (lambda: M.mobilenet_v3_small(scale=0.5, num_classes=10), 64),
        (lambda: M.shufflenet_v2_x0_25(num_classes=10), 64),
        (lambda: M.densenet121(num_classes=10), 64),
        (lambda: M.inception_v3(num_classes=10), 80),
    ])
    def test_forward_shape(self, factory, hw):
        paddle.seed(0)
        m = factory()
        m.eval()
        out = m(_img(hw))
        assert list(out.shape) == [1, 10]
        assert np.isfinite(out.numpy()).all()

    def test_googlenet_aux_heads(self):
        paddle.seed(0)
        m = M.googlenet(num_classes=10)
        m.eval()
        out, aux1, aux2 = m(_img(64))
        for o in (out, aux1, aux2):
            assert list(o.shape) == [1, 10]

    def test_feature_mode_no_classifier(self):
        m = M.mobilenet_v3_small(scale=0.5, num_classes=0, with_pool=True)
        m.eval()
        out = m(_img(64))
        assert out.ndim == 4  # pooled features, no fc

    def test_backward_trains(self):
        paddle.seed(1)
        m = M.shufflenet_v2_x0_25(num_classes=4)
        m.train()
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters())
        x = _img(64, bs=4)
        y = paddle.to_tensor(np.array([0, 1, 2, 3], np.int64))
        loss_fn = paddle.nn.CrossEntropyLoss()
        first = None
        for _ in range(4):
            loss = loss_fn(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(loss.numpy())
        assert float(loss.numpy()) < first


class TestVisionAuditComplete:
    def test_reference_models_all_covered(self):
        src = open("/root/reference/python/paddle/vision/models/"
                   "__init__.py").read()
        ref_all = None
        for node in ast.walk(ast.parse(src)):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", None) == "__all__":
                        ref_all = ast.literal_eval(node.value)
        assert ref_all
        missing = [n for n in ref_all if not hasattr(M, n)]
        assert missing == [], f"vision.models gaps: {missing}"
