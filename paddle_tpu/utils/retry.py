"""Transient-failure retry with exponential backoff + jitter.

One backoff shape for every durability path: ``framework/io.save``,
``distributed/checkpoint`` shard writes, and ``fleet.utils.fs.LocalFS``
renames all funnel through :func:`retry_os`, so the retry budget is tuned in
one place (``FLAGS_ckpt_save_retries``). The reference Paddle hand-rolls the
same shape per call site (e.g. HDFSClient's sleep_inter loop); centralizing
it keeps the checkpoint lifecycle's failure semantics uniform.
"""

from __future__ import annotations

import os
import random
import time

__all__ = ["retry_os", "atomic_write"]

# deterministic failures: retrying can't fix a missing path, a permission
# wall, or a path-type mismatch — surface them immediately, no backoff
_NON_TRANSIENT = (FileNotFoundError, PermissionError, FileExistsError,
                  IsADirectoryError, NotADirectoryError)


def retry_os(fn, retries=None, base_delay=0.01, max_delay=0.5, jitter=0.5,
             rng=None, retry_on=(OSError,)):
    """Call ``fn()``; on a *transient* exception in ``retry_on`` retry up to
    ``retries`` times (default ``FLAGS_ckpt_save_retries``), sleeping
    ``min(max_delay, base_delay * 2**attempt) * (1 + jitter * U[0,1))``
    between attempts. Deterministic OSErrors (missing path, permissions,
    path-type mismatch) are never retried. The final failure re-raises the
    original exception. Pass a seeded ``rng`` (anything with ``.random()``)
    for deterministic jitter in tests."""
    if retries is None:
        from ..core.flags import flag_value

        retries = int(flag_value("ckpt_save_retries", 3))
    if rng is None:
        rng = random
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            if isinstance(e, _NON_TRANSIENT) or attempt >= retries:
                raise
            delay = min(max_delay, base_delay * (2 ** attempt))
            time.sleep(delay * (1.0 + jitter * rng.random()))
            attempt += 1


def atomic_write(dest, write_body, fire_site=None):
    """The one atomic-publication shape: tmp file → ``write_body(f)`` →
    (injection point) → flush+fsync → ``os.replace``. The destination only
    ever holds complete bytes; any failure removes the tmp file and leaves
    the previous destination untouched. ``fire_site`` names the
    fault-injection site sitting in the "killed mid-save" window (data
    written, nothing published)."""
    from . import fault_injection

    tmp = f"{dest}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            write_body(f)
            if fire_site is not None:
                fault_injection.fire(fire_site)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, dest)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
