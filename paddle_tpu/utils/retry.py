"""Transient-failure retry with exponential backoff + jitter.

One backoff shape for every durability path: ``framework/io.save``,
``distributed/checkpoint`` shard writes, and ``fleet.utils.fs.LocalFS``
renames all funnel through :func:`retry_os`, so the retry budget is tuned in
one place (``FLAGS_ckpt_save_retries``). The reference Paddle hand-rolls the
same shape per call site (e.g. HDFSClient's sleep_inter loop); centralizing
it keeps the checkpoint lifecycle's failure semantics uniform.

Cross-filesystem publication: ``os.rename``/``os.replace`` across mount
points fails with ``EXDEV`` *deterministically* — retrying spins through the
whole budget and then fails anyway, which is why EXDEV is classified
non-transient here. :func:`replace_across_fs` is the escape hatch the
publish paths use instead: same-filesystem renames stay one atomic syscall,
and an EXDEV falls back to copy-to-tmp-on-the-destination-filesystem +
fsync + ``os.replace`` — the destination still only ever holds complete
bytes, so checkpoints to a mounted volume (NFS/GCS-FUSE scratch) keep the
atomic-visibility guarantee.
"""

from __future__ import annotations

import errno
import os
import random
import shutil
import time

__all__ = ["retry_os", "atomic_write", "replace_across_fs", "atomic_copy"]

# deterministic failures: retrying can't fix a missing path, a permission
# wall, or a path-type mismatch — surface them immediately, no backoff
_NON_TRANSIENT = (FileNotFoundError, PermissionError, FileExistsError,
                  IsADirectoryError, NotADirectoryError)
# errno-classified deterministic failures (no dedicated exception subclass):
# EXDEV (cross-device rename) needs a different *strategy*, not a retry
_NON_TRANSIENT_ERRNOS = frozenset({errno.EXDEV, errno.ENOSPC})


def _is_non_transient(e):
    return (isinstance(e, _NON_TRANSIENT)
            or getattr(e, "errno", None) in _NON_TRANSIENT_ERRNOS)


def retry_os(fn, retries=None, base_delay=0.01, max_delay=0.5, jitter=0.5,
             rng=None, retry_on=(OSError,)):
    """Call ``fn()``; on a *transient* exception in ``retry_on`` retry up to
    ``retries`` times (default ``FLAGS_ckpt_save_retries``), sleeping
    ``min(max_delay, base_delay * 2**attempt) * (1 + jitter * U[0,1))``
    between attempts. Deterministic OSErrors (missing path, permissions,
    path-type mismatch, cross-device rename, disk full) are never retried.
    The final failure re-raises the original exception. Pass a seeded
    ``rng`` (anything with ``.random()``) for deterministic jitter in
    tests."""
    if retries is None:
        from ..core.flags import flag_value

        retries = int(flag_value("ckpt_save_retries", 3))
    if rng is None:
        rng = random
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            if _is_non_transient(e) or attempt >= retries:
                raise
            delay = min(max_delay, base_delay * (2 ** attempt))
            time.sleep(delay * (1.0 + jitter * rng.random()))
            attempt += 1


def replace_across_fs(src, dst):
    """``os.replace`` that survives crossing a filesystem boundary. The
    fast path is the plain atomic rename; on ``EXDEV`` the payload is
    copied to a tmp name *on the destination filesystem*, fsynced, and
    published with a same-filesystem ``os.replace`` — so ``dst`` never
    holds partial bytes even when ``src`` lives on a different mount.
    Directories fall back to a tree copy published the same way. ``src``
    is removed after a successful cross-filesystem publish (rename
    semantics)."""
    try:
        os.replace(src, dst)
        return
    except OSError as e:
        if e.errno != errno.EXDEV:
            raise
    tmp = f"{dst}.xfs.{os.getpid()}"
    try:
        if os.path.isdir(src):
            _copytree_fsynced(src, tmp)
        else:
            with open(src, "rb") as fsrc, open(tmp, "wb") as fdst:
                shutil.copyfileobj(fsrc, fdst)
                fdst.flush()
                os.fsync(fdst.fileno())
        os.replace(tmp, dst)
    except BaseException:
        try:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)
            else:
                os.remove(tmp)
        except OSError:
            pass
        raise
    # publish succeeded; clearing the source is best-effort (a leftover
    # source never violates the destination's atomicity)
    try:
        if os.path.isdir(src):
            shutil.rmtree(src)
        else:
            os.remove(src)
    except OSError:
        pass


def _copytree_fsynced(src, tmp):
    """Copy ``src`` to the fresh tmp tree ``tmp`` and fsync every file:
    copytree alone does not fsync, and without the walk a power loss
    after a later publish could leave the destination as a
    complete-looking directory of truncated files."""
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    shutil.copytree(src, tmp)
    for root, _dirs, files in os.walk(tmp):
        for fn in files:
            with open(os.path.join(root, fn), "rb") as f:
                os.fsync(f.fileno())


def atomic_copy(src, dst):
    """Copy ``src`` (file or directory) to ``dst`` with atomic
    visibility: the payload lands under a tmp name next to ``dst``,
    is fsynced, and publishes with one rename — a torn ``dst`` is never
    visible. Files route through :func:`atomic_write` (fully atomic).

    Directory destinations are atomic-or-RECOVERABLE: ``os.replace``
    cannot clobber a non-empty directory, so an existing ``dst`` is
    first moved to the deterministic tool-owned quarantine name
    ``dst + ".__atomic_copy_old__"`` and deleted only after the new
    tree publishes. A process killed inside that window leaves ``dst``
    absent with the old tree intact under the quarantine name — the
    NEXT ``atomic_copy`` to the same destination restores it before
    doing anything else, so the previous contents are never lost (an
    in-process failure restores it immediately). The quarantine name is
    deliberately ugly: it belongs to this function, and anything found
    there is treated as its own crash leftover."""
    if os.path.isdir(src):
        old = f"{dst}.__atomic_copy_old__"
        # crash recovery from a previous copy killed between quarantine
        # and publish: the old tree is authoritative while dst is
        # missing; once dst exists again the leftover is stale
        if os.path.isdir(old):
            if not os.path.exists(dst):
                os.replace(old, dst)
            else:
                shutil.rmtree(old)
        tmp = f"{dst}.cp.{os.getpid()}"
        try:
            _copytree_fsynced(src, tmp)
            if os.path.isdir(dst):
                os.replace(dst, old)
                try:
                    replace_across_fs(tmp, dst)
                except BaseException:
                    try:
                        if not os.path.exists(dst):
                            os.replace(old, dst)
                    except OSError:
                        pass
                    raise
                shutil.rmtree(old, ignore_errors=True)
            else:
                replace_across_fs(tmp, dst)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return

    def body(f):
        with open(src, "rb") as fsrc:
            shutil.copyfileobj(fsrc, f)

    atomic_write(dst, body)


def atomic_write(dest, write_body, fire_site=None):
    """The one atomic-publication shape: tmp file → ``write_body(f)`` →
    (injection point) → flush+fsync → ``os.replace``. The destination only
    ever holds complete bytes; any failure removes the tmp file and leaves
    the previous destination untouched. ``fire_site`` names the
    fault-injection site sitting in the "killed mid-save" window (data
    written, nothing published). The final publish goes through
    :func:`replace_across_fs`, so a ``dest`` whose directory resolves to a
    different filesystem than the tmp file (exotic overlay/bind setups)
    still lands atomically instead of burning the retry budget on EXDEV."""
    from . import fault_injection

    tmp = f"{dest}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            write_body(f)
            if fire_site is not None:
                fault_injection.fire(fire_site)
            f.flush()
            os.fsync(f.fileno())
        replace_across_fs(tmp, dest)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
