"""Deterministic fault injection for the fault-tolerance surface.

Reference inspiration: the reference Paddle exercises its elastic/checkpoint
recovery paths with unit-test fakes (fake etcd stores, forced
check_finite_and_unscale overflows). Here every failure mode the durability
layer defends against is a *named site* that production code calls into; a
test (or a chaos drill) arms a site with :func:`inject` and the exact same
code path that would fail in production fails on demand, deterministically.

Sites wired into the framework:

- ``ckpt.shard_write``  — distributed.checkpoint shard write, fired after the
  shard payload hits the tmp file but before the atomic rename (the "process
  killed mid-save" window: data on disk, checkpoint not visible/committed).
- ``io.save``           — paddle.save pickle write, same window.
- ``train.grad_nan``    — FusedTrainStep input poisoning: the step's first
  floating-point input becomes NaN, so loss/grads go non-finite and the step
  guard must react.
- ``fs.rename``         — fleet.utils.fs.LocalFS.rename, fired before the
  os.rename (exercises the transient-OSError retry/backoff path).
- ``io.prefetch``       — DevicePrefetcher transfer thread, fired before a
  batch is staged (bucket-pad + device_put): the thread dies and the
  consumer must fall back to synchronous transfers without losing a batch.
- ``proc.kill``         — FusedTrainStep.drive loop head (boolean site): the
  worker SIGKILLs itself, simulating an OOM-killer/node loss at step N; the
  supervisor restarts the group and auto_resume must replay bit-exactly.
- ``hb.write``          — launch.heartbeat.write, fired before the heartbeat
  file is written: heartbeating is best-effort, so the write must fail
  WITHOUT crashing training (write() returns False).
- ``train.stall``       — FusedTrainStep.drive fetch point (boolean site):
  the step blocks as if a collective wedged; FLAGS_step_timeout_s surfaces
  it as TrainStallError (or, with the in-process guard off, the launcher's
  heartbeat watchdog kills + restarts the group).
- ``train.spike``       — FusedTrainStep input poisoning (boolean site):
  the step's first floating-point input is scaled by 1e3, so loss/grads go
  finite-but-huge — the NaN guard stays silent and the divergence sentinel
  (FLAGS_sentinel_action) must detect the spike at the next metric-fetch
  window boundary and warn/skip/rollback/raise.
- ``serve.replica_crash`` — fleet replica worker loop head (boolean site):
  the replica SIGKILLs itself mid-serve; the ReplicaSupervisor must see
  the death, respawn under the restart budget, and the Router must replay
  the replica's in-flight requests bit-exactly on a healthy peer.
- ``serve.replica_hang``  — fleet replica worker loop head (boolean site):
  the replica wedges forever WITHOUT heartbeating; only the supervisor's
  hang watchdog (SIGTERM→SIGKILL escalation) can end it — the redispatch
  dedup must also survive the window where the replica is presumed dead.
- ``serve.dispatch``      — Router placement, fired as a request is sent
  to a replica: the dispatch fails, the request requeues at the front
  with a bumped generation, and a half-delivered copy can never
  double-emit into the replayed stream.
- ``io.stream.open``      — StreamingDataset shard open, fired before the
  file handle is acquired: transient failures ride the shared retry/
  backoff budget; exhaustion surfaces as typed StreamReadError.
- ``io.stream.read``      — StreamingDataset frame read, fired before
  each positioned read (the retry re-seeks, so a flaky read can never
  skew the record framing); exhaustion surfaces as StreamReadError.
- ``io.stream.corrupt``   — StreamingDataset record decode: the record
  is treated as corrupt and must be QUARANTINED (skipped under the
  per-epoch skip budget, counted in io_records_quarantined_total) —
  never retried, never silently dropped past the budget.
- ``serve.prefill_crash`` — disaggregated prefill worker, fired between
  KV-page frame emissions (boolean site): the worker SIGKILLs itself
  MID-TRANSFER — the router must discard the partial pages atomically
  and re-drive the prefill on a healthy prefill worker
  (fleet_handoff_failovers_total), with decode streams of other
  requests never hiccuping.
- ``serve.kv_transfer_corrupt`` — disaggregated prefill worker, fired
  per KV-page frame (boolean site): the frame's payload bytes are
  corrupted AFTER its CRC was computed, so the router's CRC check must
  catch the mismatch and re-drive the prefill under the transfer retry
  budget (fleet_kv_transfer_retries_total) instead of decoding on
  garbage; past the budget the request fails with a typed
  KVTransferError.
- ``serve.kv_spill`` — HostKVTier spill capture, fired as a cold page
  set (preempted request or reclaimed prefix block) is snapshotted for
  the host-RAM tier: spilling is an *optimisation*, so the failure must
  degrade to plain recompute-eviction (the request re-prefills on
  re-admission; the block identity is simply forgotten) — never crash
  the engine, never leave a half-registered host entry.
- ``serve.store_write`` — persistent prefix-store save, fired after the
  CRC-framed shard payload hits the tmp file but before the atomic
  rename (the "killed mid-store-write" window): a previously published
  store must stay intact byte-for-byte and a torn shard must never
  become visible; boot after the failure recovers warm from the old
  store or cold-starts cleanly.
- ``serve.tenant_flood`` — Router admission, fired per submit: the fleet
  behaves as if a tenant flood has saturated the queue, so the submit is
  shed with a typed FleetOverloadedError carrying a machine-readable
  ``retry_after_s`` hint — well-behaved clients back off instead of
  hammering an overloaded fleet.
- ``serve.scale_down_kill`` — Router autoscale tick (boolean site), fired
  as a scale-down decision starts draining the victim replica: the
  replica is SIGKILLed MID-DRAIN, so its still-queued requests must ride
  the normal crash-redispatch path to healthy peers — scale-down remains
  zero-drop even when the retiring replica dies uncleanly.
- ``serve.group_member_crash`` — replica-group worker loop (boolean
  site), armed on ONE member rank of a multi-process replica group:
  that rank SIGKILLs itself mid-burst, the partial-group failure shape.
  The supervisor must fell the WHOLE group (survivors SIGTERM→SIGKILL —
  a half-dead tp group must never answer), charge one restart-budget
  slot, respawn the group on a fresh coordination port and redispatch
  its in-flight requests bit-exact.
- ``serve.group_member_hang`` — replica-group worker loop (boolean
  site), armed on ONE member rank: the rank wedges without
  heartbeating, so the group's next collective stalls EVERY member. No
  process exits — only the hang watchdog (any member's stale
  ``hb.<replica>.<rank>``) can detect it and fell the group.
- ``serve.bit_flip`` — replica worker loop (boolean site, ISSUE 20):
  injects SILENT data corruption (``integrity.flip_bit``) into a KV
  pool page, a host-tier entry, or a weight buffer
  (``CHAOS_SERVE_BIT_FLIP_TARGET`` picks which). Nothing crashes and
  nothing raises — only the integrity sentinel (page CRCs, the sampled
  output audit, the weight re-audit) can catch it.

Arming a site is scoped and seeded::

    with inject("ckpt.shard_write"):            # every call raises
        ...
    with inject("io.save", max_fires=1, exc=OSError):  # first call only
        ...
    with inject("train.grad_nan", every_n=3):   # calls 3, 6, 9, ...
        ...
    with inject("fs.rename", prob=0.5, seed=7): # seeded coin per call
        ...

Sites are process-global (checkpoint writes run on background threads and
must see the armed injector); nesting the same site restores the previous
injector on exit.
"""

from __future__ import annotations

import contextlib
import random

__all__ = ["SITES", "InjectedFault", "inject", "fire", "should_fire"]

SITES = ("ckpt.shard_write", "io.save", "train.grad_nan", "fs.rename",
         "io.prefetch", "proc.kill", "hb.write", "train.stall",
         "train.spike", "serve.replica_crash", "serve.replica_hang",
         "serve.dispatch", "io.stream.open", "io.stream.read",
         "io.stream.corrupt", "serve.prefill_crash",
         "serve.kv_transfer_corrupt", "serve.kv_spill",
         "serve.store_write", "serve.tenant_flood",
         "serve.scale_down_kill", "serve.group_member_crash",
         "serve.group_member_hang", "serve.bit_flip")


class InjectedFault(OSError):
    """Default injected exception. Subclasses OSError on purpose: the
    durability layer treats OSErrors as transient and retries them, so an
    armed site exercises the full backoff path before the failure wins."""


class _Injector:
    __slots__ = ("site", "every_n", "prob", "exc", "max_fires", "_rng",
                 "calls", "fires")

    def __init__(self, site, every_n=None, prob=None, exc=None, seed=0,
                 max_fires=None):
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r}; known: {SITES}")
        if every_n is not None and prob is not None:
            raise ValueError("pass at most one of every_n / prob")
        if every_n is not None and every_n < 1:
            raise ValueError("every_n must be >= 1")
        self.site = site
        self.every_n = every_n
        self.prob = prob
        self.exc = exc
        self.max_fires = max_fires
        self._rng = random.Random(seed)
        self.calls = 0
        self.fires = 0

    def should_fire(self):
        self.calls += 1
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.prob is not None:
            hit = self._rng.random() < self.prob
        elif self.every_n is not None:
            hit = self.calls % self.every_n == 0
        else:
            hit = True  # armed with no rate: every call fires
        if hit:
            self.fires += 1
        return hit

    def make_exc(self):
        exc = self.exc
        if exc is None:
            return InjectedFault(f"injected fault at site {self.site!r} "
                                 f"(call #{self.calls})")
        if isinstance(exc, BaseException):
            return exc
        return exc(f"injected fault at site {self.site!r} "
                   f"(call #{self.calls})")


_ACTIVE: dict[str, _Injector] = {}


@contextlib.contextmanager
def inject(site, every_n=None, prob=None, exc=None, seed=0, max_fires=None):
    """Arm ``site`` for the duration of the block. Exactly one of
    ``every_n`` (fire on calls n, 2n, ...) or ``prob`` (seeded Bernoulli per
    call) selects the rate; neither means every call fires. ``max_fires``
    caps total fires (e.g. ``max_fires=1`` = one transient failure, then
    healthy — the retry path must recover). ``exc`` is an exception class or
    instance for raising sites; boolean sites (``train.grad_nan``) ignore it.
    Yields the injector, whose ``calls``/``fires`` counters are readable
    after the block."""
    inj = _Injector(site, every_n=every_n, prob=prob, exc=exc, seed=seed,
                    max_fires=max_fires)
    prev = _ACTIVE.get(site)
    _ACTIVE[site] = inj
    try:
        yield inj
    finally:
        if prev is None:
            _ACTIVE.pop(site, None)
        else:
            _ACTIVE[site] = prev


def should_fire(site):
    """Boolean probe for non-raising sites (``train.grad_nan``). False when
    the site is unarmed — the production fast path is one dict lookup."""
    inj = _ACTIVE.get(site)
    return inj is not None and inj.should_fire()


def fire(site):
    """Raising probe for write-path sites: no-op when unarmed, raises the
    armed exception when the injector decides this call fails."""
    inj = _ACTIVE.get(site)
    if inj is not None and inj.should_fire():
        raise inj.make_exc()
