"""Utilities."""

from .functional_call import functional_call, params_dict  # noqa: F401


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError:
        return None


def deprecated(update_to="", since="", reason="", level=0):
    """reference utils/deprecated.py: warn-once decorator."""
    import functools
    import warnings

    def deco(fn):
        warned = []

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not warned:
                warned.append(True)
                msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
                if since:
                    msg += f" since {since}"
                if update_to:
                    msg += f", use {update_to} instead"
                if reason:
                    msg += f" ({reason})"
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def require_version(min_version, max_version=None):
    """reference utils/__init__.py require_version against
    paddle.__version__."""
    import paddle_tpu

    def parse(v):
        import re as _re

        parts = []
        for x in str(v).split(".")[:3]:
            m = _re.match(r"\d+", x)
            parts.append(int(m.group()) if m else 0)
        while len(parts) < 3:  # 0.1 == 0.1.0 under tuple comparison
            parts.append(0)
        return tuple(parts)

    cur = parse(paddle_tpu.__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {paddle_tpu.__version__} < required "
            f"{min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {paddle_tpu.__version__} > allowed "
            f"{max_version}")
    return True


def run_check():
    """reference utils/install_check.py run_check: compile + run a tiny
    training step on the available device(s) and report."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    dev = paddle.device.get_device()
    print(f"Running verify on {dev} ...")
    paddle.seed(0)
    model = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    x = paddle.to_tensor(np.random.rand(8, 4).astype("float32"))
    y = paddle.to_tensor(np.random.rand(8, 2).astype("float32"))
    loss = ((model(x) - y) ** 2).mean()
    loss.backward()
    opt.step()
    print(f"paddle_tpu is installed successfully on {dev}! loss="
          f"{float(loss.numpy()):.4f}")


__all__ = list(globals().get("__all__", [])) + [
    "deprecated", "require_version", "run_check"]
