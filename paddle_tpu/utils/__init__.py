"""Utilities."""

from .functional_call import functional_call, params_dict  # noqa: F401


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError:
        return None
