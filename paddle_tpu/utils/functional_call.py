"""Functional execution of a Layer — run a stateful Layer as a pure function
of a params pytree. This is the bridge between the eager Layer world and raw
jax transforms (grad/jit/shard_map); jit.to_static and the distributed train
steps are built on it."""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from ..core import state
from ..core.tensor import Tensor


def params_dict(layer, include_buffers=False):
    """name -> jax.Array for all (unique) parameters."""
    out = {}
    for name, p in layer.named_parameters():
        out[name] = p._data
    if include_buffers:
        for name, b in layer.named_buffers():
            out[name] = b._data
    return out


@contextlib.contextmanager
def _bound(layer, arrays_by_name):
    handles = {}
    for name, p in list(layer.named_parameters()) + list(layer.named_buffers()):
        if name in arrays_by_name:
            handles[name] = (p, p._data)
            p._data = arrays_by_name[name]
    try:
        yield
    finally:
        for p, old in handles.values():
            p._data = old


def functional_call(layer, arrays_by_name, *args, trace=True, **kwargs):
    """Run ``layer(*args)`` with parameters temporarily bound to the given
    arrays. Tensor args may be raw jax arrays. Returns raw arrays (pytree)."""

    def to_tensor(a):
        if isinstance(a, Tensor):
            return a
        if isinstance(a, (jax.Array,)) or hasattr(a, "dtype"):
            return Tensor._wrap(a)
        return a

    args = [to_tensor(a) for a in args]
    kwargs = {k: to_tensor(v) for k, v in kwargs.items()}
    ctx = state.trace_guard() if trace else contextlib.nullcontext()
    with _bound(layer, arrays_by_name), ctx:
        out = layer(*args, **kwargs)
    return jax.tree.map(
        lambda o: o._data if isinstance(o, Tensor) else o, out,
        is_leaf=lambda o: isinstance(o, Tensor))
