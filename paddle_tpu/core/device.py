"""Device management.

TPU-native replacement for the reference's Place/DeviceContext machinery
(paddle/phi/common/place.h, paddle/phi/backends/device_manager.h:134). On JAX
there is no per-op stream plumbing: a "device" is a ``jax.Device`` and placement
is expressed via shardings; this module keeps the ``paddle.set_device``/
``get_device`` UX and resolves default placement for new tensors.
"""

from __future__ import annotations

import functools

import jax

_current_device_str: str | None = None


@functools.lru_cache(maxsize=None)
def _platform_devices(platform: str):
    try:
        return tuple(jax.devices(platform))
    except RuntimeError:
        return ()


def _default_platform() -> str:
    return jax.default_backend()


def set_device(device: str):
    """paddle.set_device analog. Accepts 'tpu', 'tpu:0', 'cpu', 'gpu:0'."""
    global _current_device_str
    name = device.lower()
    plat, _, idx = name.partition(":")
    if plat in ("tpu", "axon"):
        plat = jax.default_backend() if jax.default_backend() != "cpu" else "tpu"
    if plat == "gpu":
        plat = "cuda"
    devs = _platform_devices(plat)
    if not devs:
        # Accept the accelerator alias even when running on the CPU backend
        # (CI / virtual-device tests).
        devs = _platform_devices(_default_platform())
    if not devs:
        raise RuntimeError(f"no devices for '{device}'")
    i = int(idx) if idx else 0
    _current_device_str = name
    jax.config.update("jax_default_device", devs[min(i, len(devs) - 1)])
    return devs[min(i, len(devs) - 1)]


def get_device() -> str:
    if _current_device_str is not None:
        return _current_device_str
    backend = jax.default_backend()
    if backend == "cpu":
        return "cpu"
    return f"{backend}:0"


def get_default_device() -> jax.Device:
    d = jax.config.jax_default_device
    return d if d is not None else jax.devices()[0]


def device_count(platform: str | None = None) -> int:
    return len(jax.devices(platform)) if platform else len(jax.devices())


def is_compiled_with_cuda() -> bool:  # API parity; always False on TPU builds
    return False


def is_compiled_with_xpu() -> bool:
    return False


class _Place:
    """Reference Place classes (paddle/phi/common/place.h) kept as tags;
    under XLA, placement is a sharding/device attribute, not an allocator
    choice. Tensors constructed with any Place land on the default device;
    CPUPlace additionally pins host-side numpy semantics in io paths."""

    _kind = "undefined"

    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"Place({self._kind}:{self.device_id})" \
            if self._kind != "cpu" else "Place(cpu)"

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.device_id == getattr(other, "device_id", 0))

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))


class CPUPlace(_Place):
    _kind = "cpu"


class CUDAPlace(_Place):
    """Accepted for API parity; resolves to the accelerator (TPU) device."""

    _kind = "gpu"


class CUDAPinnedPlace(_Place):
    _kind = "gpu_pinned"


class TPUPlace(_Place):
    _kind = "tpu"
