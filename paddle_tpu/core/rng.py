"""Random number generation.

Replaces the reference's ``phi::Generator`` (paddle/phi/core/generator.h) and the
hybrid-parallel RNG state tracker
(fleet/meta_parallel/parallel_layers/random.py ``get_rng_state_tracker``).

Design: a ``Generator`` owns a JAX PRNG key plus a monotonically increasing
counter; ``next_key()`` returns ``fold_in(base, counter)`` so that

* eager mode draws a fresh concrete key per random op, and
* under ``to_static`` tracing the base key is lifted to a *traced* argument and
  the counter is folded in at trace time, so each compiled call site gets a
  distinct, reproducible stream without retracing (the caller advances the base
  key between steps).
"""

from __future__ import annotations

import contextlib

import jax
import numpy as np


class Generator:
    """Key creation is LAZY (first use, not __init__): building a PRNG key
    initializes the XLA backend, and the module-level DEFAULT_GENERATOR
    must not do that at import time — jax.distributed.initialize() has to
    run first in multi-process jobs (launch/bootstrap.py)."""

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._base_cache = None
        self._counter = 0
        # When tracing, a traced key injected by jit/to_static machinery.
        self._traced_base = None

    @property
    def _base(self):
        if self._base_cache is None:
            self._base_cache = jax.random.key(self._seed)
        return self._base_cache

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._base_cache = None
        self._counter = 0
        return self

    seed = manual_seed

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        base = self._traced_base if self._traced_base is not None else self._base
        self._counter += 1
        return jax.random.fold_in(base, self._counter)

    def get_state(self):
        return {"seed": self._seed, "counter": self._counter}

    def set_state(self, st):
        self._seed = int(st["seed"])
        self._base_cache = None
        self._counter = int(st["counter"])

    @contextlib.contextmanager
    def traced_base(self, key):
        prev = self._traced_base
        self._traced_base = key
        try:
            yield
        finally:
            self._traced_base = prev


DEFAULT_GENERATOR = Generator(0)


def seed(s: int):
    """paddle.seed analog (python/paddle/framework/random.py)."""
    DEFAULT_GENERATOR.manual_seed(s)
    np.random.seed(s % (2**32))
    return DEFAULT_GENERATOR


def default_generator() -> Generator:
    return DEFAULT_GENERATOR


def next_key():
    return DEFAULT_GENERATOR.next_key()


def get_rng_state():
    return DEFAULT_GENERATOR.get_state()


def set_rng_state(st):
    DEFAULT_GENERATOR.set_state(st)
