"""Tape/graph autograd engine.

TPU-native redesign of the reference's eager autograd engine
(paddle/fluid/eager/backward.cc:105 ``RunBackward``, grad_node_info.h:197
``GradNodeBase``). Differences by design:

* Grad nodes do not hold hand-written backward kernels. Each node remembers the
  op's pure-JAX forward function and its primal inputs; the backward executes a
  jit-cached ``jax.vjp`` of that function. XLA dead-code-eliminates whatever
  part of the recomputed forward the VJP doesn't need (for matmul-like ops the
  backward touches only the primals), so this costs ~nothing while keeping one
  source of truth per op.
* Topological order is by construction order: a node's inputs always have
  smaller ids, so processing reachable nodes by descending id is a valid
  reverse-topological walk (replaces getInDegreeMap, backward.cc:23).
"""

from __future__ import annotations

import itertools
import weakref

import jax
import jax.numpy as jnp
import numpy as np

_node_counter = itertools.count()

_FLOAT0 = jax.dtypes.float0


class Edge:
    """One incoming edge of a GradNode — aligned 1:1 with the op's tensor args."""

    __slots__ = ("node", "out_idx", "leaf_ref", "stop")

    def __init__(self, node=None, out_idx=0, leaf_ref=None, stop=False):
        self.node = node
        self.out_idx = out_idx
        self.leaf_ref = leaf_ref
        self.stop = stop

    @staticmethod
    def from_tensor(t):
        if t is None or t.stop_gradient and t._node is None:
            return Edge(stop=True)
        if t._node is not None:
            return Edge(node=t._node, out_idx=t._out_idx, stop=t.stop_gradient)
        return Edge(leaf_ref=weakref.ref(t))


class GradNode:
    __slots__ = (
        "id",
        "name",
        "bwd",
        "primals",
        "edges",
        "out_avals",
        "n_out",
        "out_is_tuple",
        "output_hooks",
        "__weakref__",
    )

    def __init__(self, name, bwd, primals, edges, out_avals, out_is_tuple):
        self.id = next(_node_counter)
        self.name = name
        self.bwd = bwd
        self.primals = primals
        self.edges = edges
        self.out_avals = out_avals  # list[(shape, dtype)]
        self.n_out = len(out_avals)
        self.out_is_tuple = out_is_tuple
        self.output_hooks = {}  # out_idx -> [fn]

    def __repr__(self):
        return f"<GradNode {self.name}#{self.id}>"


def _zeros(aval):
    shape, dtype = aval
    return jnp.zeros(shape, dtype)


def _is_float0(g):
    return g is None or getattr(g, "dtype", None) == _FLOAT0


def _accumulate(slot, g):
    return g if slot is None else slot + g


def run_backward(tensors, grad_tensors=None, retain_graph=False, capture=None,
                 accumulate_others=False):
    """Backward pass from ``tensors``.

    capture: optional dict mapping ``id(tensor)`` -> tensor for which the
    cotangent should be captured and returned (used by ``paddle.grad``).
    Leaf tensors with ``stop_gradient=False`` get ``.grad`` accumulated unless
    ``capture`` is given (grad API semantics: don't touch .grad);
    accumulate_others=True restores .grad accumulation for non-captured
    leaves (recompute's inner backward needs both).
    """
    from .tensor import Tensor

    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # ct accumulators
    node_cts: dict[int, list] = {}
    nodes: dict[int, GradNode] = {}
    captured: dict[int, object] = {}
    capture_nodes: dict[tuple[int, int], list[int]] = {}
    leaf_capture: dict[int, int] = {}

    if capture:
        for tid, t in capture.items():
            if t._node is not None:
                capture_nodes.setdefault((t._node.id, t._out_idx), []).append(tid)
            else:
                leaf_capture[id(t)] = tid

    def seed(t, g):
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}"
                )
            g = jnp.ones(t._data.shape, t._data.dtype)
        else:
            g = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        if t._node is None:
            # backward() on a leaf: its grad is just the seed
            if not t.stop_gradient:
                if capture is None:
                    t._accumulate_grad(g)
                elif id(t) in leaf_capture:
                    captured[leaf_capture[id(t)]] = g
            return
        node = t._node
        nodes[node.id] = node
        cts = node_cts.setdefault(node.id, [None] * node.n_out)
        cts[t._out_idx] = _accumulate(cts[t._out_idx], g)

    for t, g in zip(tensors, grad_tensors):
        seed(t, g)

    # collect reachable nodes
    stack = list(nodes.values())
    while stack:
        n = stack.pop()
        for e in n.edges:
            if e.node is not None and not e.stop and e.node.id not in nodes:
                nodes[e.node.id] = e.node
                stack.append(e.node)

    for nid in sorted(nodes.keys(), reverse=True):
        node = nodes[nid]
        cts = node_cts.get(nid)
        if cts is None:
            continue  # not actually on a path from the roots
        # apply output hooks (registered via Tensor.register_hook on non-leafs)
        for oi, fns in node.output_hooks.items():
            if cts[oi] is not None:
                for fn in fns:
                    res = fn(Tensor._wrap(cts[oi]))
                    if res is not None:
                        cts[oi] = res._data if isinstance(res, Tensor) else jnp.asarray(res)
        # captured non-leaf cotangents
        for oi in range(node.n_out):
            for tid in capture_nodes.get((nid, oi), ()):
                if cts[oi] is not None:
                    captured[tid] = cts[oi]
        if node.bwd is None:
            continue
        full_cts = [
            c if c is not None else _zeros(node.out_avals[i]) for i, c in enumerate(cts)
        ]
        cts_struct = tuple(full_cts) if node.out_is_tuple else full_cts[0]
        grads = node.bwd(node.primals, cts_struct)
        if not isinstance(grads, (list, tuple)):
            grads = (grads,)
        for e, g in zip(node.edges, grads):
            if e.stop or _is_float0(g):
                continue
            if e.node is not None:
                tgt = node_cts.setdefault(e.node.id, [None] * e.node.n_out)
                tgt[e.out_idx] = _accumulate(tgt[e.out_idx], g)
            elif e.leaf_ref is not None:
                t = e.leaf_ref()
                if t is None or t.stop_gradient:
                    continue
                for fn in t._hooks:
                    res = fn(Tensor._wrap(g))
                    if res is not None:
                        g = res._data if isinstance(res, Tensor) else jnp.asarray(res)
                if capture is None:
                    t._accumulate_grad(g)
                elif id(t) in leaf_capture:
                    captured[leaf_capture[id(t)]] = _accumulate(
                        captured.get(leaf_capture[id(t)]), g
                    )
                elif accumulate_others:
                    t._accumulate_grad(g)
        node_cts[nid] = None  # free cotangent memory as we go
        if not retain_graph:
            node.primals = None
            node.bwd = None

    return captured
