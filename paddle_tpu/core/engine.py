"""Tape/graph autograd engine.

TPU-native redesign of the reference's eager autograd engine
(paddle/fluid/eager/backward.cc:105 ``RunBackward``, grad_node_info.h:197
``GradNodeBase``). Differences by design:

* Grad nodes do not hold hand-written backward kernels. Each node remembers the
  op's pure-JAX forward function and its primal inputs; the backward executes a
  jit-cached ``jax.vjp`` of that function. XLA dead-code-eliminates whatever
  part of the recomputed forward the VJP doesn't need (for matmul-like ops the
  backward touches only the primals), so this costs ~nothing while keeping one
  source of truth per op.
* Topological order is by construction order: a node's inputs always have
  smaller ids, so processing reachable nodes by descending id is a valid
  reverse-topological walk (replaces getInDegreeMap, backward.cc:23).
"""

from __future__ import annotations

import itertools
import weakref

import jax
import jax.numpy as jnp
import numpy as np

_node_counter = itertools.count()

_FLOAT0 = jax.dtypes.float0


class Edge:
    """One incoming edge of a GradNode — aligned 1:1 with the op's tensor args."""

    __slots__ = ("node", "out_idx", "leaf_ref", "stop")

    def __init__(self, node=None, out_idx=0, leaf_ref=None, stop=False):
        self.node = node
        self.out_idx = out_idx
        self.leaf_ref = leaf_ref
        self.stop = stop

    @staticmethod
    def from_tensor(t):
        if t is None or t.stop_gradient and t._node is None:
            return Edge(stop=True)
        if t._node is not None:
            return Edge(node=t._node, out_idx=t._out_idx, stop=t.stop_gradient)
        return Edge(leaf_ref=weakref.ref(t))


class GradNode:
    __slots__ = (
        "id",
        "name",
        "bwd",
        "primals",
        "edges",
        "out_avals",
        "n_out",
        "out_is_tuple",
        "output_hooks",
        "op_kwargs",
        "__weakref__",
    )

    def __init__(self, name, bwd, primals, edges, out_avals, out_is_tuple,
                 op_kwargs=None):
        self.id = next(_node_counter)
        self.name = name
        self.bwd = bwd
        self.primals = primals
        self.edges = edges
        self.out_avals = out_avals  # list[(shape, dtype)]
        self.n_out = len(out_avals)
        self.out_is_tuple = out_is_tuple
        self.output_hooks = {}  # out_idx -> [fn]
        # static-kwargs key of the forward op (dispatch ops only) — lets the
        # engine replay this node's VJP through dispatch for create_graph=True
        self.op_kwargs = op_kwargs

    def __repr__(self):
        return f"<GradNode {self.name}#{self.id}>"


def _zeros(aval):
    shape, dtype = aval
    return jnp.zeros(shape, dtype)


def _is_float0(g):
    return g is None or getattr(g, "dtype", None) == _FLOAT0


def _accumulate(slot, g):
    return g if slot is None else slot + g


def _node_vjp_through_dispatch(node, cts):
    """Run a dispatch-op node's VJP as a dispatched op so the backward's own
    ops are recorded on the tape (create_graph=True). Rebuilds tensor views of
    the primals carrying their original graph links, so second-order paths
    through the primals (e.g. d²(x²)/dx² via the saved x) stay connected."""
    from . import dispatch
    from .tensor import Tensor

    prim_ts = []
    stand_in_fix = []  # (arg index, original leaf) for mutated leaves
    for i, (e, arr) in enumerate(zip(node.edges, node.primals)):
        if arr is None:
            prim_ts.append(None)
            continue
        if e.leaf_ref is not None:
            t = e.leaf_ref()
            if t is not None and t._data is arr:
                prim_ts.append(t)
                continue
            # leaf mutated in place since forward (fill_/optimizer step):
            # compute at the SAVED primal, then re-point the new node's edge
            # at the original leaf so second-order grads still reach it
            s = Tensor._wrap(arr)
            if t is not None:
                s.stop_gradient = t.stop_gradient
                stand_in_fix.append((i, t))
            prim_ts.append(s)
            continue
        t = Tensor._wrap(arr)
        if e.node is not None and not e.stop:
            t._node, t._out_idx = e.node, e.out_idx
            t.stop_gradient = False
        prim_ts.append(t)
    out = dispatch.call_op(
        "__op_vjp__", *prim_ts, *cts,
        op_name=node.name, n_primals=len(prim_ts),
        op_kwargs=node.op_kwargs, out_tuple=node.out_is_tuple)
    outs = out if isinstance(out, (list, tuple)) else (out,)
    if stand_in_fix:
        new_node = next((o._node for o in outs
                         if o is not None and o._node is not None), None)
        if new_node is not None:
            for i, t in stand_in_fix:
                new_node.edges[i] = Edge.from_tensor(t)
    return outs


def run_backward(tensors, grad_tensors=None, retain_graph=False, capture=None,
                 accumulate_others=False, create_graph=False):
    """Backward pass from ``tensors``.

    capture: optional dict mapping ``id(tensor)`` -> tensor for which the
    cotangent should be captured and returned (used by ``paddle.grad``).
    Leaf tensors with ``stop_gradient=False`` get ``.grad`` accumulated unless
    ``capture`` is given (grad API semantics: don't touch .grad);
    accumulate_others=True restores .grad accumulation for non-captured
    leaves (recompute's inner backward needs both).

    create_graph: cotangents are threaded as Tensors and each node's VJP runs
    through dispatch, so the returned/captured grads are themselves
    differentiable (reference: paddle/fluid/eager/general_grad.h double grad).
    Implies retain_graph.
    """
    from .tensor import Tensor

    if create_graph:
        retain_graph = True
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # ct accumulators
    node_cts: dict[int, list] = {}
    nodes: dict[int, GradNode] = {}
    captured: dict[int, object] = {}
    capture_nodes: dict[tuple[int, int], list[int]] = {}
    leaf_capture: dict[int, int] = {}

    if capture:
        for tid, t in capture.items():
            if t._node is not None:
                capture_nodes.setdefault((t._node.id, t._out_idx), []).append(tid)
            else:
                leaf_capture[id(t)] = tid

    def as_ct(g):
        """Normalize a cotangent to the walk's working form: Tensor when
        create_graph (so it stays differentiable), raw array otherwise."""
        if create_graph:
            return g if isinstance(g, Tensor) else Tensor._wrap(jnp.asarray(g))
        return g._data if isinstance(g, Tensor) else jnp.asarray(g)

    def raw(g):
        return g._data if isinstance(g, Tensor) else g

    def seed(t, g):
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}"
                )
            g = jnp.ones(t._data.shape, t._data.dtype)
        g = as_ct(g)
        if t._node is None:
            # backward() on a leaf: its grad is just the seed
            if not t.stop_gradient:
                if capture is None:
                    t._accumulate_grad(raw(g))
                elif id(t) in leaf_capture:
                    captured[leaf_capture[id(t)]] = g
            return
        node = t._node
        nodes[node.id] = node
        cts = node_cts.setdefault(node.id, [None] * node.n_out)
        cts[t._out_idx] = _accumulate(cts[t._out_idx], g)

    for t, g in zip(tensors, grad_tensors):
        seed(t, g)

    # collect reachable nodes
    stack = list(nodes.values())
    while stack:
        n = stack.pop()
        for e in n.edges:
            if e.node is not None and not e.stop and e.node.id not in nodes:
                nodes[e.node.id] = e.node
                stack.append(e.node)

    from . import state as _state
    from .dispatch import OPS as _OPS

    grad_guard = _state.enable_grad_guard() if create_graph else None
    if grad_guard is not None:
        grad_guard.__enter__()
    try:
        for nid in sorted(nodes.keys(), reverse=True):
            node = nodes[nid]
            cts = node_cts.get(nid)
            if cts is None:
                continue  # not actually on a path from the roots
            # apply output hooks (via Tensor.register_hook on non-leafs)
            for oi, fns in node.output_hooks.items():
                if cts[oi] is not None:
                    for fn in fns:
                        res = fn(cts[oi] if isinstance(cts[oi], Tensor)
                                 else Tensor._wrap(cts[oi]))
                        if res is not None:
                            cts[oi] = as_ct(res)
            # captured non-leaf cotangents
            for oi in range(node.n_out):
                for tid in capture_nodes.get((nid, oi), ()):
                    if cts[oi] is not None:
                        captured[tid] = cts[oi]
            if node.bwd is None:
                continue
            full_cts = [
                c if c is not None else as_ct(_zeros(node.out_avals[i]))
                for i, c in enumerate(cts)
            ]
            if (create_graph and node.op_kwargs is not None
                    and node.name in _OPS):
                grads = _node_vjp_through_dispatch(node, full_cts)
            else:
                raw_cts = [raw(c) for c in full_cts]
                cts_struct = (tuple(raw_cts) if node.out_is_tuple
                              else raw_cts[0])
                grads = node.bwd(node.primals, cts_struct)
                if not isinstance(grads, (list, tuple)):
                    grads = (grads,)
                if create_graph:
                    # not replayable through dispatch (PyLayer/program nodes):
                    # grads are correct but constant w.r.t. further diff
                    grads = tuple(None if g is None or _is_float0(g)
                                  else as_ct(g) for g in grads)
            for e, g in zip(node.edges, grads):
                if e.stop or _is_float0(g):
                    continue
                if e.node is not None:
                    tgt = node_cts.setdefault(e.node.id, [None] * e.node.n_out)
                    tgt[e.out_idx] = _accumulate(tgt[e.out_idx], g)
                elif e.leaf_ref is not None:
                    t = e.leaf_ref()
                    if t is None or t.stop_gradient:
                        continue
                    for fn in t._hooks:
                        res = fn(g if isinstance(g, Tensor)
                                 else Tensor._wrap(g))
                        if res is not None:
                            g = as_ct(res)
                    if capture is None:
                        t._accumulate_grad(raw(g))
                    elif id(t) in leaf_capture:
                        captured[leaf_capture[id(t)]] = _accumulate(
                            captured.get(leaf_capture[id(t)]), g
                        )
                    elif accumulate_others:
                        t._accumulate_grad(raw(g))
            node_cts[nid] = None  # free cotangent memory as we go
            if not retain_graph:
                node.primals = None
                node.bwd = None
    finally:
        if grad_guard is not None:
            grad_guard.__exit__(None, None, None)

    return captured
