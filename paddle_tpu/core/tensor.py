"""Eager Tensor.

TPU-native analog of the reference's eager Tensor
(paddle/fluid/pybind/eager.cc + paddle/phi/core/dense_tensor.h:37 +
paddle/fluid/eager/autograd_meta.h). A Tensor is a thin mutable handle over an
immutable ``jax.Array`` plus autograd metadata. Because jax arrays are
immutable, in-place ops (``add_`` …) rebind ``_data``; any GradNode holding the
old array stays valid — the reference needs TensorWrapper/version-counter
machinery (tensor_wrapper.h) for this, here it falls out of functional purity.

Op methods (``t.matmul``, ``t.sum`` …) are installed by the ops package at
import time (see ops/__init__.py), mirroring how the reference generates
``core.eager.ops`` methods from YAML.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtypes
from . import state
from .device import get_default_device


class Tensor:
    __slots__ = (
        "_data",
        "stop_gradient",
        "grad",
        "_node",
        "_out_idx",
        "name",
        "persistable",
        "_hooks",
        "_placement",  # optional distributed placement annotation
        "__weakref__",
        "__dict__",
    )

    _name_counter = 0

    def __init__(self, data=None, dtype=None, place=None, stop_gradient=True, name=None):
        if data is None:
            data = jnp.zeros((), dtypes.get_default_dtype())
        self._data = _to_jax(data, dtype)
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_idx = 0
        self.name = name or f"tensor_{Tensor._bump()}"
        self.persistable = False
        self._hooks = []
        self._placement = None

    @classmethod
    def _bump(cls):
        cls._name_counter += 1
        return cls._name_counter

    @staticmethod
    def _wrap(arr) -> "Tensor":
        t = Tensor.__new__(Tensor)
        t._data = arr
        t.stop_gradient = True
        t.grad = None
        t._node = None
        t._out_idx = 0
        t.name = f"tensor_{Tensor._bump()}"
        t.persistable = False
        t._hooks = []
        t._placement = None
        return t

    # ---- metadata ----
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def ndim(self):
        return self._data.ndim

    ndimension = ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    def numel(self):
        return self.size

    @property
    def place(self):
        try:
            devs = self._data.devices()
            return str(next(iter(devs)))
        except Exception:
            return str(get_default_device())

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def T(self):
        from .. import ops

        return ops.transpose(self, perm=list(range(self.ndim))[::-1])

    # ---- value access ----
    def numpy(self):
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def item(self, *idx):
        a = np.asarray(self._data)
        return a.item(*idx) if idx else a.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of a Tensor with more than one element is ambiguous"
            )
        return bool(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}{grad_info},\n"
            f"       {np.array2string(np.asarray(self._data), prefix='       ')})"
        )

    # ---- autograd ----
    def backward(self, grad_tensor=None, retain_graph=False):
        from .engine import run_backward

        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def register_hook(self, hook):
        if self._node is not None:
            self._node.output_hooks.setdefault(self._out_idx, []).append(hook)
        else:
            self._hooks.append(hook)

        class _Handle:
            def remove(_self):
                try:
                    if self._node is not None:
                        self._node.output_hooks[self._out_idx].remove(hook)
                    else:
                        self._hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    def _accumulate_grad(self, g):
        if self.grad is None:
            self.grad = Tensor._wrap(g)
        else:
            self.grad._data = self.grad._data + g

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor._wrap(self._data)
        t.stop_gradient = True
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self):
        from .. import ops

        return ops.assign(self)

    # ---- mutation (in-place rebind) ----
    def set_value(self, value):
        self._data = _to_jax(value, self.dtype)
        return self

    def copy_(self, other, blocking=True):
        data = other._data if isinstance(other, Tensor) else _to_jax(other, None)
        self._data = jnp.asarray(data, self.dtype)
        return self

    def _rebind(self, arr):
        self._data = arr
        return self

    # ---- conversion ----
    def astype(self, dtype):
        from .. import ops

        return ops.cast(self, dtype=dtypes.convert_dtype(dtype))

    cast = astype

    def to(self, *args, **kwargs):
        # paddle Tensor.to(device|dtype): device strings move data (cpu =
        # real host offload via device_put; gpu maps to the accelerator),
        # anything else is a dtype cast
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a.lower().split(":")[0] in (
                    "cpu", "tpu", "gpu", "axon", "cuda"):
                out = out._to_device(a.lower().split(":")[0])
                continue
            try:
                out = out.astype(dtypes.convert_dtype(a))
            except TypeError:
                continue
        return out

    def _copy_with_meta(self, arr):
        """Wrap a device-moved copy carrying this tensor's user-visible
        metadata: paddle preserves stop_gradient (a trainable tensor moved
        across devices must NOT come back silently detached), persistable
        and name across device copies."""
        t = Tensor._wrap(arr)
        t.stop_gradient = self.stop_gradient
        t.persistable = self.persistable
        t.name = self.name
        return t

    def _to_device(self, kind):
        import jax

        # a GSPMD-sharded array must NOT be collapsed onto one device (OOM
        # for large tables, sharding layout lost) — keep it where it is
        try:
            if len(self._data.sharding.device_set) > 1:
                import warnings

                warnings.warn(
                    f"Tensor.to({kind!r}) on a multi-device sharded array "
                    "is a no-op (moving it would gather onto one device); "
                    "use distributed.checkpoint for host snapshots",
                    stacklevel=4)
                return self
        except AttributeError:
            pass
        if kind == "cpu":
            return self._copy_with_meta(jax.device_put(
                self._data, jax.devices("cpu")[0]))
        # gpu/cuda naming maps onto the accelerator backend on this
        # framework (one XLA device namespace)
        try:
            dev = jax.devices()[0]
        except Exception:
            return self
        if dev.platform == "cpu" and kind in ("gpu", "cuda", "tpu"):
            import warnings

            warnings.warn(f"Tensor.to({kind!r}): no accelerator backend is "
                          "available; tensor stays on cpu", stacklevel=3)
            return self
        return self._copy_with_meta(jax.device_put(self._data, dev))

    def cpu(self):
        """Host offload: a copy of this tensor on the CPU device (paddle
        Tensor.cpu). Note the copy is committed to the host — move it back
        with ``.cuda()``/``.to('tpu')`` before mixing it into device
        compute."""
        return self._to_device("cpu")

    def cuda(self, *a, **k):
        return self._to_device("cuda")

    def pin_memory(self):
        return self

    # ---- python protocol / operators: installed by ops package ----
    def __getitem__(self, idx):
        from ..ops import indexing

        return indexing.getitem(self, idx)

    def __setitem__(self, idx, value):
        from ..ops import indexing

        indexing.setitem_(self, idx, value)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


def _to_jax(data, dtype):
    dtype = dtypes.convert_dtype(dtype)
    if isinstance(data, Tensor):
        arr = data._data
        return jnp.asarray(arr, dtype) if dtype is not None and np.dtype(arr.dtype) != dtype else arr
    if isinstance(data, jax.Array):
        return jnp.asarray(data, dtype) if dtype is not None else data
    if isinstance(data, np.ndarray):
        if dtype is None and data.dtype == np.float64:
            dtype = dtypes.get_default_dtype()
        return jnp.asarray(data, dtype)
    if isinstance(data, (bool, int, float, complex)) or np.isscalar(data):
        if dtype is None:
            if isinstance(data, bool):
                dtype = np.dtype("bool")
            elif isinstance(data, int):
                dtype = dtypes.int64 if abs(int(data)) > 2**31 - 1 else dtypes.int32
            elif isinstance(data, float):
                dtype = dtypes.get_default_dtype()
        return jnp.asarray(data, dtype)
    if isinstance(data, (list, tuple)):
        arr = np.asarray(data)
        if dtype is None and arr.dtype == np.float64:
            dtype = dtypes.get_default_dtype()
        return jnp.asarray(arr, dtype)
    raise TypeError(f"cannot convert {type(data)} to Tensor")


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor analog (python/paddle/tensor/creation.py)."""
    t = Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
    return t


class Parameter(Tensor):
    """Trainable tensor (python/paddle/base/framework.py Parameter)."""

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v
