"""Forward-compat shims for new-jax APIs on older jax runtimes.

The codebase is written against the current jax surface — ``jax.shard_map``
with ``axis_names``/``check_vma``, ``jax.sharding.AxisType``,
``jax.make_mesh(..., axis_types=)`` and ``jax.lax.axis_size`` — but some
images pin jax 0.4.x, where shard_map still lives in
``jax.experimental.shard_map`` with the ``check_rep``/``auto`` spelling and
the other names do not exist at all. :func:`install` bridges the gap by
installing the missing attributes AT IMPORT (``paddle_tpu/__init__``), only
when absent: on a current jax it is a no-op, so there is no behavior fork
on the supported path.

Semantics notes for the 0.4.x bridge:

- ``axis_names`` (partial-manual shard_map) maps to FULL-manual
  (``auto=frozenset()``), not to ``auto=<other axes>``: the 0.4.x SPMD
  partitioner hard-crashes (``IsManualSubgroup`` check) on partial-manual
  regions with NamedSharding-committed inputs on CPU. Full-manual is
  value-identical whenever ``in_specs`` fully describe the intended layout
  and the body only issues collectives over the named axes — true for
  every shard_map in this repo (attention collectives, pp stage scan, MoE
  dispatch). What degrades is only GSPMD auto-partitioning *inside* the
  body over the unnamed axes (e.g. tp within a pp stage): those dims
  compute replicated on 0.4.x. Documented perf cliff, not a correctness
  one.
- ``check_vma``/``check_rep`` map to ``check_rep=False``: with the
  partial→full manual conversion the replication claims in ``out_specs``
  are not what 0.4.x's checker would verify, and every call site in this
  repo opts out anyway.
- ``jax.lax.axis_size(name)`` maps to ``lax.psum(1, name)`` — a Python
  int 1 reduced over the axis is folded statically, so the result is a
  plain int usable for trip counts and permutation tables.
"""

from __future__ import annotations

__all__ = ["install"]


def _shim_shard_map(jax):
    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  axis_names=None, check_vma=None, check_rep=None,
                  auto=None):
        del axis_names, check_vma, check_rep, auto  # see module docstring
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=False)

    shard_map.__doc__ = ("paddle_tpu jax_compat bridge to "
                         "jax.experimental.shard_map (full-manual, "
                         "check_rep=False); see core/jax_compat.py")
    return shard_map


class _AxisType:
    """Stand-in for ``jax.sharding.AxisType`` (sharding-in-types axis
    kinds). Old jax has no Explicit mode — every mesh axis already behaves
    like ``Auto`` — so the members only need identity."""

    class _Member:
        def __init__(self, name):
            self._name = name

        def __repr__(self):
            return f"AxisType.{self._name}"

    Auto = _Member("Auto")
    Explicit = _Member("Explicit")
    Manual = _Member("Manual")


def install():
    """Install the missing attributes on ``jax``. Idempotent; no-op on a
    jax that already provides them."""
    import jax

    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shim_shard_map(jax)

    if not hasattr(jax.sharding, "AxisType"):
        jax.sharding.AxisType = _AxisType

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            return jax.lax.psum(1, axis_name)

        jax.lax.axis_size = axis_size

    if not hasattr(jax.lax, "pcast"):
        # VMA (varying-manual-axes) casts only exist alongside check_vma;
        # with the bridge's check_rep=False there is no varying-ness
        # tracking to satisfy — identity is the correct lowering
        def pcast(x, axis_names=None, *, to=None):
            del axis_names, to
            return x

        jax.lax.pcast = pcast

    import inspect

    try:
        accepts_axis_types = "axis_types" in inspect.signature(
            jax.make_mesh).parameters
    except (TypeError, ValueError):  # pragma: no cover - exotic builds
        accepts_axis_types = True
    if not accepts_axis_types:
        _orig_make_mesh = jax.make_mesh

        def make_mesh(axis_shapes, axis_names, *, devices=None,
                      axis_types=None):
            del axis_types  # pre-AxisType jax: every axis is Auto already
            return _orig_make_mesh(axis_shapes, axis_names, devices=devices)

        make_mesh.__wrapped__ = _orig_make_mesh
        jax.make_mesh = make_mesh

    # transitional 0.4.x Mesh takes axis_types as a {AxisTypes: names} dict
    # (or not at all); the codebase passes the current per-axis tuple form.
    # Normalize tuple/list axis_types away — on these versions None already
    # means classic auto/GSPMD for every axis, which is what AxisType.Auto
    # requests. Patched on the class so jax.sharding.Mesh and
    # jax._src.mesh.Mesh callers both see it.
    if not hasattr(jax.sharding, "_pt_axis_types_normalized"):
        mesh_cls = jax.sharding.Mesh
        try:
            new_params = inspect.signature(mesh_cls.__new__).parameters
        except (TypeError, ValueError):  # pragma: no cover
            new_params = {}
        needs_normalize = \
            isinstance(getattr(jax.sharding, "AxisType", None), type) and \
            jax.sharding.AxisType is _AxisType
        if needs_normalize and "axis_types" in new_params:
            _orig_new = mesh_cls.__new__

            def _mesh_new(cls, devices, axis_names=None, axis_types=None,
                          *args, **kwargs):
                if isinstance(axis_types, (tuple, list)):
                    axis_types = None
                return _orig_new(cls, devices, axis_names, axis_types,
                                 *args, **kwargs)

            mesh_cls.__new__ = _mesh_new
            jax.sharding._pt_axis_types_normalized = True
        elif needs_normalize:  # Mesh without axis_types support at all
            _orig_new2 = mesh_cls.__new__

            def _mesh_new2(cls, devices, axis_names=None, *args, **kwargs):
                kwargs.pop("axis_types", None)
                return _orig_new2(cls, devices, axis_names, *args, **kwargs)

            mesh_cls.__new__ = _mesh_new2
            jax.sharding._pt_axis_types_normalized = True
