"""Op registry + eager dispatch.

TPU-native replacement for the reference's per-op dispatch spine:
``KernelFactory::SelectKernelOrThrowError`` (paddle/phi/core/kernel_factory.h:324)
plus the generated ``*_ad_func`` eager functions
(paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:251). Here an
"op" is a pure JAX function; dispatch

1. unwraps Tensor args to jax.Arrays,
2. applies AMP auto-cast by op list (analog of eager_gen.py:515),
3. runs a jit-cached executable (the "kernel"), and
4. when grad is required, records a GradNode whose backward is a jit-cached
   ``jax.vjp`` of the same function (see engine.py).

Convention: **positional args are tensor-like, keyword args are static** python
values (hashed into the jit cache key). Inside a jax trace (to_static / pallas /
shard_map), dispatch degrades to a plain function call so the surrounding trace
captures the ops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import state
from .engine import Edge, GradNode

OPS: dict[str, "OpDef"] = {}

#: live op-stats sink (dict op_name -> [fp16, bf16, fp32, other] call
#: counts) while amp.debugging collection is enabled; None = off
OP_STATS: dict | None = None


class OpDef:
    __slots__ = ("name", "fn", "differentiable", "wrapper")

    def __init__(self, name, fn, differentiable=True):
        self.name = name
        self.fn = fn
        self.differentiable = differentiable

    def __repr__(self):
        return f"<OpDef {self.name}>"


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, np.dtype):
        # .name round-trips extended dtypes (bfloat16, float8_*) via ml_dtypes;
        # .str would degrade them to void ("|V2")
        return ("npdtype", v.name)
    if isinstance(v, np.ndarray):
        return ("nparr", v.tobytes(), v.dtype.name, v.shape)
    return v


def _unhash_dtype(v):
    from . import dtype as _dtypes

    if isinstance(v, tuple) and len(v) == 2 and v[0] == "npdtype":
        return _dtypes.convert_dtype(v[1])
    if isinstance(v, tuple) and len(v) == 4 and v[0] == "nparr":
        return np.frombuffer(v[1], dtype=_dtypes.convert_dtype(v[2])).reshape(v[3])
    return v


@functools.lru_cache(maxsize=None)
def _build_execs(name: str, kwargs_key: tuple):
    opdef = OPS[name]
    kwargs = {k: _unhash_dtype(v) for k, v in kwargs_key}

    def f(*arrs):
        return opdef.fn(*arrs, **kwargs)

    fwd = jax.jit(f)

    def bwd(primals, cts):
        _, vjp = jax.vjp(f, *primals)
        return vjp(cts)

    return fwd, jax.jit(bwd)


def call_op(name: str, *args, **kwargs):
    """Invoke a registered op on tensor-like positional args."""
    from .tensor import Tensor

    opdef = OPS[name]
    arrs = []
    tensor_args = []  # Tensor or None per positional arg
    any_tracer = state.in_trace()
    requires_grad = False
    for a in args:
        if isinstance(a, Tensor):
            tensor_args.append(a)
            arrs.append(a._data)
            if not a.stop_gradient:
                requires_grad = True
            if isinstance(a._data, jax.core.Tracer):
                any_tracer = True
        elif a is None:
            tensor_args.append(None)
            arrs.append(None)
        else:
            arr = a if isinstance(a, (jax.Array, np.ndarray)) else np.asarray(a)
            if isinstance(arr, jax.core.Tracer):
                any_tracer = True
            tensor_args.append(None)
            arrs.append(arr)

    # --- AMP auto-cast (analog of eager_gen.py:515) ---
    if state.STATE.amp_level in ("O1", "O2"):
        from ..amp import amp_lists

        arrs = amp_lists.maybe_cast(name, arrs)

    # --- amp.debugging operator-stats collection (reference
    # python/paddle/amp/debugging.py:459: per-op dtype call histogram) ---
    if OP_STATS is not None:
        dt = None
        for a in arrs:
            adt = getattr(a, "dtype", None)
            if adt is not None and jnp.issubdtype(adt, jnp.floating):
                dt = str(adt)
                break
        key = {"float16": 0, "bfloat16": 1, "float32": 2}.get(dt, 3)
        counts = OP_STATS.setdefault(name, [0, 0, 0, 0])
        counts[key] += 1

    if any_tracer:
        out = opdef.fn(*arrs, **kwargs)
        return _wrap_out(out, None, requires_grad and state.STATE.grad_enabled)

    kwargs_key = tuple(sorted((k, _hashable(v)) for k, v in kwargs.items()))
    fwd, bwd = _build_execs(name, kwargs_key)
    # FLAGS_check_nan_inf: post-op output scan (analog of
    # nan_inf_utils_detail.cc wired behind paddle/phi/core/flags.cc:74)
    from . import flags as _flags

    if _flags.flag_value("check_nan_inf"):
        try:
            out = fwd(*arrs)
        except FloatingPointError as e:  # jax_debug_nans tripped inside
            raise RuntimeError(
                f"op {name!r} produced NaN values "
                "(FLAGS_check_nan_inf)") from e
        _scan_nan_inf(name, out)
    else:
        out = fwd(*arrs)
        if _flags.flag_value("benchmark"):
            jax.block_until_ready(out)

    requires_grad = requires_grad and state.grad_enabled() and opdef.differentiable
    node = None
    if requires_grad:
        out_is_tuple = isinstance(out, (list, tuple))
        outs = tuple(out) if out_is_tuple else (out,)
        out_avals = [(o.shape, o.dtype) for o in outs]
        if not any(jnp.issubdtype(av[1], jnp.inexact) for av in out_avals):
            requires_grad = False
        else:
            edges = [Edge.from_tensor(t) if t is not None else Edge(stop=True)
                     for t in tensor_args]
            node = GradNode(name, bwd, tuple(arrs), edges, out_avals,
                            out_is_tuple, op_kwargs=kwargs_key)
    return _wrap_out(out, node, requires_grad)


def _scan_nan_inf(op_name, out):
    """Raise (level 0) or warn (level 1) when an op output holds NaN/Inf."""
    from . import flags as _flags

    outs = out if isinstance(out, (list, tuple)) else (out,)
    for i, o in enumerate(outs):
        if o is None or not jnp.issubdtype(o.dtype, jnp.inexact):
            continue
        if not bool(jnp.all(jnp.isfinite(o))):
            n_nan = int(jnp.sum(jnp.isnan(o)))
            n_inf = int(jnp.sum(jnp.isinf(o)))
            msg = (f"op {op_name!r} output {i} (shape {tuple(o.shape)}, "
                   f"dtype {o.dtype}) contains {n_nan} NaN / {n_inf} Inf "
                   "values (FLAGS_check_nan_inf)")
            if int(_flags.flag_value("check_nan_inf_level", 0)) >= 1:
                import warnings

                warnings.warn(msg)
            else:
                raise RuntimeError(msg)


def _wrap_out(out, node, requires_grad):
    from .tensor import Tensor

    def wrap(o, idx):
        if o is None:
            return None
        t = Tensor._wrap(o)
        t.stop_gradient = not requires_grad
        if node is not None:
            t._node = node
            t._out_idx = idx
        return t

    if isinstance(out, (list, tuple)):
        return type(out)(wrap(o, i) for i, o in enumerate(out))
    return wrap(out, 0)


def _op_vjp_fn(*arrs, op_name="", n_primals=0, op_kwargs=(), out_tuple=False):
    """Generic VJP-as-an-op: running an op's backward THROUGH dispatch makes
    the backward's ops land on the tape, which is what ``create_graph=True``
    (double grad) needs. Analog of the reference's higher-order grad nodes
    (paddle/fluid/eager/general_grad.h:1 + double-grad ops in backward.yaml).

    Positional args: the node's primal inputs followed by the output
    cotangents; statics identify the forward op. Returns one grad per primal;
    where jax reports float0 / None (typically stop edges) the slot carries
    primal-shaped zeros so it still composes if consumed downstream.
    """
    opdef = OPS[op_name]
    kw = {k: _unhash_dtype(v) for k, v in op_kwargs}
    primals = arrs[:n_primals]
    cts = arrs[n_primals:]

    def f(*ps):
        return opdef.fn(*ps, **kw)

    _, vjp = jax.vjp(f, *primals)
    grads = vjp(tuple(cts) if out_tuple else cts[0])
    out = []
    for g, p in zip(grads, primals):
        if g is None or getattr(g, "dtype", None) == jax.dtypes.float0:
            # match the primal's shape/dtype so that if this slot is ever a
            # real (non-stop) edge the cotangent still composes downstream
            dt = getattr(p, "dtype", jnp.float32)
            if not jnp.issubdtype(dt, jnp.floating):
                dt = jnp.float32
            out.append(jnp.zeros(jnp.shape(p), dt))
        else:
            out.append(g)
    return tuple(out)


def op(name=None, differentiable=True):
    """Register a pure-JAX function as a framework op.

    The decorated function remains directly callable with jax arrays; calling it
    with Tensor args routes through eager dispatch.
    """

    def deco(fn):
        opname = name or fn.__name__
        opdef = OpDef(opname, fn, differentiable)
        OPS[opname] = opdef

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return call_op(opname, *args, **kwargs)

        wrapper.op_name = opname
        wrapper.raw_fn = fn
        opdef.wrapper = wrapper
        return wrapper

    return deco


# generic VJP op used by the engine for create_graph=True backward
OPS["__op_vjp__"] = OpDef("__op_vjp__", _op_vjp_fn, differentiable=True)
