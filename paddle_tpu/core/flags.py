"""Runtime flag registry — ``paddle.set_flags`` / ``paddle.get_flags``.

Reference: the self-hosted flag registry ``paddle/utils/flags_native.h:112``
(``PD_DEFINE_VARIABLE``) with ~120 exported flags in
``paddle/phi/core/flags.cc``, env-overridable as ``FLAGS_*`` and settable via
``paddle.set_flags``.

Here flags are plain Python state consulted by the dispatch layer and
subsystems. Registered flags are the ones with real effect in this framework;
reference flags that govern machinery XLA owns (allocator strategy, cudnn
knobs, executor toggles) are registered as accepted-but-inert so reference
scripts keep running, and marked ``inert=True`` for honesty.
"""

from __future__ import annotations

import os

__all__ = ["set_flags", "get_flags", "register_flag", "flag_value"]


class _Flag:
    __slots__ = ("name", "default", "type", "help", "inert", "on_change",
                 "value")

    def __init__(self, name, default, help="", inert=False, on_change=None):
        self.name = name
        self.default = default
        self.type = type(default)
        self.help = help
        self.inert = inert
        self.on_change = on_change
        self.value = self._from_env()

    def _from_env(self):
        env = os.environ.get(f"FLAGS_{self.name}")
        if env is None:
            return self.default
        return self._coerce(env)

    def _coerce(self, v):
        if self.type is bool:
            if isinstance(v, str):
                return v.lower() in ("1", "true", "yes", "on")
            return bool(v)
        return self.type(v)

    def set(self, v):
        old = self.value
        self.value = self._coerce(v)
        if self.on_change is not None:
            try:
                self.on_change(self.value)
            except BaseException:
                self.value = old  # a rejecting validator must not leave
                raise             # the invalid value installed


_REGISTRY: dict[str, _Flag] = {}


def register_flag(name, default, help="", inert=False, on_change=None):
    """Register a flag (PD_DEFINE_VARIABLE analog). Env FLAGS_<name>
    overrides the default at registration time (and fires on_change, so
    env-set flags get the same side effects as paddle.set_flags)."""
    name = name.removeprefix("FLAGS_")
    f = _Flag(name, default, help, inert, on_change)
    _REGISTRY[name] = f
    if on_change is not None and os.environ.get(f"FLAGS_{name}") is not None:
        on_change(f.value)
    return f


def _lookup(name):
    key = name.removeprefix("FLAGS_")
    if key not in _REGISTRY:
        raise ValueError(
            f"unknown flag {name!r}; registered flags: "
            f"{sorted(_REGISTRY)}")
    return _REGISTRY[key]


def set_flags(flags):
    """paddle.set_flags({'FLAGS_check_nan_inf': 1})."""
    if not isinstance(flags, dict):
        raise TypeError("set_flags takes a dict of {flag_name: value}")
    for k, v in flags.items():
        _lookup(k).set(v)


def get_flags(flags):
    """paddle.get_flags('FLAGS_x') or (['FLAGS_x', ...]) -> dict."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        f = _lookup(k)
        key = k if k.startswith("FLAGS_") else f"FLAGS_{f.name}"
        out[key] = f.value
    return out


def flag_value(name, default=None):
    """Internal fast read used by dispatch/subsystems."""
    f = _REGISTRY.get(name.removeprefix("FLAGS_"))
    return f.value if f is not None else default


# ---- flags with real effect ------------------------------------------------

def _sync_debug_nans(_):
    # bridge into jax for traced/jit code (covers to_static + fused steps);
    # only in raise mode (level 0) — debug_nans cannot warn-and-continue
    import jax

    enabled = bool(flag_value("check_nan_inf", False)) and \
        int(flag_value("check_nan_inf_level", 0)) == 0
    try:
        jax.config.update("jax_debug_nans", enabled)
    except Exception:
        pass


register_flag(
    "check_nan_inf", False,
    help="scan every eager op's outputs for NaN/Inf and raise with the op "
         "name (ref paddle/phi/core/flags.cc:74); also enables "
         "jax_debug_nans for compiled code",
    on_change=_sync_debug_nans)
register_flag(
    "check_nan_inf_level", 0,
    help="0: raise on NaN/Inf; 1: warn only (ref flags.cc:88 levels)",
    on_change=_sync_debug_nans)
register_flag(
    "benchmark", False,
    help="block on every eager op (device sync) for accurate per-op timing")

register_flag(
    "ckpt_save_retries", 3,
    help="retries for transient OSErrors on checkpoint writes (paddle.save, "
         "distributed shard writes, LocalFS renames) with exponential "
         "backoff + jitter; 0 disables retrying")
register_flag(
    "ckpt_quarantine_keep", -1,
    help="CheckpointManager retention bound on *.replaced.* quarantine "
         "dirs that still hold the only committed copy of their step "
         "(redundant quarantines are always swept): -1 (default) keeps "
         "all — the PR-2 never-delete behavior — while N >= 0 keeps only "
         "the N newest such quarantines. N >= 1 is recommended when "
         "bounding: 0 sweeps even the newest, which can discard the only "
         "committed copy of a step whose re-save keeps getting torn")


def _validate_nan_action(v):
    if v not in ("none", "warn", "skip", "raise"):
        raise ValueError(
            f"FLAGS_check_nan_inf_action must be one of "
            f"none/warn/skip/raise, got {v!r}")


def _validate_positive_int(name):
    def check(v):
        if int(v) < 1:
            raise ValueError(f"FLAGS_{name} must be >= 1, got {v!r}")
    return check


register_flag(
    "prefetch_depth", 2,
    help="DevicePrefetcher double-buffer depth: how many batches the "
         "transfer thread stages ahead (host bucket-pad + device_put) "
         "while the device computes the current one; >= 1. Depth 2 is the "
         "classic double buffer — batch N+1 transfers during batch N's "
         "compute",
    on_change=_validate_positive_int("prefetch_depth"))
register_flag(
    "metric_fetch_interval", 10,
    help="default log_every for FusedTrainStep.drive: loss/guard metrics "
         "accumulate on device and are fetched every N steps (each fetch "
         "is an ~8-15 ms host round-trip over the axon tunnel; N=1 "
         "restores per-step fetch)",
    on_change=_validate_positive_int("metric_fetch_interval"))

def _validate_non_negative(name):
    def check(v):
        if float(v) < 0:
            raise ValueError(f"FLAGS_{name} must be >= 0, got {v!r}")
    return check


# ---- supervision / elastic restart flags -----------------------------------
# The launcher-side flags are read by the supervisor PROCESS (the
# `python -m paddle_tpu.distributed.launch` parent), so set them via the
# FLAGS_* environment variable of the launch command — paddle.set_flags in
# the training script runs in a different process and cannot reach them.

register_flag(
    "worker_hang_timeout_s", 0.0,
    help="launcher watchdog: kill + restart the local worker group when the "
         "stalest worker heartbeat (written by FusedTrainStep.drive at every "
         "metric-fetch window boundary) is older than this many seconds; "
         "0 disables hang detection. Launcher-side: set via env on the "
         "launch command",
    on_change=_validate_non_negative("worker_hang_timeout_s"))
register_flag(
    "step_timeout_s", 0.0,
    help="in-process stall watchdog: FusedTrainStep.drive arms a wall-clock "
         "timer around its fetch points and raises TrainStallError when a "
         "step makes no progress for this many seconds (a wedged collective "
         "surfaces as a crash the supervisor can restart); 0 disables",
    on_change=_validate_non_negative("step_timeout_s"))
register_flag(
    "restart_window_s", 3600.0,
    help="rolling window of the launcher's leaky-bucket restart budget: "
         "--max_restart crash restarts are allowed per this many seconds "
         "(old crashes age out instead of consuming budget forever); "
         "0 makes the budget lifetime-scoped. Launcher-side env flag",
    on_change=_validate_non_negative("restart_window_s"))
register_flag(
    "restart_backoff_s", 1.0,
    help="base delay of the launcher's exponential restart backoff "
         "(doubled per crash currently in the budget window, capped at "
         "30s); clean preemptions relaunch immediately. Launcher-side "
         "env flag",
    on_change=_validate_non_negative("restart_backoff_s"))
register_flag(
    "worker_term_grace_s", 10.0,
    help="grace period between the launcher's SIGTERM and SIGKILL when "
         "killing a worker group, and the wait for remaining workers to "
         "finish their preemption checkpoint after one exits preempted. "
         "Launcher-side env flag",
    on_change=_validate_non_negative("worker_term_grace_s"))

# ---- divergence sentinel flags ---------------------------------------------
# Configure the TrainingSentinel layer (paddle.incubate.TrainingSentinel):
# loss-spike / grad-explosion detection at metric-fetch window boundaries in
# FusedTrainStep.drive (zero added per-step host syncs — detection rides the
# deferred-window fetch) and its graceful-degradation response ladder.

def _validate_sentinel_action(v):
    if v not in ("none", "warn", "skip", "rollback", "raise"):
        raise ValueError(
            f"FLAGS_sentinel_action must be one of "
            f"none/warn/skip/rollback/raise, got {v!r}")


def _validate_unit_interval(name):
    def check(v):
        if not (0.0 < float(v) < 1.0):
            raise ValueError(f"FLAGS_{name} must be in (0, 1), got {v!r}")
    return check


def _validate_unit_interval_inclusive_one(v):
    if not (0.0 < float(v) <= 1.0):
        raise ValueError(
            f"FLAGS_sentinel_lr_cooldown must be in (0, 1], got {v!r}")


register_flag(
    "sentinel_action", "none",
    help="divergence-sentinel response when a training window is judged a "
         "spike: 'none' (sentinel off), 'warn' (RuntimeWarning, continue), "
         "'skip' (warn + drop the next window of batches — assumes a "
         "contiguous poisoned input region; the bad window's updates stay "
         "applied), 'rollback' (restore model+optimizer+sampler from the "
         "last HEALTHY checkpoint, skip the offending batches, optional LR "
         "cooldown, budgeted), 'raise' (typed TrainDivergenceError at the "
         "first verdict)",
    on_change=_validate_sentinel_action)
register_flag(
    "sentinel_zscore", 6.0,
    help="spike threshold: a window whose mean loss sits more than this "
         "many EMA standard deviations ABOVE the running EMA mean is a "
         "spike (one-sided; armed after FLAGS_sentinel_warmup_windows "
         "clean windows); <= 0 disables the z-score detector",
)
register_flag(
    "sentinel_ema_beta", 0.9,
    help="EMA decay for the sentinel's running mean/variance of window "
         "mean losses (higher = longer memory, slower to absorb genuine "
         "regime changes); spike windows never update the EMA, so one "
         "spike cannot normalize the next",
    on_change=_validate_unit_interval("sentinel_ema_beta"))
register_flag(
    "sentinel_warmup_windows", 3,
    help="clean windows the sentinel observes before the z-score detector "
         "arms (the EMA baseline must exist before deviations from it mean "
         "anything); the grad-norm ceiling and patience detectors are "
         "active from the first window",
    on_change=_validate_positive_int("sentinel_warmup_windows"))
register_flag(
    "sentinel_grad_norm_ceiling", 0.0,
    help="absolute ceiling on the window's peak global grad norm (tracked "
         "device-side in the fused step's donated accumulator — no extra "
         "per-step host sync): any window whose peak exceeds it is a "
         "spike; 0 disables and skips the in-graph norm reduction when "
         "grad clipping is not already computing it",
    on_change=_validate_non_negative("sentinel_grad_norm_ceiling"))
register_flag(
    "sentinel_patience", 0,
    help="divergence-trend detector: this many CONSECUTIVE windows of "
         "strictly rising mean loss is a spike verdict even when no "
         "single window clears the z-score bar (slow divergence); 0 "
         "disables",
    on_change=_validate_non_negative("sentinel_patience"))
register_flag(
    "sentinel_rollback_budget", 3,
    help="leaky-bucket cap on sentinel rollbacks: at most this many "
         "rollbacks per rolling FLAGS_sentinel_budget_window_s window "
         "(mirroring the launcher's RestartBudget); exhaustion raises "
         "TrainDivergenceError carrying the spike history",
    on_change=_validate_positive_int("sentinel_rollback_budget"))
register_flag(
    "sentinel_budget_window_s", 3600.0,
    help="rolling window of the sentinel's rollback budget (old rollbacks "
         "age out instead of consuming budget forever); 0 makes the "
         "budget lifetime-scoped",
    on_change=_validate_non_negative("sentinel_budget_window_s"))
register_flag(
    "sentinel_lr_cooldown", 1.0,
    help="learning-rate multiplier applied after each sentinel rollback "
         "(the restored step's LR scale times this; e.g. 0.5 halves the "
         "LR past the spike region); 1.0 disables. Applied as a scale on "
         "top of the optimizer's own schedule, persisted in the fused "
         "step's state dict",
    on_change=_validate_unit_interval_inclusive_one)
register_flag(
    "sentinel_healthy_windows", 2,
    help="clean windows that must pass beyond a committed checkpoint step "
         "before CheckpointManager tags it HEALTHY (rollback only ever "
         "targets healthy steps, so a checkpoint written during an "
         "undetected spike cannot become a rollback target); a bad window "
         "resets every pending count",
    on_change=_validate_positive_int("sentinel_healthy_windows"))

register_flag(
    "check_nan_inf_action", "none",
    help="FusedTrainStep step-guard action when loss/grads go non-finite: "
         "'none' (guard off, no per-step host sync), 'warn' (warn and apply "
         "the update), 'skip' (discard the update, keep params/moments, "
         "back off an attached GradScaler), 'raise' (discard the update and "
         "raise FloatingPointError)",
    on_change=_validate_nan_action)

# ---- accepted-but-inert reference flags (XLA owns this machinery) ----------

for _name, _default in [
    ("allocator_strategy", "auto_growth"),
    ("fraction_of_gpu_memory_to_use", 0.92),
    ("cudnn_deterministic", False),
    ("embedding_deterministic", 0),
    ("conv_workspace_size_limit", 512),
    ("cudnn_exhaustive_search", False),
    ("use_pinned_memory", True),
    ("init_allocated_mem", False),
    ("eager_delete_tensor_gb", 0.0),
]:
    register_flag(_name, _default, inert=True,
                  help="accepted for reference-script compatibility; the "
                       "equivalent machinery is owned by XLA on TPU")
