"""Typed supervision exceptions + the in-process stall guard.

A wedged collective (one peer dead, the rest parked in an allgather) or a
hung input pipeline blocks the training loop forever without raising — the
process looks alive to everything except its own wall clock. The reference
framework's elastic stack surfaces this at two levels: in-process (trainer
watchdog timers) and out-of-process (the elastic controller's heartbeat
scanner). This module is the in-process half: :func:`stall_guard` arms a
wall-clock timer around a blocking region and turns "no progress within
``FLAGS_step_timeout_s``" into a typed :class:`TrainStallError` the caller
— and the supervising launcher, via the nonzero exit it causes — can treat
exactly like a crash. The out-of-process half is the heartbeat watchdog in
``paddle_tpu.distributed.launch`` (a stall the guard cannot interrupt, e.g.
code blocked in C holding the GIL, is caught there instead).
"""

from __future__ import annotations

import contextlib
import signal
import threading

__all__ = ["TrainDivergenceError", "TrainStallError", "stall_guard"]


class TrainDivergenceError(RuntimeError):
    """Training is finite-but-wrong and the divergence sentinel ran out of
    graceful responses: the loss-spike / grad-explosion detector
    (``FLAGS_sentinel_action``) either exhausted its rollback budget
    (``FLAGS_sentinel_rollback_budget`` rollbacks per rolling
    ``FLAGS_sentinel_budget_window_s`` window), was configured to raise on
    the first verdict, or had no healthy checkpoint to roll back to.

    ``history`` carries the sentinel's spike records (one dict per spike
    verdict: step, window mean loss, z-score, grad-norm peak, reasons) and
    ``rollbacks`` the number of rollbacks already performed — enough for a
    supervisor or a human to reconstruct the divergence post-mortem without
    the (possibly dead) process's logs."""

    def __init__(self, msg, history=None, rollbacks=0):
        super().__init__(msg)
        self.history = list(history or [])
        self.rollbacks = int(rollbacks)


class TrainStallError(RuntimeError):
    """A training step made no progress within the armed timeout
    (``FLAGS_step_timeout_s``): the fetch/dispatch the guard wrapped is
    wedged — typically a collective waiting on a dead peer or a stuck
    input pipeline. Semantically a crash: checkpoint state on disk is
    intact, so the supervisor's restart + ``auto_resume`` is the fix."""


@contextlib.contextmanager
def stall_guard(timeout_s, what="training step"):
    """Arm a wall-clock watchdog over the enclosed block: if it does not
    finish within ``timeout_s`` seconds, raise :class:`TrainStallError`
    *inside* the block (SIGALRM-based, so a Python-level block — e.g. a
    queue wait or ``time.sleep`` — is interrupted).

    No-op when ``timeout_s`` is falsy/<= 0, off the main thread, or on
    platforms without ``SIGALRM`` — the guard degrades to unsupervised
    rather than refusing to run. Best-effort by design: code blocked in C
    without releasing the GIL only unblocks at the next bytecode boundary;
    the launcher's heartbeat watchdog is the backstop for those."""
    if (not timeout_s or float(timeout_s) <= 0
            or not hasattr(signal, "SIGALRM")
            or threading.current_thread() is not threading.main_thread()):
        yield
        return

    def _on_alarm(signum, frame):
        raise TrainStallError(
            f"no progress within {float(timeout_s):g}s at {what} "
            "(FLAGS_step_timeout_s) — surfacing the wedged step as a crash")

    prev_handler = signal.signal(signal.SIGALRM, _on_alarm)
    signal.setitimer(signal.ITIMER_REAL, float(timeout_s))
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, prev_handler)
