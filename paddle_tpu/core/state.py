"""Global interpreter state.

Replaces the reference's thread-local tracer/controller state
(paddle/fluid/eager/api/utils/global_utils.h ``egr::Controller``,
paddle/fluid/imperative/tracer.h:60): grad mode, AMP mode, default dtype,
and the eager/trace mode switch.
"""

from __future__ import annotations

import contextlib
import threading

import numpy as np

DEFAULT_DTYPE = np.dtype("float32")


class _ThreadLocalState(threading.local):
    def __init__(self):
        self.grad_enabled = True
        # AMP: None | "O1" | "O2"  (amp/auto_cast.py drives these)
        self.amp_level = "O0"
        self.amp_dtype = None  # np.dtype when amp active
        self.amp_custom_white = frozenset()
        self.amp_custom_black = frozenset()
        # When inside a jax trace (to_static / grad tracing), per-op jit and
        # autograd taping are disabled; ops run as plain traceable jax calls.
        self.trace_depth = 0


STATE = _ThreadLocalState()


def grad_enabled() -> bool:
    return STATE.grad_enabled and STATE.trace_depth == 0


@contextlib.contextmanager
def no_grad_guard():
    prev = STATE.grad_enabled
    STATE.grad_enabled = False
    try:
        yield
    finally:
        STATE.grad_enabled = prev


@contextlib.contextmanager
def enable_grad_guard():
    prev = STATE.grad_enabled
    STATE.grad_enabled = True
    try:
        yield
    finally:
        STATE.grad_enabled = prev


@contextlib.contextmanager
def trace_guard():
    """Mark that we're inside a jax trace: disable per-op jit + taping."""
    STATE.trace_depth += 1
    try:
        yield
    finally:
        STATE.trace_depth -= 1


def in_trace() -> bool:
    return STATE.trace_depth > 0
