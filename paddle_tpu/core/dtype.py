"""Dtype system.

TPU-native analog of the reference's dtype plumbing (paddle/phi/common/data_type.h,
python/paddle/framework/dtype.py). Dtypes are thin aliases over numpy/jax dtypes; the
canonical in-framework representation is a ``jnp.dtype``.

Divergence from the reference: default integer dtype is int32 (TPU-friendly, matches
JAX x32 mode) where paddle defaults to int64. float64 is supported but discouraged on
TPU (XLA emulates it slowly).
"""

from __future__ import annotations

import builtins

import numpy as np
import jax.numpy as jnp
import ml_dtypes

# Canonical dtype objects (numpy dtype instances, usable anywhere jax accepts a dtype)
bool = np.dtype("bool")  # noqa: A001
uint8 = np.dtype("uint8")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
float16 = np.dtype("float16")
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")
float8_e4m3fn = np.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)

_STR_ALIASES = {
    "bool": bool,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "half": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "float": float32,
    "float64": float64,
    "double": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
}

_FLOATS = {float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2}
_INTS = {uint8, int8, int16, int32, int64}
_COMPLEX = {complex64, complex128}


def convert_dtype(dtype):
    """Normalize any user-supplied dtype spec to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower()
        if key in _STR_ALIASES:
            return _STR_ALIASES[key]
        return np.dtype(dtype)
    if dtype is builtins.float:
        return float32
    if dtype is builtins.int:
        return int32
    if dtype is builtins.bool:
        return np.dtype("bool")
    return np.dtype(dtype)


def is_floating_point(dtype) -> builtins.bool:
    return np.dtype(dtype) in _FLOATS


def is_integer(dtype) -> builtins.bool:
    return np.dtype(dtype) in _INTS or np.dtype(dtype) == np.dtype("bool")


def is_complex(dtype) -> builtins.bool:
    return np.dtype(dtype) in _COMPLEX


def get_default_dtype():
    from . import state

    return state.DEFAULT_DTYPE


def set_default_dtype(dtype):
    from . import state

    d = convert_dtype(dtype)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(f"set_default_dtype only supports float dtypes, got {dtype}")
    state.DEFAULT_DTYPE = d


def promote_types(a, b):
    return jnp.promote_types(a, b)
