"""User-facing autograd API.

Reference: python/paddle/autograd (PyLayer at autograd/py_layer.py,
paddle.grad in base/dygraph/base.py, no_grad).
"""

from __future__ import annotations

import contextlib

from ..core import state
from ..core.engine import run_backward
from ..core.tensor import Tensor

__all__ = ["backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
           "is_grad_enabled", "PyLayer", "PyLayerContext", "saved_tensors_hooks"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None, name=None):
    """paddle.grad analog (imperative partial-grad GeneralGrad,
    paddle/fluid/eager/general_grad.h). ``create_graph=True`` threads the
    backward through dispatch so the returned grads are differentiable
    (double grad); retain_graph then defaults to True like the reference."""
    if create_graph and retain_graph is False:
        # the second-order graph's edges point INTO the first-order graph;
        # freeing it would silently zero later derivatives — refuse loudly
        raise ValueError(
            "create_graph=True requires the graph to be retained; do not "
            "pass retain_graph=False")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    capture = {id(t): t for t in inputs}
    retain = (bool(retain_graph) if retain_graph is not None
              else bool(create_graph))
    captured = run_backward(list(outputs), grad_outputs, retain_graph=retain,
                            capture=capture, create_graph=create_graph)
    results = []
    for t in inputs:
        g = captured.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    f"input tensor {t.name} is unused in the graph; pass "
                    "allow_unused=True to return None for it"
                )
            results.append(None)
        else:
            results.append(g if isinstance(g, Tensor) else Tensor._wrap(g))
    return results


class no_grad:
    """Usable as decorator or context manager (paddle.no_grad)."""

    def __init__(self, func=None):
        self._func = func

    def __call__(self, *args, **kwargs):
        if self._func is not None:
            with state.no_grad_guard():
                return self._func(*args, **kwargs)
        return self

    def __enter__(self):
        self._cm = state.no_grad_guard()
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)


class enable_grad:
    def __enter__(self):
        self._cm = state.enable_grad_guard()
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)


@contextlib.contextmanager
def set_grad_enabled(mode: bool):
    prev = state.STATE.grad_enabled
    state.STATE.grad_enabled = bool(mode)
    try:
        yield
    finally:
        state.STATE.grad_enabled = prev


def is_grad_enabled() -> bool:
    return state.STATE.grad_enabled


class PyLayerContext:
    """Reference: python/paddle/autograd/py_layer.py PyLayerContext."""

    def __init__(self):
        self._saved = ()
        self.materialize_grads = True
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_not_inplace(self, *args):
        self.not_inplace_tensors = args

    def set_materialize_grads(self, value):
        self.materialize_grads = bool(value)


class _PyLayerNodeBuilder:
    """Bridges a user PyLayer.backward into the engine's GradNode protocol."""

    def __init__(self, layer_cls, ctx, n_inputs):
        self.layer_cls = layer_cls
        self.ctx = ctx
        self.n_inputs = n_inputs

    def __call__(self, primals, cts):
        import jax.numpy as jnp

        cts_t = (
            tuple(Tensor._wrap(c) for c in cts)
            if isinstance(cts, tuple)
            else (Tensor._wrap(cts),)
        )
        with state.no_grad_guard():
            grads = self.layer_cls.backward(self.ctx, *cts_t)
        if not isinstance(grads, (list, tuple)):
            grads = (grads,)
        out = []
        for g in grads:
            if g is None:
                out.append(None)
            elif isinstance(g, Tensor):
                out.append(g._data)
            else:
                out.append(jnp.asarray(g))
        return tuple(out)


class PyLayer:
    """Custom autograd op via subclassing (paddle.autograd.PyLayer).

    class Tanh(PyLayer):
        @staticmethod
        def forward(ctx, x): ...
        @staticmethod
        def backward(ctx, dy): ...
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core.engine import Edge, GradNode

        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        requires_grad = state.grad_enabled() and any(
            not t.stop_gradient for t in tensor_args
        )
        with state.no_grad_guard():
            out = cls.forward(ctx, *args, **kwargs)
        out_is_tuple = isinstance(out, (list, tuple))
        outs = tuple(out) if out_is_tuple else (out,)
        if requires_grad:
            edges = [Edge.from_tensor(a) if isinstance(a, Tensor) else Edge(stop=True)
                     for a in args]
            out_avals = [(tuple(o._data.shape), o._data.dtype) for o in outs]
            node = GradNode(
                f"pylayer_{cls.__name__}",
                _PyLayerNodeBuilder(cls, ctx, len(args)),
                (),
                edges,
                out_avals,
                out_is_tuple,
            )
            new_outs = []
            for i, o in enumerate(outs):
                t = Tensor._wrap(o._data)
                t.stop_gradient = False
                t._node = node
                t._out_idx = i
                new_outs.append(t)
            outs = tuple(new_outs)
        return (list(outs) if isinstance(out, list) else tuple(outs)) if out_is_tuple else outs[0]


@contextlib.contextmanager
def saved_tensors_hooks(pack_hook, unpack_hook):
    """API parity stub: jax arrays are immutable and the engine stores primal
    arrays directly, so pack/unpack hooks have nothing to intercept. Reference:
    python/paddle/autograd/saved_tensors_hooks.py."""
    yield


def jacobian(ys, xs, batch_axis=None):
    """Reference autograd/autograd.py jacobian: lazy full Jacobian of
    ``ys`` w.r.t ``xs``. TPU-native: rather than N backward passes through
    the eager tape, re-trace the subgraph functionally and let
    ``jax.jacrev`` batch the rows in one compiled program. ``ys`` must be
    produced by a function of ``xs``; for API convenience this accepts a
    callable or a (fn, primal) pair via paddle.autograd.jacobian(fn, x).
    """
    import jax

    from ..core.state import trace_guard

    if not callable(ys):
        raise TypeError(
            "paddle.autograd.jacobian here takes (fn, x): pass the function "
            "producing ys (the eager-tape lazy-Jacobian form requires "
            "recording every intermediate; the functional form compiles to "
            "one fused program instead)")
    fn = ys
    x = xs

    def arr_fn(a):
        with trace_guard():
            out = fn(Tensor._wrap(a))
        return out._data if isinstance(out, Tensor) else out

    j = jax.jacrev(arr_fn)(x._data if isinstance(x, Tensor) else x)
    return Tensor._wrap(j)


def hessian(func, xs, batch_axis=None):
    """Reference autograd/autograd.py hessian — forward-over-reverse."""
    import jax

    from ..core.state import trace_guard

    x = xs

    def arr_fn(a):
        with trace_guard():
            out = func(Tensor._wrap(a))
        return out._data if isinstance(out, Tensor) else out

    h = jax.hessian(arr_fn)(x._data if isinstance(x, Tensor) else x)
    return Tensor._wrap(h)


__all__ += ["jacobian", "hessian"]
