"""Conv–BatchNorm folding for inference/eval steps (ISSUE 6 A/B probe).

Reference analog: ``paddle.incubate`` / Paddle-Inference's conv_bn_fuse
pass. In eval mode BatchNorm is an affine transform with frozen statistics,
so it folds into the preceding convolution exactly:

    W' = W * gamma / sqrt(var + eps)        (per output channel)
    b' = (b - mean) * gamma / sqrt(var + eps) + beta

The fold removes one full feature-map read+write per conv (the BN op), the
classic inference-graph fusion. Whether it *pays* under XLA — which already
fuses the BN affine into the conv's output elementwise epilogue — is an
empirical question; ``scripts/bench_conv_bn_fold.py`` measures it per the
PERF.md A/B discipline and the verdict (kept or reverted) is recorded in
PERF.md's round-7 table either way.

Only eval-mode models fold (training BN updates running stats and
normalizes by batch statistics — folding would change the math);
``fold_conv_bn`` walks every sublayer and folds each BatchNorm2D that
DIRECTLY follows a Conv2D in its parent's sublayer order — the
conv→bn idiom ResNet/PPYOLOE-style blocks register."""

from __future__ import annotations

import numpy as np

__all__ = ["fold_conv_bn"]


def _foldable(conv, bn):
    from ..nn.layer.conv import Conv2D
    from ..nn.layer.norm import BatchNorm2D

    return (isinstance(conv, Conv2D) and isinstance(bn, BatchNorm2D)
            and not conv._transpose
            and bn._mean.shape[0] == conv.weight.shape[0])


def fold_conv_bn(model, verify_eval=True):
    """Fold every (Conv2D -> BatchNorm2D) adjacent pair in ``model``'s
    sublayer trees into the conv; the BN is replaced with ``Identity``.
    Returns the number of folded pairs. The model must be in eval mode
    (``verify_eval=False`` skips the check for frozen-BN training
    setups).

    Adjacency is REGISTRATION order, not dataflow: the fold assumes a BN
    registered right after a conv normalizes that conv's output (the
    conv→bn idiom of ResNet/PPYOLOE-style blocks). A model whose forward
    wires them differently (e.g. the BN applied to a skip branch) would
    be silently mis-folded — this utility cannot see the forward graph,
    so ALWAYS verify folded-vs-unfolded outputs on a sample batch before
    trusting a folded model (``scripts/bench_conv_bn_fold.py`` does
    exactly this and refuses to report a speedup on mismatch)."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    from ..nn.layer.common import Identity

    if verify_eval and model.training:
        raise RuntimeError(
            "fold_conv_bn requires an eval-mode model (model.eval()): "
            "training-mode BatchNorm normalizes by batch statistics and "
            "cannot be folded")
    folded = 0
    for _, parent in model.named_sublayers(include_self=True):
        subs = list(parent._sub_layers.items())
        for (_, conv), (bn_name, bn) in zip(subs, subs[1:]):
            if not _foldable(conv, bn):
                continue
            gamma = np.asarray(bn.weight._data, np.float32)
            beta = np.asarray(bn.bias._data, np.float32)
            mean = np.asarray(bn._mean._data, np.float32)
            var = np.asarray(bn._variance._data, np.float32)
            scale = gamma / np.sqrt(var + bn._epsilon)
            w = np.asarray(conv.weight._data, np.float32)
            w_dtype = conv.weight._data.dtype
            new_w = w * scale.reshape(-1, 1, 1, 1)
            b = (np.asarray(conv.bias._data, np.float32)
                 if conv.bias is not None else 0.0)
            new_b = (b - mean) * scale + beta
            conv.weight._rebind(jnp.asarray(new_w).astype(w_dtype))
            if conv.bias is not None:
                conv.bias._rebind(
                    jnp.asarray(new_b).astype(conv.bias._data.dtype))
            else:
                conv.bias = conv.create_parameter(
                    [conv._out_channels], is_bias=True)
                conv.bias._rebind(jnp.asarray(new_b).astype(w_dtype))
                conv.bias.stop_gradient = True
            parent._sub_layers[bn_name] = Identity()
            folded += 1
    return folded
