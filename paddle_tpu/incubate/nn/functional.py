"""paddle.incubate.nn.functional — fused functional ops.

Reference: python/paddle/incubate/nn/functional/fused_rms_norm.py:21 and
fused_layer_norm.py:21 (CUDA kernels supporting the
``norm(bias + residual + x)`` fused pattern, returning
``(out, residual_out)`` when a residual is passed). TPU-native: routes to
the Pallas fused resid-add+norm kernels (ops/pallas/rms_norm.py) when the
shape contract holds, else to the XLA composition — calling this API is
itself the opt-in, no env flag needed. The int8 quant epilogue arguments
are not supported (quantization lives in paddle.quantization)."""

from __future__ import annotations

__all__ = ["fused_rms_norm", "fused_layer_norm"]


def _fusable(x, begin_norm_axis, *extras):
    ndim = len(x.shape)
    if begin_norm_axis not in (ndim - 1, -1):
        return False
    if x.shape[-1] % 128 != 0:
        return False
    return all(e is None for e in extras)


def _norm_ndims(x, begin_norm_axis):
    """Number of trailing dims the norm statistics cover."""
    ndim = len(x.shape)
    if begin_norm_axis < 0:
        begin_norm_axis += ndim
    return ndim - begin_norm_axis


def _flat_norm(norm_fn, x, begin_norm_axis):
    """Apply a last-dim norm over the flattened trailing dims selected by
    begin_norm_axis (the reference normalizes x[begin_norm_axis:] as one
    flattened axis), restoring the original shape."""
    nd = _norm_ndims(x, begin_norm_axis)
    if nd == 1:
        return norm_fn(x)
    shape = list(x.shape)
    flat = x.reshape(shape[:len(shape) - nd] + [-1])
    return norm_fn(flat).reshape(shape)


def _check_quant(quant_scale):
    if quant_scale != -1:
        raise NotImplementedError(
            "quantized fused norm is not supported on TPU; use "
            "paddle.quantization for int8 paths")


def fused_rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis,
                   bias=None, residual=None, quant_scale=-1,
                   quant_round_type=0, quant_max_bound=0, quant_min_bound=0):
    """RMSNorm(bias + residual + x) * norm_weight (+ norm_bias).

    Returns ``(out, residual_out)`` when ``residual`` is given (the fused
    pattern), else ``out`` — matching the reference return convention
    (fused_rms_norm.py:95).
    """
    _check_quant(quant_scale)
    import paddle_tpu.nn.functional as F

    if residual is not None:
        branch = x if bias is None else x + bias
        if _fusable(x, begin_norm_axis, norm_bias):
            from ...ops.pallas.rms_norm import fused_add_rms_norm

            out, resid = fused_add_rms_norm(residual, branch, norm_weight,
                                            epsilon=epsilon)
            return out, resid
        resid = residual + branch
        out = _flat_norm(lambda t: F.rms_norm(t, norm_weight, epsilon),
                         resid, begin_norm_axis)
        if norm_bias is not None:
            out = out + norm_bias
        return out, resid
    pre = x if bias is None else x + bias
    out = _flat_norm(lambda t: F.rms_norm(t, norm_weight, epsilon),
                     pre, begin_norm_axis)
    if norm_bias is not None:
        out = out + norm_bias
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis,
                     bias=None, residual=None, quant_scale=-1,
                     quant_round_type=0, quant_max_bound=0,
                     quant_min_bound=0):
    """LayerNorm(bias + residual + x); same conventions as
    :func:`fused_rms_norm` (reference fused_layer_norm.py:21)."""
    _check_quant(quant_scale)
    import paddle_tpu.nn.functional as F

    def ln(t):
        return F.layer_norm(t, [t.shape[-1]], norm_weight, norm_bias,
                            epsilon)

    if residual is not None:
        branch = x if bias is None else x + bias
        if _fusable(x, begin_norm_axis) and norm_bias is not None:
            from ...ops.pallas.rms_norm import fused_add_layer_norm

            out, resid = fused_add_layer_norm(residual, branch, norm_weight,
                                              norm_bias, epsilon=epsilon)
            return out, resid
        resid = residual + branch
        return _flat_norm(ln, resid, begin_norm_axis), resid
    pre = x if bias is None else x + bias
    return _flat_norm(ln, pre, begin_norm_axis)


# ---------------------------------------------------------------------------
# Fused transformer functional surface
# (reference: python/paddle/incubate/nn/functional/__init__.py __all__ :41)
# The CUDA fused kernels collapse into XLA fusion + the Pallas flash path:
# calling these APIs routes to scaled_dot_product_attention (Pallas when
# shapes qualify) and XLA-fused matmul epilogues — same contract, TPU body.
# ---------------------------------------------------------------------------

__all__ += [
    "fused_multi_head_attention", "fused_feedforward",
    "fused_bias_dropout_residual_layer_norm", "fused_dropout_add",
    "fused_rotary_position_embedding", "fused_linear", "fused_matmul_bias",
    "fused_linear_activation", "fused_ec_moe", "fused_multi_transformer",
]


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """y + dropout(x) in one epilogue (reference fused_dropout_add.py:22).
    XLA fuses the mask-scale-add chain into one kernel."""
    import paddle_tpu.nn.functional as F

    return y + F.dropout(x, p=p, training=training, mode=mode)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """matmul + bias epilogue (reference fused_matmul_bias.py:21, cublasLt);
    XLA fuses the bias add into the GEMM."""
    from paddle_tpu import ops

    out = ops.matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    if bias is not None:
        out = out + bias
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """Reference fused_matmul_bias.py:75."""
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation=None):
    """Reference fused_matmul_bias.py:110 (GEMM + bias + gelu/relu
    epilogue)."""
    import paddle_tpu.nn.functional as F

    out = fused_matmul_bias(x, y, bias, transpose_x=trans_x,
                            transpose_y=trans_y)
    if activation in (None, "none", ""):
        return out
    if activation not in ("gelu", "relu"):
        raise ValueError(
            f"fused_linear_activation supports gelu/relu, got {activation!r}")
    return getattr(F, activation)(out)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True,
                                           mode="upscale_in_train",
                                           name=None):
    """layer_norm(residual + dropout(bias + x)) — reference
    fused_transformer.py:323."""
    import paddle_tpu.nn.functional as F

    h = x if bias is None else x + bias
    h = residual + F.dropout(h, p=dropout_rate, training=training, mode=mode)
    return F.layer_norm(h, [h.shape[-1]], ln_scale, ln_bias, ln_epsilon)


def _default_rope_tables(seq_len, head_dim, dtype, neox=True):
    import numpy as np

    import paddle_tpu as paddle

    inv = 1.0 / (10000.0 ** (np.arange(0, head_dim, 2,
                                       dtype=np.float64) / head_dim))
    t = np.arange(seq_len, dtype=np.float64)
    freqs = np.outer(t, inv)                       # [S, D/2]
    if neox:
        # pairs (2i, 2i+1) share frequency i -> interleaved layout
        emb = np.repeat(freqs, 2, axis=-1)         # [f0,f0,f1,f1,...]
    else:
        # half-rotation pairs (i, i+D/2) share frequency i -> concat layout
        emb = np.concatenate([freqs, freqs], axis=-1)  # [f0..fk,f0..fk]
    return (paddle.to_tensor(np.sin(emb).astype(dtype)),
            paddle.to_tensor(np.cos(emb).astype(dtype)))


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """Rotary embedding applied to q/k/v in one pass (reference
    fused_rotary_position_embedding.py:21; CUDA fused_rope kernel).

    Shapes: q/k/v [B, S, H, D]; sin/cos [S, D] or [1, S, 1, D];
    position_ids [B, S]. neox style rotates adjacent pairs; non-neox
    rotates front/back halves. Returns a tuple matching the (q, k, v)
    arguments that were passed.
    """
    from paddle_tpu import ops

    head_dim = q.shape[-1]
    if head_dim % 2 != 0:
        raise ValueError("head_dim must be even for rotary embedding, got "
                         f"{head_dim}")
    if (sin is None) != (cos is None):
        raise ValueError("sin and cos must be given together")
    if sin is None:
        sin, cos = _default_rope_tables(q.shape[1], head_dim,
                                        str(q.dtype).split(".")[-1],
                                        neox=use_neox_rotary_style)

    # normalize tables to [S, D] then index / broadcast to [B-or-1, S, 1, D]
    if len(sin.shape) == 4:
        sin = sin.reshape([sin.shape[1], sin.shape[3]])
        cos = cos.reshape([cos.shape[1], cos.shape[3]])
    if position_ids is not None:
        sin = ops.gather(sin, position_ids.reshape([-1]), axis=0) \
            .reshape([position_ids.shape[0], position_ids.shape[1], 1,
                      head_dim])
        cos = ops.gather(cos, position_ids.reshape([-1]), axis=0) \
            .reshape([position_ids.shape[0], position_ids.shape[1], 1,
                      head_dim])
    else:
        sin = sin.reshape([1, sin.shape[0], 1, head_dim])
        cos = cos.reshape([1, cos.shape[0], 1, head_dim])

    import jax.numpy as jnp

    from ...core.dispatch import op as _op

    if not hasattr(fused_rotary_position_embedding, "_kernel"):
        @_op("fused_rope")
        def _kernel(x, sin_a, cos_a, neox=True):
            if neox:
                # pairs (0,1),(2,3),...: rotate_half interleaves (-x1, x0)
                x0 = x[..., 0::2]
                x1 = x[..., 1::2]
                rot = jnp.stack([-x1, x0], axis=-1).reshape(x.shape)
            else:
                # front half / back half
                half = x.shape[-1] // 2
                rot = jnp.concatenate([-x[..., half:], x[..., :half]],
                                      axis=-1)
            return x * cos_a + rot * sin_a

        fused_rotary_position_embedding._kernel = _kernel

    kern = fused_rotary_position_embedding._kernel
    # reference contract (fused_rotary_position_embedding.py:126): always a
    # 3-tuple (out_q, out_k, out_v), None for absent inputs
    return tuple(
        kern(t, sin, cos, neox=use_neox_rotary_style)
        if t is not None else None
        for t in (q, k, v))


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-05, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-05,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=-1,
                               transpose_qkv_wb=False, name=None):
    """Packed-QKV self-attention block (reference fused_transformer.py:514
    pseudo code): optional pre-LN, QKV projection, sdpa (Pallas flash when
    shapes qualify), out projection, dropout+residual, optional post-LN.
    """
    import paddle_tpu.nn.functional as F

    b, s, embed_dim = x.shape
    if transpose_qkv_wb:
        assert num_heads > 0, "num_heads required when transpose_qkv_wb"
        n_heads = num_heads
        head_dim = embed_dim // n_heads        # [E, 3E] layout implies it
        qkv_w = qkv_weight                     # [E, 3E]
        bias_flat = qkv_bias                   # [3E] or None
    else:
        # the 4-D layout carries head_dim explicitly and the reference
        # permits head_dim != embed_dim // num_heads here — keep it
        _, n_heads, head_dim, _ = qkv_weight.shape
        qkv_w = qkv_weight.reshape([3 * n_heads * head_dim, embed_dim]).t()
        bias_flat = (qkv_bias.reshape([3 * n_heads * head_dim])
                     if qkv_bias is not None else None)

    residual = x
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, [embed_dim], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    qkv = fused_matmul_bias(h, qkv_w, bias_flat)
    qkv = qkv.reshape([b, s, 3, n_heads, head_dim])
    q, k, v = (qkv[:, :, i] for i in range(3))  # [b, s, h, d]

    cache_out = None
    if cache_kv is not None:
        # cache_kv: [2, B, n_heads, cache_len, head_dim] (reference layout);
        # append this step's k/v and attend over the full sequence
        from paddle_tpu import ops

        k_cache = cache_kv[0].transpose([0, 2, 1, 3])  # [B, cache, H, D]
        v_cache = cache_kv[1].transpose([0, 2, 1, 3])
        k = ops.concat([k_cache, k], axis=1)
        v = ops.concat([v_cache, v], axis=1)
        cache_out = ops.stack([k.transpose([0, 2, 1, 3]),
                               v.transpose([0, 2, 1, 3])], axis=0)

    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0, training=training)
    out = out.reshape([b, s, n_heads * head_dim])
    out = fused_matmul_bias(out, linear_weight, linear_bias)
    out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [embed_dim], ln_scale, ln_bias, ln_epsilon)
    return out if cache_out is None else (out, cache_out)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1, add_residual=True,
                      name=None):
    """Transformer FFN block (reference fused_transformer.py:36 pseudo
    code): optional pre-LN, linear1+act+dropout1, linear2, dropout2 +
    residual, optional post-LN."""
    import paddle_tpu.nn.functional as F

    d_model = x.shape[-1]
    residual = x
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, [d_model], ln1_scale, ln1_bias, ln1_epsilon)
    h = fused_linear_activation(h, linear1_weight, linear1_bias,
                                activation=activation)
    h = F.dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = fused_matmul_bias(h, linear2_weight, linear2_bias)
    h = F.dropout(h, p=dropout2_rate, training=training, mode=mode)
    if add_residual:
        h = residual + h
    if not pre_layer_norm:
        h = F.layer_norm(h, [d_model], ln2_scale, ln2_bias, ln2_epsilon)
    return h


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type):
    """Expert-choice MoE (reference fused_ec_moe.py:18) — routed through the
    same dense einsum dispatch kernel as incubate.nn.FusedEcMoe."""
    from .layer import ec_moe_kernel

    if act_type not in ("gelu", "relu"):
        raise ValueError(f"act_type must be gelu/relu, got {act_type!r}")
    return ec_moe_kernel()(x, gate, bmm0_weight, bmm0_bias, bmm1_weight,
                           bmm1_bias, act=act_type)


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-05, cache_kvs=None, pre_caches=None,
                            seq_lens=None, rotary_embs=None, rotary_emb_dims=0,
                            time_step=None, attn_mask=None,
                            dropout_rate=0.0, activation="gelu",
                            training=False, mode="upscale_in_train",
                            trans_qkvw=True, ring_id=-1, name=None):
    """Stacked fused transformer blocks (reference fused_transformer.py:976)
    — each layer runs fused_multi_head_attention + fused_feedforward.
    cache_kvs follows the same [2, B, H, T, D]-per-layer convention."""
    unsupported = {"rotary_embs": rotary_embs, "time_step": time_step,
                   "seq_lens": seq_lens, "pre_caches": pre_caches}
    bad = [k for k, v in unsupported.items() if v is not None]
    if bad:
        raise NotImplementedError(
            f"fused_multi_transformer does not support {bad} on TPU; apply "
            "fused_rotary_position_embedding before the stack, and use "
            "masked_multihead_attention / models.llama generate for "
            "decode-step caching")
    n_layers = len(qkv_weights)
    h = x
    cache_outs = [] if cache_kvs is not None else None
    for i in range(n_layers):
        cache = cache_kvs[i] if cache_kvs is not None else None
        att = fused_multi_head_attention(
            h, qkv_weights[i], linear_weights[i],
            pre_layer_norm=pre_layer_norm, pre_ln_scale=ln_scales[i],
            pre_ln_bias=ln_biases[i], ln_scale=ln_scales[i],
            ln_bias=ln_biases[i], pre_ln_epsilon=epsilon,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            cache_kv=cache, attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, ln_epsilon=epsilon,
            training=training, mode=mode)
        if cache is not None:
            att, cache_out = att
            cache_outs.append(cache_out)
        h = fused_feedforward(
            att, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i], ln1_bias=ffn_ln_biases[i],
            ln2_scale=ffn_ln_scales[i], ln2_bias=ffn_ln_biases[i],
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, ln1_epsilon=epsilon, ln2_epsilon=epsilon,
            pre_layer_norm=pre_layer_norm, training=training, mode=mode)
    if cache_outs is not None:
        return h, cache_outs
    return h


__all__ += ["masked_multihead_attention", "block_multihead_attention",
            "variable_length_memory_efficient_attention"]


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0):
    """Variable-length attention (reference
    variable_length_memory_efficient_attention.py:28, CUTLASS kernel).

    TPU-native: padded dense attention with a length mask — XLA/Pallas want
    static shapes, so variable length is expressed as masking, not ragged
    kernels. Shapes: q/k/v [B, S, H, D] (paddle convention), seq_lens /
    kv_seq_lens [B, 1].
    """
    import jax
    import jax.numpy as jnp

    from ...core.dispatch import op as _op

    if not hasattr(variable_length_memory_efficient_attention, "_kernel"):
        @_op("varlen_mea_attention")
        def _kernel(q, k, v, q_lens, kv_lens, mask, scale=None,
                    causal=False, pre_cache_length=0):
            b, sq, h, d = q.shape
            sk = k.shape[1]
            if scale is None:
                scale = 1.0 / (d ** 0.5)
            qt = jnp.swapaxes(q, 1, 2)          # [B, H, Sq, D]
            kt = jnp.swapaxes(k, 1, 2)
            vt = jnp.swapaxes(v, 1, 2)
            logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
            neg = jnp.finfo(jnp.float32).min
            kv_valid = (jnp.arange(sk)[None, :]
                        < kv_lens.reshape(-1, 1))          # [B, Sk]
            logits = jnp.where(kv_valid[:, None, None, :], logits, neg)
            if causal:
                # query i attends kv positions <= offset + i, where the
                # offset covers the pre-cache (and any kv prefix when
                # sk > sq): kv j visible iff j - offset <= i
                offset = pre_cache_length if pre_cache_length else sk - sq
                cm = (jnp.arange(sk)[None, :] - offset
                      <= jnp.arange(sq)[:, None])          # [Sq, Sk]
                logits = jnp.where(cm[None, None], logits, neg)
            if mask is not None:
                logits = logits + mask
            p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(qt.dtype), vt)
            q_valid = (jnp.arange(sq)[None, :]
                       < q_lens.reshape(-1, 1))            # [B, Sq]
            out = out * q_valid[:, None, :, None].astype(out.dtype)
            return jnp.swapaxes(out, 1, 2)                 # [B, S, H, D]

        variable_length_memory_efficient_attention._kernel = _kernel

    return variable_length_memory_efficient_attention._kernel(
        query, key, value, seq_lens, kv_seq_lens, mask,
        scale=None if scale is None else float(scale), causal=bool(causal),
        pre_cache_length=int(pre_cache_length))


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               cum_offsets=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None,
                               out_smooth=None, seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """One-token decode attention against a KV cache (reference
    masked_multihead_attention.py:19).

    x: [B, 3*H*D] packed qkv for THIS step; cache_kv: [2, B, H, T_max, D].
    ``sequence_lengths`` [B, 1] gives each row's current length (entries at
    and beyond it are masked); the step's k/v are written at that position.
    Returns (out [B, H*D], updated cache_kv) like the reference. The int8
    quant epilogue args are unsupported (paddle.quantization owns that).
    """
    if any(a is not None for a in (cum_offsets, beam_cache_offset,
                                   qkv_out_scale, out_shift, out_smooth)):
        raise NotImplementedError(
            "masked_multihead_attention quant/beam epilogues are not "
            "supported on TPU")
    assert cache_kv is not None, "cache_kv is required"

    import jax
    import jax.numpy as jnp

    from ...core.dispatch import op as _op

    if not hasattr(masked_multihead_attention, "_kernel"):
        @_op("masked_mha_decode")
        def _kernel(x, cache, bias, src_mask, seq_lens, rotary, neox=False):
            b = x.shape[0]
            _, _, h, t_max, d = cache.shape
            qkv = x.reshape(b, 3, h, d)
            if bias is not None:
                qkv = qkv + bias[None]
            q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [B, H, D]
            if seq_lens is None:
                pos = jnp.zeros((b,), jnp.int32)
            else:
                pos = seq_lens.reshape(-1).astype(jnp.int32)
            if rotary is not None:
                # rotary: [2, B, 1, T_max, D] (cos, sin) per reference
                cos = jnp.take_along_axis(
                    rotary[0].reshape(b, t_max, d),
                    pos[:, None, None], axis=1)              # [B, 1, D]
                sin = jnp.take_along_axis(
                    rotary[1].reshape(b, t_max, d),
                    pos[:, None, None], axis=1)

                def rot(t):
                    if neox:
                        t0, t1 = t[..., 0::2], t[..., 1::2]
                        r = jnp.stack([-t1, t0], -1).reshape(t.shape)
                    else:
                        half = t.shape[-1] // 2
                        r = jnp.concatenate([-t[..., half:], t[..., :half]],
                                            -1)
                    return t * cos + r * sin

                q, k_new = rot(q), rot(k_new)
            # write k/v at pos
            onehot = jax.nn.one_hot(pos, t_max, dtype=cache.dtype)  # [B, T]
            k_cache = cache[0] * (1 - onehot[:, None, :, None]) + \
                k_new[:, :, None, :] * onehot[:, None, :, None]
            v_cache = cache[1] * (1 - onehot[:, None, :, None]) + \
                v_new[:, :, None, :] * onehot[:, None, :, None]
            scale = 1.0 / (d ** 0.5)
            logits = jnp.einsum("bhd,bhtd->bht", q, k_cache) * scale
            neg = jnp.finfo(jnp.float32).min
            valid = jnp.arange(t_max)[None, :] <= pos[:, None]  # [B, T]
            logits = jnp.where(valid[:, None, :], logits, neg)
            if src_mask is not None:
                logits = logits + src_mask.reshape(b, 1, -1)[:, :, :t_max]
            p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            out = jnp.einsum("bht,bhtd->bhd", p.astype(q.dtype), v_cache)
            return (out.reshape(b, h * d),
                    jnp.stack([k_cache, v_cache], axis=0))

        masked_multihead_attention._kernel = _kernel

    return masked_multihead_attention._kernel(
        x, cache_kv, bias, src_mask, sequence_lengths, rotary_tensor,
        neox=bool(use_neox_rotary_style))


def block_multihead_attention(*args, **kwargs):
    """Paged/blocked KV-cache attention (reference
    block_multihead_attention.py — CUDA paged-attention kernel).

    Not supported: paged KV block tables are a GPU-memory-pool design; the
    TPU-native serving path keeps dense per-sequence caches
    (models/llama.py generate: prefill + windowed decode under jit) and
    masked_multihead_attention for single-step decode. Use those.
    """
    raise NotImplementedError(
        "block_multihead_attention (paged KV cache) is not supported on "
        "TPU; use masked_multihead_attention for single-step decode or "
        "models.llama's KV-cache generate path")
