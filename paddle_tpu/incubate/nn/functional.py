"""paddle.incubate.nn.functional — fused functional ops.

Reference: python/paddle/incubate/nn/functional/fused_rms_norm.py:21 and
fused_layer_norm.py:21 (CUDA kernels supporting the
``norm(bias + residual + x)`` fused pattern, returning
``(out, residual_out)`` when a residual is passed). TPU-native: routes to
the Pallas fused resid-add+norm kernels (ops/pallas/rms_norm.py) when the
shape contract holds, else to the XLA composition — calling this API is
itself the opt-in, no env flag needed. The int8 quant epilogue arguments
are not supported (quantization lives in paddle.quantization)."""

from __future__ import annotations

__all__ = ["fused_rms_norm", "fused_layer_norm"]


def _fusable(x, begin_norm_axis, *extras):
    ndim = len(x.shape)
    if begin_norm_axis not in (ndim - 1, -1):
        return False
    if x.shape[-1] % 128 != 0:
        return False
    return all(e is None for e in extras)


def _norm_ndims(x, begin_norm_axis):
    """Number of trailing dims the norm statistics cover."""
    ndim = len(x.shape)
    if begin_norm_axis < 0:
        begin_norm_axis += ndim
    return ndim - begin_norm_axis


def _flat_norm(norm_fn, x, begin_norm_axis):
    """Apply a last-dim norm over the flattened trailing dims selected by
    begin_norm_axis (the reference normalizes x[begin_norm_axis:] as one
    flattened axis), restoring the original shape."""
    nd = _norm_ndims(x, begin_norm_axis)
    if nd == 1:
        return norm_fn(x)
    shape = list(x.shape)
    flat = x.reshape(shape[:len(shape) - nd] + [-1])
    return norm_fn(flat).reshape(shape)


def _check_quant(quant_scale):
    if quant_scale != -1:
        raise NotImplementedError(
            "quantized fused norm is not supported on TPU; use "
            "paddle.quantization for int8 paths")


def fused_rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis,
                   bias=None, residual=None, quant_scale=-1,
                   quant_round_type=0, quant_max_bound=0, quant_min_bound=0):
    """RMSNorm(bias + residual + x) * norm_weight (+ norm_bias).

    Returns ``(out, residual_out)`` when ``residual`` is given (the fused
    pattern), else ``out`` — matching the reference return convention
    (fused_rms_norm.py:95).
    """
    _check_quant(quant_scale)
    import paddle_tpu.nn.functional as F

    if residual is not None:
        branch = x if bias is None else x + bias
        if _fusable(x, begin_norm_axis, norm_bias):
            from ...ops.pallas.rms_norm import fused_add_rms_norm

            out, resid = fused_add_rms_norm(residual, branch, norm_weight,
                                            epsilon=epsilon)
            return out, resid
        resid = residual + branch
        out = _flat_norm(lambda t: F.rms_norm(t, norm_weight, epsilon),
                         resid, begin_norm_axis)
        if norm_bias is not None:
            out = out + norm_bias
        return out, resid
    pre = x if bias is None else x + bias
    out = _flat_norm(lambda t: F.rms_norm(t, norm_weight, epsilon),
                     pre, begin_norm_axis)
    if norm_bias is not None:
        out = out + norm_bias
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis,
                     bias=None, residual=None, quant_scale=-1,
                     quant_round_type=0, quant_max_bound=0,
                     quant_min_bound=0):
    """LayerNorm(bias + residual + x); same conventions as
    :func:`fused_rms_norm` (reference fused_layer_norm.py:21)."""
    _check_quant(quant_scale)
    import paddle_tpu.nn.functional as F

    def ln(t):
        return F.layer_norm(t, [t.shape[-1]], norm_weight, norm_bias,
                            epsilon)

    if residual is not None:
        branch = x if bias is None else x + bias
        if _fusable(x, begin_norm_axis) and norm_bias is not None:
            from ...ops.pallas.rms_norm import fused_add_layer_norm

            out, resid = fused_add_layer_norm(residual, branch, norm_weight,
                                              norm_bias, epsilon=epsilon)
            return out, resid
        resid = residual + branch
        return _flat_norm(ln, resid, begin_norm_axis), resid
    pre = x if bias is None else x + bias
    return _flat_norm(ln, pre, begin_norm_axis)
