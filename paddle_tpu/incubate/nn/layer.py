"""incubate.nn fused layers.

Reference: python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention :33, FusedFeedForward :~400,
FusedTransformerEncoderLayer, FusedMultiTransformer :~900, FusedLinear,
FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd, FusedEcMoe) — thin
Python over monolithic fused CUDA ops. TPU-native: the same computations
expressed in the layer/functional vocabulary; XLA fuses the epilogues the
CUDA ops fuse by hand, and the attention core rides the Pallas flash
kernel via scaled_dot_product_attention. Parameter names/shapes follow the
reference so state dicts line up.
"""

from __future__ import annotations

import paddle_tpu.nn.functional as F

from ...nn.initializer import Constant
from ...nn.layer.layers import Layer

__all__ = [
    "FusedMultiHeadAttention", "FusedFeedForward",
    "FusedTransformerEncoderLayer", "FusedMultiTransformer", "FusedLinear",
    "FusedBiasDropoutResidualLayerNorm", "FusedEcMoe", "FusedDropoutAdd",
]


class FusedLinear(Layer):
    """reference fused_linear: GEMM + bias in one op (cublasLt epilogue);
    XLA always fuses the bias add."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = [out_features, in_features] if transpose_weight \
            else [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = (self.create_parameter([out_features], attr=bias_attr,
                                           is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        from . import functional as IF

        return IF.fused_linear(x, self.weight, self.bias,
                               transpose_weight=self.transpose_weight)


class FusedDropoutAdd(Layer):
    """reference fused_dropout_add: y + dropout(x)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        from . import functional as IF

        return IF.fused_dropout_add(x, y, p=self.p, training=self.training,
                                    mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """reference fused_bias_dropout_residual_layer_norm:
    LN(residual + dropout(x + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter([embed_dim],
                                                 attr=bias_attr,
                                                 is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr, default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], attr=bias_attr,
                                             is_bias=True)

    def forward(self, x, residual):
        from . import functional as IF

        return IF.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedMultiHeadAttention(Layer):
    """reference FusedMultiHeadAttention (fused_transformer.py:33): packed
    QKV projection + attention + out projection + residual + LN, pre- or
    post-norm. Attention runs through scaled_dot_product_attention (Pallas
    flash kernel when shapes qualify)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert not need_weights, "need_weights unsupported (reference too)"
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        # packed [3, H, D, embed] like the fused op's layout
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = (self.create_parameter(
            [3, num_heads, self.head_dim], attr=qkv_bias_attr, is_bias=True)
            if qkv_bias_attr is not False else None)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr,
            default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter(
            [embed_dim], attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], attr=ln_bias_attr,
                                             is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if cache is not None:
            raise NotImplementedError(
                "FusedMultiHeadAttention cache decoding is not wired; use "
                "models.llama's KV-cache generate path for incremental "
                "decoding")
        residual = query
        x = query
        if self.normalize_before:
            x = F.layer_norm(x, [self.embed_dim], self.pre_ln_scale,
                             self.pre_ln_bias, self._epsilon)
        b, s, _ = x.shape
        qkv_w = self.qkv_weight.reshape([3 * self.embed_dim,
                                         self.embed_dim]).t()
        qkv = F.linear(x, qkv_w,
                       None if self.qkv_bias is None
                       else self.qkv_bias.reshape([3 * self.embed_dim]))
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = (qkv[:, :, i] for i in range(3))  # [b, s, h, d]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
            training=self.training)
        out = out.reshape([b, s, self.embed_dim])
        out = F.linear(out, self.linear_weight, self.linear_bias)
        out = residual + F.dropout(out, p=self.dropout_rate,
                                   training=self.training)
        if not self.normalize_before:
            out = F.layer_norm(out, [self.embed_dim], self.ln_scale,
                               self.ln_bias, self._epsilon)
        return out


class FusedFeedForward(Layer):
    """reference FusedFeedForward: LN? -> linear -> act -> dropout ->
    linear -> dropout -> +residual -> LN?."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.d_model = d_model
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.activation = activation
        self._epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], attr=ln1_scale_attr,
            default_initializer=Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], attr=ln1_bias_attr,
                                              is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], attr=ln2_scale_attr,
            default_initializer=Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], attr=ln2_bias_attr,
                                              is_bias=True)

    def forward(self, src, cache=None):
        residual = src
        if self.normalize_before:
            src = F.layer_norm(src, [self.d_model], self.ln1_scale,
                               self.ln1_bias, self._epsilon)
        h = F.linear(src, self.linear1_weight, self.linear1_bias)
        h = getattr(F, self.activation)(h)
        h = F.dropout(h, p=self.act_dropout_rate, training=self.training)
        h = F.linear(h, self.linear2_weight, self.linear2_bias)
        out = residual + F.dropout(h, p=self.dropout_rate,
                                   training=self.training)
        if not self.normalize_before:
            out = F.layer_norm(out, [self.d_model], self.ln2_scale,
                               self.ln2_bias, self._epsilon)
        return out


class FusedTransformerEncoderLayer(Layer):
    """reference FusedTransformerEncoderLayer = fused MHA + fused FFN."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        ad = dropout_rate if attn_dropout_rate is None else attn_dropout_rate
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate, attn_dropout_rate=ad,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(Layer):
    """reference FusedMultiTransformer (fused_multi_transformer_op.cu):
    a pre-LN decoder stack in one op; here a stack of the fused layers."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, num_layers=-1, nranks=1, ring_id=-1,
                 name=None, **kwargs):
        super().__init__()
        assert normalize_before, \
            "reference FusedMultiTransformer is pre-LN only"
        if num_layers <= 0:
            # reference fused_transformer.py:230 infers depth from the
            # per-layer attr lists
            for key in ("qkv_weight_attrs", "ln_scale_attrs"):
                attrs = kwargs.get(key) if key in kwargs else (
                    ln_scale_attrs if key == "ln_scale_attrs" else None)
                if isinstance(attrs, (list, tuple)):
                    num_layers = len(attrs)
                    break
        assert num_layers > 0, \
            "pass num_layers or per-layer attr lists to fix the depth"
        n = num_layers
        self.layers = [FusedTransformerEncoderLayer(
            embed_dim, num_heads, dim_feedforward,
            dropout_rate=dropout_rate, activation=activation,
            normalize_before=True) for _ in range(n)]
        for i, l in enumerate(self.layers):
            self.add_sublayer(f"layer_{i}", l)

    def forward(self, src, attn_mask=None, caches=None, **kwargs):
        if caches is not None:
            raise NotImplementedError(
                "FusedMultiTransformer cache decoding is not wired; use "
                "models.llama's KV-cache generate path")
        out = src
        for l in self.layers:
            out = l(out, src_mask=attn_mask)
        return out


class FusedEcMoe(Layer):
    """reference FusedEcMoe (fused_ec_moe op): expert-choice routing — each
    expert picks its own top-C tokens (Zhou et al. 2022), so load is
    perfectly balanced by construction. Dense einsum dispatch; under GSPMD
    the expert dim shards over 'ep'."""

    def __init__(self, hidden_size, inter_size, num_experts,
                 act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        assert act_type in ("gelu", "relu")
        self.act_type = act_type
        self.num_experts = num_experts
        self.bmm_weight0 = self.create_parameter(
            [num_experts, hidden_size, inter_size], attr=weight_attr)
        self.bmm_bias0 = (self.create_parameter(
            [num_experts, 1, inter_size], attr=bias_attr, is_bias=True)
            if bias_attr is not False else None)
        self.bmm_weight1 = self.create_parameter(
            [num_experts, inter_size, hidden_size], attr=weight_attr)
        self.bmm_bias1 = (self.create_parameter(
            [num_experts, 1, hidden_size], attr=bias_attr, is_bias=True)
            if bias_attr is not False else None)

    def forward(self, x, gate):
        """x: [B, S, H]; gate: [B, S, E] logits."""
        return ec_moe_kernel()(x, gate, self.bmm_weight0, self.bmm_bias0,
                               self.bmm_weight1, self.bmm_bias1,
                               act=self.act_type)


_EC_MOE_KERNEL = None


def ec_moe_kernel():
    """Lazily-registered expert-choice MoE dispatch op, shared by the
    FusedEcMoe layer and incubate.nn.functional.fused_ec_moe."""
    global _EC_MOE_KERNEL
    if _EC_MOE_KERNEL is None:
        import jax
        import jax.numpy as jnp

        from ...core.dispatch import op as _op

        @_op("fused_ec_moe")
        def _kernel(x, gate, w0, b0, w1, b1, act="gelu"):
            b, s, h = x.shape
            e = gate.shape[-1]
            t = b * s
            cap = max(t // e, 1)
            xf = x.reshape(t, h)
            probs = jax.nn.softmax(gate.reshape(t, e).astype(jnp.float32),
                                   axis=-1)
            # expert-choice: each expert takes its top-cap tokens
            topv, topi = jax.lax.top_k(probs.T, cap)      # [E, cap]
            tok = jnp.take(xf, topi.reshape(-1), axis=0) \
                .reshape(e, cap, h)
            hmid = jnp.einsum("ech,ehi->eci", tok, w0)
            if b0 is not None:
                hmid = hmid + b0
            hmid = (jax.nn.gelu(hmid) if act == "gelu"
                    else jnp.maximum(hmid, 0))
            out_e = jnp.einsum("eci,eih->ech", hmid, w1)
            if b1 is not None:
                out_e = out_e + b1
            # combine: scatter-add weighted expert outputs back
            flat = jnp.zeros((t, h), out_e.dtype)
            contrib = out_e * topv[..., None].astype(out_e.dtype)
            flat = flat.at[topi.reshape(-1)].add(
                contrib.reshape(e * cap, h))
            return flat.reshape(b, s, h).astype(x.dtype)

        _EC_MOE_KERNEL = _kernel
    return _EC_MOE_KERNEL
