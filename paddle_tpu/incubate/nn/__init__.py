"""paddle.incubate.nn — fused-op layer APIs.

Reference: python/paddle/incubate/nn/ (FusedMultiHeadAttention,
FusedFeedForward layer wrappers over the fused CUDA ops). Here the
functional namespace maps onto the Pallas kernel suite (ops/pallas/)."""

from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd, FusedEcMoe,
    FusedFeedForward, FusedLinear, FusedMultiHeadAttention,
    FusedMultiTransformer, FusedTransformerEncoderLayer,
)

__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer",
           "FusedLinear", "FusedBiasDropoutResidualLayerNorm", "FusedEcMoe",
           "FusedDropoutAdd"]
