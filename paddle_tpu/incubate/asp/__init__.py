"""paddle.incubate.asp — 2:4 (n:m) structured sparsity.

Reference: python/paddle/incubate/asp/ (asp.py: decorate :216,
prune_model :302; utils.py: calculate_density :78, get_mask_1d :184,
get_mask_2d_greedy :326, create_mask :498, check_sparsity :569).

TPU-native: masks are computed host-side in numpy (a one-off pruning pass)
and mask re-application after each optimizer step is one fused multiply —
XLA folds it into the update. The reference's sparse tensor-core GEMMs have
no TPU analog (the MXU is dense), so ASP here is the TRAINING-side workflow:
prune, keep sparsity through updates, verify. That matches the reference's
own CPU path, where masked weights run through dense kernels too.
"""

from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor

__all__ = ["calculate_density", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers", "get_mask_1d",
           "get_mask_2d_greedy", "check_sparsity", "ASPHelper"]

_EXCLUDED: set[str] = set()


def calculate_density(x):
    """reference utils.py:78 — fraction of non-zeros."""
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def get_mask_1d(mat, n, m):
    """Keep the n largest-|.| of every m consecutive elements per row
    (reference utils.py:184)."""
    mat = np.asarray(mat)
    shape = mat.shape
    flat = mat.reshape(-1, m)
    mask = np.zeros_like(flat, dtype=bool)
    keep = np.argsort(-np.abs(flat), axis=1)[:, :n]
    np.put_along_axis(mask, keep, True, axis=1)
    return mask.reshape(shape)


def get_mask_2d_greedy(mat, n, m):
    """Greedy m x m block mask with n:m per row AND per column
    (reference utils.py:326)."""
    mat = np.asarray(mat)
    h, w = mat.shape
    mask = np.zeros((h, w), dtype=bool)
    for bi in range(0, h, m):
        for bj in range(0, w, m):
            block = np.abs(mat[bi:bi + m, bj:bj + m])
            bm = np.zeros_like(block, dtype=bool)
            order = np.argsort(-block, axis=None)
            rows = np.zeros(block.shape[0], np.int64)
            cols = np.zeros(block.shape[1], np.int64)
            for idx in order:
                i, j = divmod(int(idx), block.shape[1])
                if rows[i] < n and cols[j] < n:
                    bm[i, j] = True
                    rows[i] += 1
                    cols[j] += 1
            mask[bi:bi + m, bj:bj + m] = bm
    return mask


def check_sparsity(tensor, n=2, m=4, func_name="check_1d"):
    """reference utils.py:569 — every m-group holds <= n non-zeros."""
    arr = np.asarray(tensor._data if isinstance(tensor, Tensor) else tensor)
    if arr.ndim < 2 or arr.shape[-1] % m:
        return False
    flat = arr.reshape(-1, m)
    return bool((np.count_nonzero(flat, axis=1) <= n).all())


def set_excluded_layers(param_names, main_program=None):
    """reference asp.py:40."""
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    """reference asp.py:127."""
    _EXCLUDED.clear()


class ASPHelper:
    """Mask registry (reference asp.py:515). Class-level like the
    reference's per-program info map."""

    _masks: dict[int, np.ndarray] = {}  # id(param) -> mask
    _params: dict[int, Tensor] = {}

    @classmethod
    def _supported(cls, name, param):
        if name in _EXCLUDED:
            return False
        arr = param._data
        # Linear [in, out] / Conv [out, in, kh, kw]: prune along the input
        # dim in groups of m like the reference's supported_layer_list
        return arr.ndim >= 2 and "weight" in name.split(".")[-1]

    @classmethod
    def prune_model(cls, model, n=2, m=4, mask_algo="mask_1d",
                    with_mask=True):
        import jax.numpy as jnp

        masks = {}
        for name, p in model.named_parameters():
            if not cls._supported(name, p):
                continue
            w = np.asarray(p._data, dtype=np.float32)
            mat = w.reshape(w.shape[0], -1) if w.ndim > 2 else w
            if mat.shape[-1] % m:
                continue
            if mask_algo in ("mask_1d", "MaskAlgo.MASK_1D"):
                mask = get_mask_1d(mat, n, m)
            else:
                mask = get_mask_2d_greedy(mat, n, m)
            mask = mask.reshape(w.shape)
            p._data = (p._data * jnp.asarray(mask, p._data.dtype))
            if with_mask:
                masks[name] = mask
                cls._masks[id(p)] = mask
                cls._params[id(p)] = p
        return masks

    @classmethod
    def reapply_masks(cls):
        import jax.numpy as jnp

        for pid, mask in cls._masks.items():
            p = cls._params[pid]
            p._data = p._data * jnp.asarray(mask, p._data.dtype)


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """reference asp.py:302 — one-off magnitude pruning to n:m."""
    return ASPHelper.prune_model(model, n, m, mask_algo, with_mask)


class OptimizerWithSparsityGuarantee:
    """reference asp.py:918 — re-applies masks after every step so pruned
    slots stay zero through training."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        self._optimizer.step()
        ASPHelper.reapply_masks()


def decorate(optimizer):
    """reference asp.py:216."""
    return OptimizerWithSparsityGuarantee(optimizer)
