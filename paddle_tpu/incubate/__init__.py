"""paddle.incubate — experimental APIs.

Reference: python/paddle/incubate/ (MoE layers, autotune, fused ops,
DistributedFusedLamb). TPU-native contents: the fused single-dispatch
train step and (distributed) the sparse all-to-all MoE layer.
"""

from .fused_train_step import FusedTrainStep, fused_train_step  # noqa: F401
from .fold_conv_bn import fold_conv_bn  # noqa: F401
from .sentinel import RollbackBudget, TrainingSentinel  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from .extras import (  # noqa: F401
    LookAhead, ModelAverage, graph_khop_sampler, graph_reindex,
    graph_sample_neighbors, graph_send_recv, identity_loss, segment_max,
    segment_mean, segment_min, segment_sum, softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)

__all__ = ["FusedTrainStep", "fused_train_step", "fold_conv_bn",
           "RollbackBudget",
           "TrainingSentinel", "asp", "autotune", "nn",
           "optimizer", "LookAhead", "ModelAverage", "graph_khop_sampler",
           "graph_reindex", "graph_sample_neighbors", "graph_send_recv",
           "identity_loss", "segment_max", "segment_mean", "segment_min",
           "segment_sum", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle"]
