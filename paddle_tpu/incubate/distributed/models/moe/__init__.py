from .moe_layer import (  # noqa: F401
    MoELayer,
    combine_from_experts,
    dispatch_to_experts,
    moe_capacity,
    top_k_capacity_gating,
)

__all__ = ["MoELayer", "combine_from_experts", "dispatch_to_experts",
           "moe_capacity", "top_k_capacity_gating"]
