"""Sparse Mixture-of-Experts with capacity-based top-k dispatch.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py —
MoELayer (:119) dispatching tokens to experts across the expert-parallel
group with the global_scatter/global_gather all-to-all ops
(paddle/fluid/operators/collective/global_scatter_op.*, :119-190).

TPU-native redesign: the reference's dynamic per-rank token counts
(global_scatter carries local_count/global_count) cannot compile under
XLA's static shapes, so dispatch is GShard-style **capacity-based**: each
expert processes at most C = ceil(top_k * T / E * capacity_factor) tokens
per shard, encoded as one-hot dispatch/combine tensors. Per-token FLOPs
are top_k * expert_FLOPs — independent of num_experts (the round-1
dense-dispatch form computed every expert on every token).

Two execution paths share the gate math:
- expert-parallel: `shard_map` over the 'ep' mesh axis with TWO
  `lax.all_to_all` collectives (the global_scatter / global_gather
  equivalents) moving expert batches between ranks; expert weights are
  stacked [E, ...] and split over 'ep'.
- single-shard / GSPMD: the same dispatch expressed as einsums; under
  pjit the expert dim shards over 'ep' and GSPMD inserts the all-to-alls.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .....core.dispatch import op
from .....core.tensor import Tensor
from .....nn.layer.layers import Layer
from .....utils import functional_call, params_dict

__all__ = ["MoELayer", "top_k_capacity_gating", "moe_capacity"]


def top_k_capacity_gating(probs, top_k, capacity):
    """GShard gating, scatter form. Returns
    (expert_idx [T,k], slot_idx [T,k], keep [T,k], weights [T,k], aux).

    Token t's kk-th choice goes to slot ``slot_idx[t,kk]`` of expert
    ``expert_idx[t,kk]``; ``keep`` is False for choices beyond the
    expert's capacity (dropped — standard GShard semantics; the
    reference's global_scatter instead grows buffers dynamically).
    ``weights`` are the renormalised top-k router probabilities. ``aux``
    is the load-balancing loss E * sum(me * ce) (Switch/GShard).

    Memory is O(T*E) (the per-round one-hot), NOT O(T*E*C): dispatch and
    combine are done by scatter-add / gather on flat slot indices, so no
    [T, E, C] tensor is ever materialised.
    """
    T, E = probs.shape
    C = int(capacity)
    topv, topi = jax.lax.top_k(probs, top_k)  # [T, k]
    topv = topv / (jnp.sum(topv, axis=-1, keepdims=True) + 1e-9)

    counts = jnp.zeros((E,), jnp.int32)
    slots = []
    keeps = []
    for kk in range(top_k):
        oh = jax.nn.one_hot(topi[:, kk], E, dtype=jnp.int32)  # [T, E]
        pos = jnp.cumsum(oh, axis=0) - 1 + counts[None, :]
        slot_k = jnp.take_along_axis(pos, topi[:, kk:kk + 1], axis=1)[:, 0]
        keeps.append(slot_k < C)
        slots.append(jnp.clip(slot_k, 0, C - 1))
        counts = counts + jnp.sum(oh, axis=0)
    slot_idx = jnp.stack(slots, axis=1)
    keep = jnp.stack(keeps, axis=1)

    # load-balance aux: fraction of tokens routed (top-1) vs mean prob
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(
        jax.nn.one_hot(topi[:, 0], E, dtype=probs.dtype), axis=0)
    aux = E * jnp.sum(me * ce)
    return topi, slot_idx, keep, topv, aux


def dispatch_to_experts(x, expert_idx, slot_idx, keep, num_experts,
                        capacity):
    """Scatter tokens into their expert slots: [T,h] -> [E,C,h]."""
    T, h = x.shape
    k = expert_idx.shape[1]
    flat = expert_idx * capacity + slot_idx  # [T, k]
    flat = jnp.where(keep, flat, num_experts * capacity)  # overflow row
    buf = jnp.zeros((num_experts * capacity + 1, h), x.dtype)
    xk = jnp.broadcast_to(x[:, None, :], (T, k, h)).reshape(T * k, h)
    buf = buf.at[flat.reshape(-1)].add(xk)
    return buf[:-1].reshape(num_experts, capacity, h)


def combine_from_experts(expert_out, expert_idx, slot_idx, keep, weights):
    """Gather expert outputs back to tokens: [E,C,h] -> [T,h]."""
    E, C, h = expert_out.shape
    T, k = expert_idx.shape
    flat = expert_idx * C + slot_idx
    gathered = expert_out.reshape(E * C, h)[flat.reshape(-1)]
    gathered = gathered.reshape(T, k, h)
    w = (weights * keep.astype(weights.dtype)).astype(expert_out.dtype)
    return jnp.einsum("tkh,tk->th", gathered, w)


def moe_capacity(num_tokens, num_experts, top_k, factor):
    return max(int(math.ceil(top_k * num_tokens / num_experts * factor)), 1)


def _expert_apply(template, names, stacked_leaves, expert_in):
    """vmap the template expert over the (local) expert dim."""

    def one(leaves, xs):
        return functional_call(template, dict(zip(names, leaves)), xs)

    return jax.vmap(one)(stacked_leaves, expert_in)


@op("moe_sparse_dispatch")
def _moe_sparse_op(x, logits, *stacked_leaves, names=(), top_k=2,
                   capacity_factor=1.25, ep_axis=None, mesh=None,
                   template=None):
    """x: [T, h]; logits: [T, E]; stacked_leaves: expert params stacked on
    a leading [E] dim. Returns (out [T, h], aux scalar)."""
    num_experts = logits.shape[-1]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    if ep_axis is None or mesh is None or mesh.shape.get(ep_axis, 1) == 1:
        C = moe_capacity(x.shape[0], num_experts, top_k, capacity_factor)
        ei, si, keep, w, aux = top_k_capacity_gating(probs, top_k, C)
        expert_in = dispatch_to_experts(x, ei, si, keep, num_experts, C)
        expert_out = _expert_apply(template, names, stacked_leaves,
                                   expert_in)
        out = combine_from_experts(expert_out, ei, si, keep, w)
        return out, aux

    n = mesh.shape[ep_axis]
    assert num_experts % n == 0, (num_experts, n)
    T = x.shape[0]
    assert T % n == 0, f"token count {T} not divisible by ep degree {n}"
    C = moe_capacity(T // n, num_experts, top_k, capacity_factor)

    def local(x_l, logits_l, *leaves_l):
        # x_l: [T/n, h] this rank's tokens; leaves_l: [E/n, ...] its experts
        probs_l = jax.nn.softmax(logits_l.astype(jnp.float32), axis=-1)
        ei, si, keep, w, aux = top_k_capacity_gating(probs_l, top_k, C)
        expert_in = dispatch_to_experts(x_l, ei, si, keep, num_experts, C)
        # global_scatter equivalent: exchange expert batches so each rank
        # holds ALL ranks' tokens for ITS experts
        expert_in = jax.lax.all_to_all(
            expert_in, ep_axis, split_axis=0, concat_axis=1, tiled=True)
        expert_out = _expert_apply(template, names, leaves_l, expert_in)
        # global_gather equivalent: send results back to token owners
        expert_out = jax.lax.all_to_all(
            expert_out, ep_axis, split_axis=1, concat_axis=0, tiled=True)
        out = combine_from_experts(expert_out, ei, si, keep, w)
        return out, jax.lax.pmean(aux, ep_axis)

    shmap = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(ep_axis), P(ep_axis))
        + tuple(P(ep_axis) for _ in stacked_leaves),
        out_specs=(P(ep_axis), P()),
        axis_names={ep_axis},
        check_vma=False)
    return shmap(x, logits, *stacked_leaves)


class MoELayer(Layer):
    """Reference-parity MoELayer (moe_layer.py:119). `experts` is a list
    of structurally identical Layers (one per expert, reference-style);
    forward stacks their params on a leading expert dim (a taped `stack`,
    so eager autograd reaches every expert) and — when `moe_group`
    carries a mesh axis — executes expert-parallel via shard_map +
    all_to_all.

    Usage::

        experts = [ExpertMLP(d) for _ in range(E)]
        moe = MoELayer(d_model, experts, gate=nn.Linear(d, E),
                       moe_group=group_with_ep_axis, top_k=2)
        y = moe(x)                       # [B, S, d] or [T, d]
        loss = task_loss + 0.01 * moe.l_aux
    """

    def __init__(self, d_model, experts, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, top_k=2,
                 capacity_factor=1.25):
        super().__init__()
        self.d_model = d_model
        self.experts = list(experts)
        self.num_experts = len(self.experts)
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.gate = gate
        self.l_aux = None

        self._axis = getattr(moe_group, "axis_name", None)
        self._mesh = getattr(moe_group, "mesh", None)

        assert all(isinstance(e, Layer) for e in self.experts)
        names = sorted(params_dict(self.experts[0]))
        for e in self.experts[1:]:
            assert sorted(params_dict(e)) == names, \
                "experts must be structurally identical"
        self._names = tuple(names)
        for i, e in enumerate(self.experts):
            self.add_sublayer(f"expert_{i}", e)
        if gate is not None:
            self.add_sublayer("gate_layer", gate)

    def forward(self, x):
        from ..... import ops as _ops

        shape = x.shape
        flat = x.reshape([-1, shape[-1]])
        if self.gate is not None:
            logits = self.gate(flat)
        else:
            raise ValueError("MoELayer needs a gate layer")
        per_expert = [dict(e.named_parameters()) for e in self.experts]
        stacked = [
            _ops.manipulation.stack([pe[n] for pe in per_expert], axis=0)
            for n in self._names
        ]
        out, aux = _moe_sparse_op(
            flat, logits, *stacked, names=self._names, top_k=self.top_k,
            capacity_factor=self.capacity_factor, ep_axis=self._axis,
            mesh=self._mesh, template=self.experts[0])
        self.l_aux = aux
        return out.reshape(shape)
