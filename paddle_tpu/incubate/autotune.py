"""paddle.incubate.autotune — kernel/layout/dataloader tuning config.

Reference: python/paddle/incubate/autotune.py:24 (set_config with kernel /
layout / dataloader sections; the kernel section drives cuDNN exhaustive
algorithm search, phi/kernels/autotune/).

TPU-native mapping (each honest, not a silent no-op):

* kernel: XLA's autotuner always runs at compile time (it IS the
  exhaustive-search cache the reference builds at step time). Enabling the
  section additionally turns on jax's persistent compilation cache so the
  tuned executables survive process restarts — the durable analog of the
  reference's algorithm cache.
* layout: XLA chooses layouts during compilation; nothing to toggle. The
  setting is recorded and readable.
* dataloader: sets the default ``num_workers`` hint that ``paddle.io``'s
  DataLoader uses when constructed with ``num_workers=0`` and tuning is on.
"""

from __future__ import annotations

import json
import os

_CONFIG = {
    "kernel": {"enable": False, "tuning_range": [1, 10]},
    "layout": {"enable": False},
    "dataloader": {"enable": False, "num_workers": None},
}

__all__ = ["set_config", "get_config"]


def set_config(config=None):
    """reference autotune.py:24 — dict or path to a json file."""
    if config is None:
        for section in _CONFIG.values():
            section["enable"] = True
        _apply()
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    for key, val in config.items():
        if key not in _CONFIG:
            raise ValueError(f"unknown autotune section {key!r}; "
                             f"expected one of {sorted(_CONFIG)}")
        _CONFIG[key].update(val)
    _apply()


def get_config():
    return {k: dict(v) for k, v in _CONFIG.items()}


def _apply():
    if _CONFIG["kernel"]["enable"]:
        import jax

        cache_dir = os.environ.get(
            "PT_COMPILE_CACHE", os.path.expanduser("~/.paddle_tpu_xla_cache"))
        os.makedirs(cache_dir, exist_ok=True)
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.5)
        except Exception:
            pass  # older jax without the persistent cache config


def tuned_num_workers():
    """DataLoader hint (None = tuning off or unset)."""
    if not _CONFIG["dataloader"]["enable"]:
        return None
    n = _CONFIG["dataloader"]["num_workers"]
    return n if n is not None else min(4, os.cpu_count() or 1)
