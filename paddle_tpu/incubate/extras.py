"""incubate long-tail: LookAhead/ModelAverage optimizers, fused masked
softmax, graph-op aliases, segment reductions, identity_loss.

Reference sites: python/paddle/incubate/optimizer/lookahead.py:30,
modelaverage.py:29, operators/softmax_mask_fuse.py,
softmax_mask_fuse_upper_triangle.py, operators/graph_*.py,
tensor/math.py segment_*, paddle/fluid/operators identity_loss.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import op

__all__ = [
    "LookAhead", "ModelAverage", "softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle", "graph_send_recv",
    "graph_khop_sampler", "graph_sample_neighbors", "graph_reindex",
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "identity_loss",
]

# segment reductions are first-class in geometric; incubate re-exports the
# same ops (the reference grew them in incubate first, then promoted)
from ..geometric import (  # noqa: E402,F401
    segment_max, segment_mean, segment_min, segment_sum,
)


@op("softmax_mask_fuse")
def _softmax_mask_fuse(x, mask):
    import jax

    return jax.nn.softmax(x.astype(jnp.float32) + mask.astype(jnp.float32),
                          axis=-1).astype(x.dtype)


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fused op (reference
    operators/softmax_mask_fuse.py over the fused CUDA kernel; XLA fuses
    the add into the softmax automatically — the API exists for parity)."""
    return _softmax_mask_fuse(x, mask)


@op("softmax_mask_fuse_upper_triangle")
def _softmax_mask_fuse_upper_triangle(x):
    import jax

    s = x.shape[-1]
    causal = jnp.tril(jnp.ones((x.shape[-2], s), bool), k=s - x.shape[-2])
    logits = jnp.where(causal, x.astype(jnp.float32), -1e30)
    return jax.nn.softmax(logits, axis=-1).astype(x.dtype)


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax (reference
    operators/softmax_mask_fuse_upper_triangle.py)."""
    return _softmax_mask_fuse_upper_triangle(x)


def graph_send_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                    name=None):
    """Alias of geometric.send_u_recv (the reference kept the incubate
    name; operators/graph_send_recv.py)."""
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=reduce_op,
                       out_size=out_size)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    from ..geometric import sample_neighbors

    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size, eids=eids,
                            return_eids=return_eids)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    from ..geometric import reindex_graph

    return reindex_graph(x, neighbors, count)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop sampling (reference operators/graph_khop_sampler.py):
    chain sample_neighbors per hop, then reindex the union subgraph.
    Returns (edge_src, edge_dst, sample_index, reindex_nodes)."""
    import numpy as np

    from ..core.tensor import Tensor
    from ..geometric import reindex_graph, sample_neighbors

    cur = input_nodes
    all_nb, all_cnt = [], []
    seeds = [np.asarray(input_nodes.numpy())]
    for k in sample_sizes:
        nb, cnt = sample_neighbors(row, colptr, cur, sample_size=int(k))
        all_nb.append(np.asarray(nb.numpy()))
        all_cnt.append((cur, cnt))
        cur = Tensor(np.unique(np.asarray(nb.numpy())))
        seeds.append(np.asarray(cur.numpy()))
    # flatten hops into one edge list rooted at the original nodes
    nbs = np.concatenate(all_nb) if all_nb else np.zeros(0, np.int64)
    cnts = np.concatenate([np.asarray(c.numpy()) for _, c in all_cnt]) \
        if all_cnt else np.zeros(0, np.int64)
    srcs_nodes = np.concatenate([np.asarray(n.numpy())
                                 for n, _ in all_cnt]) \
        if all_cnt else np.zeros(0, np.int64)
    src, dst, nodes = reindex_graph(Tensor(srcs_nodes), Tensor(nbs),
                                    Tensor(cnts))
    return src, dst, Tensor(np.unique(np.concatenate(seeds))), nodes


@op("identity_loss")
def _identity_loss(x, reduction=1):
    if reduction == 0:
        return jnp.sum(x)
    if reduction == 1:
        return jnp.mean(x)
    return x


def identity_loss(x, reduction="none"):
    """Reference identity_loss op (IPU training epilogue): marks x as the
    loss, optionally reducing. reduction: 'sum'|'mean'|'none' or 0|1|2."""
    codes = {"sum": 0, "mean": 1, "none": 2}
    r = codes.get(reduction, reduction)
    return _identity_loss(x, reduction=int(r))


class LookAhead:
    """reference incubate/optimizer/lookahead.py:30 — fast weights step
    with the inner optimizer every call; every k steps the slow weights
    pull toward the fast ones and the fast weights reset to them."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_count = 0
        self._slow = None

    def _params(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        if self._slow is None:
            # copy: the inner optimizers donate param buffers on update,
            # which would delete aliased views of the old values
            self._slow = [jnp.copy(p._data) for p in self._params()]
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            new_slow = []
            for p, slow in zip(self._params(), self._slow):
                s = slow + self.alpha * (p._data.astype(slow.dtype) - slow)
                # rebind a distinct buffer: same-dtype astype is a no-copy
                # alias, and the next inner step donates p's buffer
                p._rebind(jnp.copy(s).astype(p._data.dtype))
                new_slow.append(s)
            self._slow = new_slow

    def clear_grad(self, *a, **k):
        self.inner_optimizer.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step_count
        return sd


class ModelAverage:
    """reference incubate/optimizer/modelaverage.py:29 — running average
    of parameters; ``apply()`` swaps averages in (optionally restoring)."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        assert parameters is not None
        self._params = list(parameters)
        self._sum = [p._data.astype(jnp.float32) * 0 for p in self._params]
        self._n = 0
        self._backup = None

    def step(self):
        self._sum = [s + p._data.astype(jnp.float32)
                     for s, p in zip(self._sum, self._params)]
        self._n += 1

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._backup = [jnp.copy(p._data) for p in self._params]
            n = max(self._n, 1)
            for p, s in zip(self._params, self._sum):
                p._rebind((s / n).astype(p._data.dtype))
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return ctx()

    def restore(self, executor=None):
        if self._backup is not None:
            for p, b in zip(self._params, self._backup):
                p._rebind(b)
            self._backup = None
