"""Fused training step: forward + backward + optimizer update in ONE
donated XLA executable.

TPU-native extension (no single reference counterpart — the reference's
equivalent is the fused CUDA optimizer kernels + multi-stream executor,
e.g. paddle/fluid/operators/fused/ and DistributedFusedLamb in
python/paddle/incubate/optimizer/). The eager path runs three dispatches
per step (to_static forward, backward, optimizer); this collapses them
into one jit with parameter/moment buffer donation, so weights are
updated in place in HBM and per-step dispatch overhead is one call.

Host–device overlap: loss, the finite flag and the bias-correction step
count are device-resident (threaded through the executable as one donated
accumulator), so nothing forces a device→host round-trip per step. The
``drive(loader, steps, log_every=...)`` multi-step driver exploits that:
batches stream through a ``paddle.io.DevicePrefetcher`` (H2D overlapped
with compute), dispatches queue back-to-back, and metrics are fetched
every ``log_every`` steps (``FLAGS_metric_fetch_interval``) — amortizing
the ~8–15 ms axon-tunnel sync PERF.md measured, with a trajectory
bit-identical to per-step fetch (skip-step semantics are in-graph).

Supported optimizers: SGD, Momentum, Adam, AdamW (the bench/optimizer
hot set). Learning-rate schedulers are honored by passing the current lr
as a traced scalar. ClipGradByGlobalNorm is fused in-graph when set on
the optimizer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..observability import metrics as _obs_metrics
from ..observability import trace as _obs_trace
from ..utils import functional_call, params_dict

__all__ = ["FusedTrainStep", "fused_train_step"]

# how long the train.stall chaos site blocks: long enough that either the
# in-process stall guard (FLAGS_step_timeout_s) or the launcher's heartbeat
# watchdog (FLAGS_worker_hang_timeout_s) must be the thing that ends it
_STALL_SLEEP_S = 3600.0

# drive() observability (ISSUE 10): every series is labeled by this step
# instance's stats name, recorded ONLY at window boundaries from values
# the host already holds — zero added host syncs (the A/B in
# tests/test_observability.py asserts host_syncs and losses bit-identical
# with observability on vs off). The guard gauges are the registry mirror
# behind guard_stats()' backward-compatible dict.
_M_TRAIN_STEPS = _obs_metrics.counter(
    "train_steps_total", "fused train steps dispatched through drive()")
_M_TRAIN_SKIPPED = _obs_metrics.counter(
    "train_skipped_steps_total",
    "updates discarded in-graph for non-finite loss/grads")
_M_TRAIN_ROLLBACKS = _obs_metrics.counter(
    "train_rollbacks_total", "divergence-sentinel rollbacks performed")
_H_WINDOW_S = _obs_metrics.histogram(
    "train_window_seconds", "wall time of one metric-fetch window",
    buckets=_obs_metrics.DEFAULT_SECONDS_BUCKETS)
_G_ITEMS_PER_S = _obs_metrics.gauge(
    "train_items_per_sec",
    "tokens-or-examples/s over the last recorded window (tokens when the "
    "leading input is 2-D integer ids, else leading-dim examples)")
_G_GUARD = {
    "total": _obs_metrics.gauge(
        "train_guard_total", "steps dispatched through the anomaly guard"),
    "skipped": _obs_metrics.gauge(
        "train_guard_skipped", "guard-discarded steps (host mirror)"),
    "consecutive_skips": _obs_metrics.gauge(
        "train_guard_consecutive_skips", "current non-finite skip streak"),
    "warned": _obs_metrics.gauge(
        "train_guard_warned", "warn-mode non-finite events"),
}


def _f32(x):
    return x.astype(jnp.float32)


class FusedTrainStep:
    """``step_lr_scheduler=True`` (default) means the fused step OWNS
    scheduler stepping: it calls ``optimizer._learning_rate.step()`` once per
    invocation, and the caller must NOT also call ``lr_scheduler.step()`` in
    the training loop (that would advance the schedule twice per step). Pass
    ``step_lr_scheduler=False`` to keep the standard paddle pattern where the
    loop steps the scheduler itself.

    Checkpointing: while a FusedTrainStep trains, the moment buffers and
    bias-correction step live HERE (in-graph, donated), not in the wrapped
    optimizer's accumulators — so checkpoint the step object itself:
    ``CheckpointManager.save(step, model=model, optimizer=fused_step)`` and
    ``auto_resume(model, fused_step)`` (state_dict/set_state_dict are
    duck-type compatible, keyed by structured parameter names). Externally
    restored weights (any ``_rebind`` outside the step) are adopted on the
    next call."""

    _instance_count = 0

    def __init__(self, model, optimizer, loss_fn=None, step_lr_scheduler=True,
                 shape_buckets=None, bucket_args=None, grad_scaler=None,
                 plan=None):
        from ..jit.cache import BucketSpec

        from ..optimizer.optimizers import SGD, Adam, AdamW, Momentum

        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self._step_lr_scheduler = step_lr_scheduler
        # sharding plan (distributed.plan.Plan): parameters are committed
        # to their plan shardings IN PLACE before capture below, moments
        # take the plan's moment layout (zeroN dim-0 sharding), data
        # inputs are placed per the activation rules at dispatch, and the
        # step compiles through compile_step_with_plan — the ONE compile
        # layer shared with hapi fit and LLMEngine. plan=None keeps the
        # exact single-device program (same entry point, no fork).
        self._plan = plan
        if plan is not None:
            plan.apply_to_model(model)
        # step anomaly guard (FLAGS_check_nan_inf_action) + optional fused
        # dynamic loss scaling: with a grad_scaler the loss is scaled and the
        # grads unscaled in-graph (one executable, same as the reference's
        # check_finite_and_unscale fusion), the step OWNS scaler bookkeeping
        # (do not also call scaler.step/update in the loop), and a non-finite
        # step both skips the update and backs off the scale
        self._scaler = grad_scaler
        self._guard = {"total": 0, "skipped": 0, "consecutive_skips": 0,
                       "warned": 0}
        # pad-up shape buckets (paddle.jit semantics): data inputs are
        # zero-padded to the nearest registered boundary before dispatch so
        # a variable-length stream costs O(buckets) compiles, and the
        # compile/hit counters surface in paddle.jit.cache_stats().
        # bucket_args (positional indices / kw names) pins WHICH inputs pad;
        # default is the dominant-length rule — see paddle.jit.to_static.
        self._shape_buckets = BucketSpec.normalize(shape_buckets)
        self._bucket_args = (None if bucket_args is None
                             else frozenset(bucket_args))
        # per-instance stats row: each FusedTrainStep owns its own jax.jit
        # cache, so merging instances of one model class would both blur the
        # counters and false-trigger the recompile-cliff warning (9 steps
        # compiling once each is not a cliff)
        FusedTrainStep._instance_count += 1
        self._stats_name = (f"fused_train_step[{type(model).__name__}"
                            f"#{FusedTrainStep._instance_count}]")
        self._seen_sigs = set()
        self._names = sorted(params_dict(model))
        self._tensors = dict(model.named_parameters())
        # trainable params only (stop_gradient=True params stay frozen)
        self._names = [n for n in self._names
                       if n in self._tensors
                       and not self._tensors[n].stop_gradient]
        self._params = {n: self._tensors[n]._data for n in self._names}
        self._step_count = 0
        # device-resident step metrics, threaded through the executable as
        # one donated tuple: (bias-correction step count, running loss sum,
        # skipped-step count, window peak global grad norm). The step count
        # lives ON DEVICE — in protect mode it advances only on finite
        # steps IN-GRAPH — so a deferred metric fetch (drive/log_every) is
        # bit-identical to per-step fetch even across NaN-skipped windows.
        # The grad-norm peak feeds the divergence sentinel
        # (FLAGS_sentinel_grad_norm_ceiling) and is fetched/reset only at
        # window boundaries — zero per-step host syncs. self._step_count
        # stays as the host mirror for telemetry (synced at fetch
        # boundaries).
        self._acc = (jnp.float32(0.0), jnp.float32(0.0), jnp.float32(0.0),
                     jnp.float32(0.0))
        # divergence-rollback LR cooldown: a scale on top of the
        # optimizer's own schedule, multiplied by FLAGS_sentinel_lr_cooldown
        # at each sentinel rollback and persisted in state_dict
        self._lr_scale = 1.0
        self._scaler_fallback_warned = False
        # FLAGS_sentinel_action-created TrainingSentinel, cached across
        # drive() calls so budget/history/EMA accumulate over epochs
        self._flag_sentinel = None

        opt = optimizer
        if isinstance(opt, AdamW):
            self._kind = "adamw"
        elif isinstance(opt, Adam):
            self._kind = "adam"
        elif isinstance(opt, Momentum):
            self._kind = "momentum"
        elif isinstance(opt, SGD):
            self._kind = "sgd"
        else:
            raise TypeError(
                f"fused_train_step supports SGD/Momentum/Adam/AdamW, got "
                f"{type(opt).__name__}")
        # row-sparse lazy route (Adam/AdamW lazy_mode=True): embedding-table
        # params skip the dense vocab-sized gradient entirely — the lookup
        # is captured (ops/sparse_grad.py), its backward yields
        # (row_ids, row_grads) at batchxfields size, and the update is a
        # gather→update→scatter over touched rows only. Zero model-code
        # change: any SparseEmbedding / sparse nn.Embedding parameter
        # qualifies automatically.
        self._sparse_names = ()
        if self._kind in ("adam", "adamw") and \
                bool(getattr(opt, "_lazy_mode", False)):
            self._sparse_names = tuple(sorted(
                self._find_sparse_param_names(model)))

        if self._kind in ("adam", "adamw"):
            z = {n: jnp.zeros(self._params[n].shape, jnp.float32)
                 for n in self._names}
            self._m1 = z
            self._m2 = {n: jnp.zeros_like(v) for n, v in z.items()}
        elif self._kind == "momentum":
            self._m1 = {n: jnp.zeros(self._params[n].shape, jnp.float32)
                        for n in self._names}
            self._m2 = {}
        else:
            self._m1, self._m2 = {}, {}
        if plan is not None:
            # zeroN moment layout (dim-0 over the sharding axis when it
            # divides, else the param's own spec) — committed up front so
            # the first dispatch compiles for it
            self._m1 = {n: jax.device_put(
                v, plan.moment_sharding_for(n, v.shape))
                for n, v in self._m1.items()}
            self._m2 = {n: jax.device_put(
                v, plan.moment_sharding_for(n, v.shape))
                for n, v in self._m2.items()}

        if self._kind in ("adam", "adamw"):
            # per-param decoupled decay honoring apply_decay_param_fun
            base_wd = float(opt._wd_coeff())
            fun = getattr(opt, "_apply_decay_param_fun", None)
            self._wds = {
                n: (base_wd if fun is None or fun(self._tensors[n].name)
                    else 0.0)
                for n in self._names
            }
            ratio_fun = getattr(opt, "_lr_ratio", None)
            self._lr_ratios = {
                n: (float(ratio_fun(self._tensors[n]))
                    if ratio_fun is not None else 1.0)
                for n in self._names
            }
        else:
            # coupled-L2 coefficients (SGD/Momentum regularizer path)
            self._wds = {n: float(opt._weight_decay_value(self._tensors[n]))
                         for n in self._names}
            self._lr_ratios = {n: 1.0 for n in self._names}

        clip = getattr(opt, "_grad_clip", None)
        from ..nn.clip import ClipGradByGlobalNorm

        if clip is None:
            self._clip_norm = None
        elif isinstance(clip, ClipGradByGlobalNorm):
            self._clip_norm = float(clip.clip_norm)
        else:
            raise TypeError(
                f"fused_train_step fuses ClipGradByGlobalNorm only; the "
                f"optimizer has {type(clip).__name__} — use the eager step "
                "for other clip types")
        # guard mode is a static arg ("off": no finite check in the graph
        # at all, "flag": compute the all-finite flag only, "protect": flag
        # + skip-step select): flipping FLAGS_check_nan_inf_action between
        # modes mid-run costs one recompile, steady state costs none and
        # the guard-off path stays exactly the pre-guard program. The same
        # holds for track_gnorm (the sentinel's grad-norm ceiling): off
        # compiles out both the norm reduction (unless grad clipping
        # already pays it) and the peak update
        from ..distributed.plan import compile_step_with_plan

        # the one compile layer (ROADMAP item 3): plan=None lowers to the
        # identical plain jax.jit; a real plan lets GSPMD partition the
        # step from the committed param/moment/data placements (shard_map
        # regions for the sep attention collectives ride inside the trace).
        # out_shardings pin the updated params/moments to their DECLARED
        # layouts: without them GSPMD propagates the dp-sharded moment
        # layout into the new params, and after one donation round-trip a
        # zero1 plan silently creeps into a zero3 one.
        in_specs = out_specs = None
        if self._plan is not None:
            p_specs = {n: self._plan.spec_for(n, self._params[n].shape)
                       for n in self._params}
            m1_specs = {n: self._plan.moment_spec_for(n, self._m1[n].shape)
                        for n in self._m1}
            m2_specs = {n: self._plan.moment_spec_for(n, self._m2[n].shape)
                        for n in self._m2}
            # params/moments pinned on BOTH sides: inputs so GSPMD cannot
            # re-layout an uncommitted buffer away from its declared spec,
            # outputs so the donated round-trip hands back the same layout
            # (otherwise propagation leaks the dp moment sharding into the
            # new params and a zero1 plan creeps into zero3 — and the
            # donation aliaser rejects the input/output layout mismatch).
            # acc/lr/scale/data/kwdata stay None: committed data placement
            # (activation rules) already says everything the plan knows.
            in_specs = (p_specs, m1_specs, m2_specs,
                        None, None, None, None, None)
            out_specs = (None, None, None, p_specs, m1_specs, m2_specs)
        self._jitted = compile_step_with_plan(
            self._step_impl, self._plan, in_specs=in_specs,
            out_specs=out_specs,
            donate_argnums=(0, 1, 2, 3), static_argnums=(8, 9))

    def _find_sparse_param_names(self, model):
        """Trainable params that are embedding tables: the weights of
        ``distributed.ps.SparseEmbedding`` layers and of ``nn.Embedding``
        layers constructed with ``sparse=True`` (the reference's
        SelectedRows-gradient markers)."""
        from ..distributed.ps import SparseEmbedding
        from ..nn.layer.common import Embedding

        by_id = {id(self._tensors[n]): n for n in self._names}
        names = set()
        for _, sub in model.named_sublayers(include_self=True):
            if isinstance(sub, SparseEmbedding):
                w = sub.weight
            elif isinstance(sub, Embedding) and getattr(sub, "_sparse",
                                                        False):
                w = sub.weight
            else:
                continue
            n = by_id.get(id(w))
            if n is not None:
                names.add(n)
        return names

    # -- pure step ------------------------------------------------------
    def _loss(self, params, data, kwdata, scale):
        all_params = dict(params)
        # frozen params participate in forward with their current values
        for n, t in self._tensors.items():
            if n not in all_params:
                all_params[n] = t._data
        out = functional_call(self.model, all_params, *data, **kwdata)
        if self.loss_fn is not None:
            out = self.loss_fn(out)
        elif isinstance(out, (tuple, list)):
            out = out[0]
        return out * scale  # loss scaling fused in-graph (scale==1 => no-op)

    def _sparse_value_and_grad(self, params, data, kwdata, scale, sparse):
        """Differentiate the loss with embedding tables on the row-sparse
        path: the tables enter through ``stop_gradient`` and each captured
        lookup's rows ride a zeros ``[n_ids, dim]`` delta, so the backward
        emits per-occurrence row grads instead of a vocab-sized
        scatter-add. Returns ``(loss, dense_grads, sparse_grads)`` where
        ``sparse_grads[name] = (uniq_ids, row_grads, valid)`` — duplicate
        ids already segment-summed into unique slots at the static
        batchxfields bound (shapes stay bucket-stable for the jit cache)."""
        from ..ops import sparse_grad

        registry = {id(params[n]): n for n in sparse}
        # discovery: one abstract forward (jax.make_jaxpr — no FLOPs, no
        # executable, runs at trace time only) records each lookup's
        # flattened id count so the deltas exist before differentiation,
        # and yields the jaxpr for the lookup-only safety analysis
        with sparse_grad.capture(registry, "discover") as cap:
            closed = jax.make_jaxpr(
                lambda: self._loss(params, data, kwdata, scale))()
        # safety gate: a table consumed by anything other than the
        # capture's stop_gradient route (tied weights, direct matmul, a
        # cast that broke identity matching) would silently LOSE that
        # gradient on the row-sparse path — fall it back to dense
        safe = sparse_grad.lookup_only_tables(
            closed, {n: params[n] for n in sparse})
        unsafe = [n for n in sparse if n not in safe]
        if unsafe:
            import warnings

            warnings.warn(
                f"{self._stats_name}: sparse table(s) {sorted(unsafe)} are "
                "used outside embedding lookups in this loss (tied "
                "weights / direct reads) — taking the DENSE gradient path "
                "for them; lazy_mode row-sparse updates apply only to "
                "lookup-only tables", stacklevel=2)
            sparse = [n for n in sparse if n in safe]
            if not sparse:
                loss, grads = jax.value_and_grad(self._loss)(
                    params, data, kwdata, scale)
                return loss, grads, {}
            registry = {id(params[n]): n for n in sparse}
        sparse_set = set(sparse)
        deltas = {n: [jnp.zeros((k, params[n].shape[-1]), jnp.float32)
                      for k in cap.counts.get(n, [])] for n in sparse}
        dense_params = {n: v for n, v in params.items()
                        if n not in sparse_set}

        def loss_fn(dp, deltas_):
            full = dict(dp)
            for n in sparse:
                full[n] = params[n]
            with sparse_grad.capture(registry, "apply", deltas_) as c:
                out = self._loss(full, data, kwdata, scale)
                ids = {n: list(c.ids.get(n, [])) for n in sparse}
            return out, ids

        (loss, ids_rec), (dgrads, delta_grads) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(dense_params, deltas)
        sgrads = {}
        for n in sparse:
            chunks = ids_rec.get(n, [])
            if not chunks:
                # registered table the forward never looked up: no rows
                # touched, no update this step
                dim = params[n].shape[-1]
                sgrads[n] = (jnp.zeros((0,), jnp.int32),
                             jnp.zeros((0, dim), jnp.float32),
                             jnp.zeros((0,), jnp.bool_))
                continue
            ids_all = (chunks[0] if len(chunks) == 1
                       else jnp.concatenate(chunks))
            g_all = (delta_grads[n][0] if len(delta_grads[n]) == 1
                     else jnp.concatenate(delta_grads[n]))
            sgrads[n] = sparse_grad.segment_rows(ids_all, g_all,
                                                 combine="add")
        return loss, dgrads, sgrads

    def _step_impl(self, params, m1, m2, acc, lr, scale, data, kwdata,
                   guard, track_gnorm):
        step_prev, loss_sum, skips, gpeak = acc
        step = step_prev + 1.0  # bias-correction count for THIS step
        sparse = [n for n in self._sparse_names if n in params]
        if sparse:
            loss, grads, sgrads = self._sparse_value_and_grad(
                params, data, kwdata, scale, sparse)
        else:
            loss, grads = jax.value_and_grad(self._loss)(params, data,
                                                         kwdata, scale)
            sgrads = {}
        # unscale: grads of the scaled loss divided by scale are the true
        # grads (reference check_finite_and_unscale), and the finite check
        # runs post-unscale exactly like AmpScaler.unscale_
        inv = 1.0 / scale
        loss = loss * inv
        grads = jax.tree.map(lambda g: (_f32(g) * inv).astype(g.dtype),
                             grads)
        sgrads = {n: (ids, g * inv, valid)
                  for n, (ids, g, valid) in sgrads.items()}
        sgrad_leaves = [g for _, g, _ in sgrads.values()]
        if guard == "off":
            all_finite = jnp.bool_(True)  # constant: no reduction in-graph
        else:
            all_finite = jnp.all(jnp.isfinite(loss))
            for g in jax.tree.leaves(grads) + sgrad_leaves:
                all_finite = jnp.logical_and(all_finite,
                                             jnp.all(jnp.isfinite(g)))
        gnorm = None  # pre-clip global grad norm (the explosion signal)
        if self._clip_norm is not None or track_gnorm:
            # dead dedup slots hold zero rows, so the row-grad squares sum
            # to exactly the dense table-grad norm contribution
            gnorm = jnp.sqrt(sum(
                jnp.sum(_f32(g) ** 2)
                for g in jax.tree.leaves(grads) + sgrad_leaves))
        if self._clip_norm is not None:
            factor = jnp.minimum(1.0, self._clip_norm / (gnorm + 1e-12))
            grads = jax.tree.map(lambda g: (_f32(g) * factor).astype(g.dtype),
                                 grads)
            sgrads = {n: (ids, g * factor, valid)
                      for n, (ids, g, valid) in sgrads.items()}
        opt = self.optimizer
        kind = self._kind
        if kind in ("adam", "adamw"):
            b1 = jnp.float32(opt._beta1)
            b2 = jnp.float32(opt._beta2)
            eps = jnp.float32(opt._epsilon)
            b1p = jnp.power(b1, step)
            b2p = jnp.power(b2, step)

            def upd(p, g, m1_, m2_, wd, lr_ratio):
                gf, pf = _f32(g), _f32(p)
                if kind == "adam":
                    gf = gf + wd * pf
                m1n = b1 * m1_ + (1 - b1) * gf
                m2n = b2 * m2_ + (1 - b2) * gf * gf
                m1h = m1n / (1 - b1p)
                m2h = m2n / (1 - b2p)
                step_lr = lr * lr_ratio
                new = pf - step_lr * m1h / (jnp.sqrt(m2h) + eps)
                if kind == "adamw":
                    new = new - step_lr * wd * pf
                return new.astype(p.dtype), m1n, m2n

            out = {n: upd(params[n], grads[n], m1[n], m2[n],
                          self._wds[n], self._lr_ratios[n])
                   for n in params if n not in sgrads}
            new_p = {n: v[0] for n, v in out.items()}
            new_m1 = {n: v[1] for n, v in out.items()}
            new_m2 = {n: v[2] for n, v in out.items()}
            if sgrads:
                from ..optimizer.optimizers import lazy_adam_rows

                for n, (ids, row_g, valid) in sgrads.items():
                    # protect mode gates the scatter itself: a non-finite
                    # step masks every slot, and masked slots write back
                    # current values — the dense path's vocab-sized
                    # jnp.where select is never needed here
                    upd_mask = (jnp.logical_and(valid, all_finite)
                                if guard == "protect" else valid)
                    np_, nm1, nm2 = lazy_adam_rows(
                        params[n], m1[n], m2[n], ids, row_g, upd_mask,
                        lr, b1, b2, eps, b1p, b2p, kind,
                        jnp.float32(self._wds[n]),
                        jnp.float32(self._lr_ratios[n]))
                    new_p[n] = np_
                    new_m1[n] = nm1
                    new_m2[n] = nm2
        elif kind == "momentum":
            mu = jnp.float32(opt._momentum)

            def updm(p, g, v, wd):
                gf = _f32(g) + wd * _f32(p)
                vn = mu * v + gf
                return (_f32(p) - lr * vn).astype(p.dtype), vn

            out = {n: updm(params[n], grads[n], m1[n], self._wds[n])
                   for n in params}
            new_p = {n: v[0] for n, v in out.items()}
            new_m1 = {n: v[1] for n, v in out.items()}
            new_m2 = m2
        else:  # sgd
            new_p = {n: (_f32(params[n])
                         - lr * (_f32(grads[n])
                                 + self._wds[n] * _f32(params[n]))
                         ).astype(params[n].dtype)
                     for n in params}
            new_m1, new_m2 = m1, m2
        if guard == "protect":
            # skip-step semantics: a non-finite step leaves params AND
            # moments untouched (one jnp.where per buffer — XLA fuses the
            # select into the update, no extra memory traffic), and the
            # bias-correction count does not advance — all in-graph, so no
            # host fetch is needed for the discard to be correct
            def keep(new, old):
                # sparse-route entries were already gated at scatter time
                # (upd_mask) — a vocab-sized select here would reintroduce
                # the full-table traffic the lazy path removes
                return {n: (new[n] if n in sgrads
                            else jnp.where(all_finite, new[n], old[n]))
                        for n in new}

            new_p = keep(new_p, params)
            new_m1 = keep(new_m1, m1) if new_m1 is not m1 else m1
            new_m2 = keep(new_m2, m2) if new_m2 is not m2 else m2
            new_step = jnp.where(all_finite, step, step_prev)
            new_skips = skips + jnp.where(all_finite, 0.0, 1.0)
            # a skipped step must not poison the running loss sum with NaN
            loss_inc = jnp.where(all_finite, _f32(loss), 0.0)
        else:
            new_step = step
            new_skips = skips
            loss_inc = _f32(loss)
        if track_gnorm:
            # window peak; a non-finite norm is the NaN guard's domain,
            # not the sentinel's ceiling — excluded so a skipped NaN step
            # cannot wedge the peak at inf/NaN for the rest of the window
            new_gpeak = jnp.maximum(gpeak, jnp.where(
                jnp.isfinite(gnorm), _f32(gnorm), 0.0))
        else:
            new_gpeak = gpeak
        new_acc = (new_step, loss_sum + loss_inc, new_skips, new_gpeak)
        return loss, all_finite, new_acc, new_p, new_m1, new_m2

    # -- public ---------------------------------------------------------
    def _lower(self, *data, **kwdata):
        """Lower (but do not run) the fused executable for these inputs —
        guard off, gnorm tracking off: the plain steady-state program.
        When the step already compiled for these shapes, ``.compile()`` on
        the result is a cache hit, not a second compile."""
        darrs, karrs = self._prepare_arrays(data, kwdata, record=False)
        return self._jitted.lower(
            self._params, self._m1, self._m2,
            (jnp.float32(0), jnp.float32(0), jnp.float32(0),
             jnp.float32(0)),
            jnp.float32(1e-3), jnp.float32(1), darrs, karrs, "off",
            False)

    def lowered_flops(self, *data, **kwdata):
        """FLOPs of one full fused step (forward + backward + update) from
        XLA's HLO cost analysis on the lowered program — self-measured, no
        hand-derived formula. Returns None when the backend provides no
        estimate. Used by bench.py for MFU accounting."""
        try:
            lowered = self._lower(*data, **kwdata)
            cost = lowered.cost_analysis()
            if not (hasattr(cost, "get") and cost.get("flops")):
                # some backends only report cost post-compile
                cost = lowered.compile().cost_analysis()
            flops = cost.get("flops") if hasattr(cost, "get") else None
            return float(flops) if flops and flops > 0 else None
        except Exception:
            return None

    def hlo_cost_report(self, *data, top_n=None, **kwdata):
        """Per-op cost ledger of this step's OPTIMIZED HLO for the given
        inputs: each entry-computation op with its bytes accessed (result
        + operands — a fusion's external traffic) and estimated FLOPs,
        ranked by bytes. See ``paddle.jit.hlo_audit`` for the method and
        ``scripts/audit_hlo.py`` for the per-workload reports."""
        from ..jit import hlo_audit

        compiled = self._lower(*data, **kwdata).compile()
        return hlo_audit.audit(compiled, top_n=top_n)

    def _prepare_arrays(self, data, kwdata, record=True):
        """Unwrap call inputs to jax arrays, padding each up to its shape
        bucket when buckets are registered (per-step or global).
        ``record=False`` keeps estimation-only callers (lowered_flops) out
        of the dispatch telemetry."""
        from ..jit import cache as jit_cache

        darrs = tuple(d._data if isinstance(d, Tensor) else jnp.asarray(d)
                      for d in data)
        karrs = {k: (v._data if isinstance(v, Tensor) else jnp.asarray(v))
                 for k, v in kwdata.items()}
        spec = (self._shape_buckets if self._shape_buckets is not None
                else jit_cache.get_shape_buckets())
        if spec is not None:
            # selection: bucket_args pins the padded inputs explicitly;
            # otherwise the dominant-length rule (jit_cache
            # .infer_call_lengths) — the first input carrying the bucketed
            # axis defines the call's length and only matching inputs pad,
            # so [B, 1] labels / [B, n_features] dense vectors pass through
            # instead of gaining fabricated zeros. Use bucket_args when a
            # fixed field's width can coincide with a sequence length.
            sel = self._bucket_args
            lengths = (jit_cache.infer_call_lengths(
                list(darrs) + list(karrs.values()), spec)
                if sel is None else None)
            n_pad = 0
            padded = []
            for i, a in enumerate(darrs):
                if sel is None or i in sel:
                    a, p = jit_cache.pad_array_to_bucket(a, spec, lengths)
                    n_pad += p
                padded.append(a)
            darrs = tuple(padded)
            for k, a in karrs.items():
                if sel is None or k in sel:
                    a, p = jit_cache.pad_array_to_bucket(a, spec, lengths)
                    n_pad += p
                    karrs[k] = a
            if record:
                jit_cache.record_bucket_pads(self._stats_name, n_pad)
        if self._plan is not None:
            # activation rules: commit each data input to its plan
            # sharding (batch over dp, seq over sep, ...) so GSPMD sees
            # the intended layout instead of inferring replication
            darrs = tuple(self._plan.place_data(a) for a in darrs)
            karrs = {k: self._plan.place_data(a) for k, a in karrs.items()}
        return darrs, karrs

    def _count_dispatch(self, darrs, karrs):
        """Compile-vs-hit telemetry: a shape signature not seen before means
        jax.jit traces + XLA-compiles a fresh executable this dispatch."""
        from ..jit import cache as jit_cache

        sig = jit_cache.shape_signature(
            list(darrs) + [karrs[k] for k in sorted(karrs)])
        if sig in self._seen_sigs:
            jit_cache.record_hit(self._stats_name)
        else:
            self._seen_sigs.add(sig)
            jit_cache.record_compile(self._stats_name, sig)

    def state_dict(self):
        """Checkpointable state of the fused step: the in-graph moment
        buffers, the bias-correction step count and the sentinel's LR
        cooldown scale (weights live in the model; this object is the
        optimizer-state owner while it trains). Duck-type-compatible with
        ``CheckpointManager.save(optimizer=...)`` /
        ``auto_resume(optimizer=...)``."""
        import numpy as np

        # the authoritative step count is the device accumulator (the host
        # mirror can lag inside a deferred-fetch window) — guard_stats
        # (sync=True) flushes the host mirrors from it in one host sync
        # here, at the checkpoint boundary, so checkpoint-time telemetry
        # is as authoritative as the checkpoint itself
        self.guard_stats(sync=True)
        sd = {"step_count": self._step_count,
              "lr_scale": float(self._lr_scale)}
        # the LR scheduler advanced once per dispatched step; without its
        # state a restore (crash-resume OR divergence rollback) would
        # resume the schedule N steps ahead of the restored trajectory
        sched = getattr(self.optimizer, "_learning_rate", None)
        if hasattr(sched, "state_dict"):
            sd["lr_sched"] = sched.state_dict()
        for prefix, store in (("m1", self._m1), ("m2", self._m2)):
            for n, v in store.items():
                sd[f"{prefix}.{n}"] = np.asarray(v)
        return sd

    def set_state_dict(self, sd):
        self._step_count = int(sd.get("step_count", self._step_count))
        self._lr_scale = float(sd.get("lr_scale", 1.0))
        sched = getattr(self.optimizer, "_learning_rate", None)
        if "lr_sched" in sd and hasattr(sched, "set_state_dict"):
            sched.set_state_dict(sd["lr_sched"])
        self._acc = (jnp.float32(self._step_count), self._acc[1],
                     self._acc[2], self._acc[3])
        for prefix, store in (("m1", self._m1), ("m2", self._m2)):
            for n in store:
                key = f"{prefix}.{n}"
                if key in sd:
                    v = sd[key]
                    arr = jnp.asarray(
                        v._data if isinstance(v, Tensor) else v)
                    if self._plan is not None:
                        arr = jax.device_put(
                            arr,
                            self._plan.moment_sharding_for(n, arr.shape))
                    store[n] = arr

    load_state_dict = set_state_dict

    @property
    def plan(self):
        """The sharding Plan this step compiles under (None on the
        single-device path)."""
        return self._plan

    def _adopt_external_rebinds(self):
        """A checkpoint resume (``CheckpointManager.auto_resume`` /
        ``set_state_dict``) rebinds the model's parameter Tensors outside
        this step's control; detect that (pointer comparison per param) and
        adopt the new arrays, else the next dispatch would clobber the
        restored weights with this step's stale internal copies."""
        for n in self._names:
            t = self._tensors[n]._data
            if t is not self._params[n]:
                if self._plan is not None:
                    # a restore loads host arrays; re-commit to the plan
                    # layout or the next dispatch would compile/reshard
                    # for a replicated input
                    t = jax.device_put(
                        t, self._plan.sharding_for(n, t.shape))
                    self._tensors[n]._rebind(t)
                self._params[n] = t

    def device_metrics(self):
        """The device-resident accumulator, fetched in ONE host sync:
        ``{"step_count", "loss_sum", "skipped", "gnorm_peak"}``.
        ``loss_sum`` is the running sum of applied per-step losses
        (non-finite skipped steps excluded in protect mode), ``skipped``
        counts in-graph discards, ``gnorm_peak`` the peak global grad norm
        since the last window reset (0.0 unless the sentinel's grad-norm
        tracking is armed). Authoritative at any time — including inside a
        deferred-fetch window, where the host mirrors (``guard_stats``)
        lag until the next boundary or an explicit
        ``guard_stats(sync=True)``."""
        import numpy as np

        vals = np.asarray(jnp.stack([jnp.asarray(a, jnp.float32)
                                     for a in self._acc]))
        return {"step_count": int(vals[0]), "loss_sum": float(vals[1]),
                "skipped": int(vals[2]), "gnorm_peak": float(vals[3])}

    def guard_stats(self, sync=False):
        """Step-anomaly-guard counters: ``total`` dispatched steps,
        ``skipped`` updates discarded for non-finite loss/grads,
        ``consecutive_skips`` current streak (a growing streak means the run
        is in a NaN spiral, not a one-off overflow), ``warned`` warn-mode
        events.

        Inside a deferred-fetch window (``drive``) the host mirrors lag
        the device until the next boundary replays the bookkeeping;
        ``sync=True`` flushes them NOW from the authoritative device
        accumulator (one host sync — ``step_count``/``skipped`` become
        exact; ``consecutive_skips`` is inherently boundary-resolution and
        is left untouched). ``state_dict`` uses this, so checkpoint-time
        stats are authoritative."""
        if sync:
            dm = self.device_metrics()
            self._step_count = dm["step_count"]
            self._guard["skipped"] = dm["skipped"]
        self._publish_guard_metrics()
        return dict(self._guard)

    def _publish_guard_metrics(self):
        """Mirror the guard's host counters into the registry
        (``train_guard_*{instance=...}``) — guard_stats() keeps its dict
        shape, the registry carries the same numbers for scraping."""
        for k, g in _G_GUARD.items():
            g.set(self._guard[k], instance=self._stats_name)

    @staticmethod
    def _batch_items(args, kw):
        """Items one batch contributes to the throughput gauge: tokens
        (rows x length) when the leading input is a 2-D integer array
        (token ids), else leading-dim examples. A heuristic, stated as
        one — the gauge is `train_items_per_sec`, not a benchmark."""
        for x in list(args) + list(kw.values()):
            arr = x._data if isinstance(x, Tensor) else x
            shape = getattr(arr, "shape", None)
            if shape is None or len(shape) == 0:
                continue
            if len(shape) == 2 and jnp.issubdtype(arr.dtype, jnp.integer):
                return int(shape[0]) * int(shape[1])
            return int(shape[0])
        return 1

    def _record_window_obs(self, obs_state, n_steps, n_bad, t_end):
        """Accumulate one flushed window into the pending observability
        state and publish at the ``metrics_every`` cadence. Pure host
        arithmetic over values already fetched — never a device sync."""
        every = obs_state["every"]
        if every == 0:
            return
        obs_state["steps"] += n_steps
        obs_state["bad"] += n_bad
        if every is not None and obs_state["steps"] < every:
            return
        self._publish_window_obs(obs_state, t_end)

    def _publish_window_obs(self, obs_state, t_end):
        """Publish the pending accumulation. Also called once at drive
        exit with whatever remains: a `*_total` counter that silently
        dropped the trailing sub-``metrics_every`` window would
        undercount every drive whose step count is not a multiple."""
        if obs_state["every"] == 0 or not obs_state["steps"]:
            return
        wall = max(t_end - obs_state["t0"], 1e-9)
        inst = self._stats_name
        _M_TRAIN_STEPS.inc(obs_state["steps"], instance=inst)
        if obs_state["bad"]:
            _M_TRAIN_SKIPPED.inc(obs_state["bad"], instance=inst)
        _H_WINDOW_S.observe(wall, instance=inst)
        if obs_state["items_per_step"]:
            _G_ITEMS_PER_S.set(
                obs_state["items_per_step"] * obs_state["steps"] / wall,
                instance=inst)
        self._publish_guard_metrics()
        obs_state["steps"] = 0
        obs_state["bad"] = 0
        obs_state["t0"] = t_end

    @staticmethod
    def _poison_first_float(darrs, karrs, fn):
        """Apply ``fn`` to the first floating-point call input (shape/
        dtype signature unchanged — no recompile). Shared walker for the
        input-poisoning fault sites."""
        darrs = list(darrs)
        for i, a in enumerate(darrs):
            if jnp.issubdtype(a.dtype, jnp.inexact):
                darrs[i] = fn(a)
                return tuple(darrs), karrs
        for k in sorted(karrs):
            if jnp.issubdtype(karrs[k].dtype, jnp.inexact):
                karrs = dict(karrs)
                karrs[k] = fn(karrs[k])
                return tuple(darrs), karrs
        return tuple(darrs), karrs

    def _poison_nan(self, darrs, karrs):
        """train.grad_nan injection: NaN-fill the first floating-point
        input so loss/grads go non-finite this step."""
        return self._poison_first_float(
            darrs, karrs, lambda a: jnp.full_like(a, jnp.nan))

    _SPIKE_SCALE = 1e3

    def _poison_spike(self, darrs, karrs):
        """train.spike injection: scale the first floating-point input by
        1e3 so loss/grads go finite-but-huge — the NaN guard stays silent
        and only the divergence sentinel can catch it."""
        return self._poison_first_float(
            darrs, karrs,
            lambda a: a * jnp.asarray(self._SPIKE_SCALE, a.dtype))

    def _dispatch(self, data, kwdata, guard, scale_val, track_gnorm=False):
        """One asynchronous dispatch of the fused executable: prepare and
        bucket-pad inputs, fire, rebind donated buffers. Returns the lazy
        (loss, finite) device values — NO host sync happens here; that is
        the caller's choice (per-step in ``__call__``, per-window in
        ``drive``)."""
        from ..utils import fault_injection

        lr = jnp.float32(self.optimizer.get_lr() * self._lr_scale)
        self._adopt_external_rebinds()
        darrs, karrs = self._prepare_arrays(data, kwdata)
        if fault_injection.should_fire("train.grad_nan"):
            darrs, karrs = self._poison_nan(darrs, karrs)
        if fault_injection.should_fire("train.spike"):
            darrs, karrs = self._poison_spike(darrs, karrs)
        self._count_dispatch(darrs, karrs)
        loss, finite, self._acc, self._params, self._m1, self._m2 = \
            self._jitted(self._params, self._m1, self._m2, self._acc, lr,
                         jnp.float32(scale_val), darrs, karrs, guard,
                         track_gnorm)
        # donation invalidated the old buffers — rebind the live Tensors
        for n in self._names:
            self._tensors[n]._rebind(self._params[n])
        return loss, finite

    def __call__(self, *data, **kwdata):
        from ..core.flags import flag_value

        self._step_count += 1
        self._guard["total"] += 1
        action = str(flag_value("check_nan_inf_action", "none"))
        # a disabled scaler (GradScaler(enable=False)) must behave exactly
        # like no scaler: no host sync, no silent skip semantics
        scaler = (self._scaler if self._scaler is not None
                  and self._scaler.is_enable() else None)
        # guard host-syncs the finite flag when an action wants it or a
        # scaler needs the signal; "protect" discards non-finite updates
        # in-graph (always on with a scaler: GradScaler.step semantics);
        # "off" compiles the guard out entirely
        guard_active = action != "none" or scaler is not None
        protect = scaler is not None or action in ("skip", "raise")
        guard = "protect" if protect else ("flag" if guard_active else "off")
        scale_val = 1.0 if scaler is None else float(scaler._scale)
        loss, finite = self._dispatch(data, kwdata, guard, scale_val)
        skipped = False
        if guard_active:
            ok = bool(finite)  # the guard's single host sync
            if not ok:
                if action == "warn":
                    import warnings

                    self._guard["warned"] += 1
                    warnings.warn(
                        f"non-finite loss/grads at step {self._step_count}"
                        + ("" if protect else " — update applied anyway "
                           "(FLAGS_check_nan_inf_action=warn)"),
                        stacklevel=2)
                if protect:
                    skipped = True
                    self._guard["skipped"] += 1
                    self._guard["consecutive_skips"] += 1
                    # the discarded step must not advance bias correction
                    self._step_count -= 1
                if scaler is not None:
                    # found_inf -> dynamic backoff (scale decays, good-step
                    # streak resets), mirroring scaler.update() after a
                    # skipped scaler.step()
                    scaler._found_inf = True
                    scaler.update()
                if action == "raise":
                    raise FloatingPointError(
                        f"non-finite loss/grads at step "
                        f"{self._step_count + 1}; update discarded "
                        "(FLAGS_check_nan_inf_action=raise)")
            else:
                self._guard["consecutive_skips"] = 0
                if scaler is not None:
                    scaler._found_inf = False
                    scaler.update()  # good-step bookkeeping (may grow scale)
        if self._step_lr_scheduler and not skipped:
            sched = getattr(self.optimizer, "_learning_rate", None)
            if hasattr(sched, "step"):
                sched.step()
        return Tensor._wrap(loss)

    # -- multi-step driver ----------------------------------------------
    @staticmethod
    def _call_form(batch):
        """A loader batch as this step's call arguments: tuples/lists are
        positional, dicts travel by keyword, anything else is one arg."""
        if isinstance(batch, dict):
            return (), batch
        if isinstance(batch, (list, tuple)):
            return tuple(batch), {}
        return (batch,), {}

    def drive(self, data, steps=None, log_every=None, prefetch=None,
              prefetch_depth=None, on_window=None, checkpoint=None,
              sampler=None, heartbeat=True, handle_preemption=True,
              sentinel=None, metrics_every=None):
        """Multi-step driver: dispatch fused steps back-to-back with NO
        per-step host sync, so the device executable queue stays deep while
        the input side is double-buffered by a :class:`DevicePrefetcher`.

        Per step the host does only: pull a staged batch, dispatch, enqueue
        the lazy (loss, finite) handles. Every ``log_every`` steps
        (default ``FLAGS_metric_fetch_interval``) the window is fetched in
        O(1) host round-trips — one ``jnp.stack`` of the window losses (+
        one of the finite flags when the guard is armed) — and the guard's
        host bookkeeping (warn/skip counters, ``raise``) is replayed.
        Skip-step semantics need no host involvement at all: a non-finite
        step's update AND its bias-correction advance are discarded
        in-graph, so the deferred trajectory is bit-identical to per-step
        fetch.

        ``data`` is any batch iterable (DataLoader, list of batches, or an
        existing DevicePrefetcher). ``prefetch=False`` disables the
        wrapping; by default batches are staged through a prefetcher that
        inherits this step's shape buckets / bucket_args so pre-padded
        shapes hit the same executables (zero extra compiles).

        Deferred-mode differences, stated honestly: an attached enabled
        GradScaler forces the per-step-fetch path (the scale for step N+1
        depends on step N's finite flag); an LR scheduler advances every
        step including ones later found non-finite (the skip signal is not
        on host until the boundary); ``action='raise'`` raises at the fetch
        boundary, with the offending updates already discarded in-graph.
        Checkpoint at fetch boundaries (e.g. from ``on_window``) —
        ``state_dict`` reads the authoritative device step count.

        Supervision (the elastic-launcher contract):

        - **Heartbeats** (``heartbeat=True``): when launched under
          ``paddle_tpu.distributed.launch`` (``PADDLE_HEARTBEAT_DIR``
          set), a heartbeat file is written at drive start and at every
          window boundary, feeding the launcher's hang watchdog
          (``FLAGS_worker_hang_timeout_s``). Unsupervised runs pay one
          env lookup.
        - **Graceful preemption** (``handle_preemption=True``): SIGTERM is
          trapped; the loop finishes the in-flight fetch window, writes a
          committed checkpoint through ``checkpoint`` (a
          ``CheckpointManager`` — saving this step's model, its own
          optimizer state, and ``sampler``'s stream cursor), then raises
          ``SystemExit(PREEMPT_EXIT_CODE)`` (123), which the launcher
          relaunches WITHOUT consuming restart budget. Stopping only at
          window boundaries keeps multi-process ranks checkpointing at the
          same global step (windows are step-aligned across ranks).
        - **Stall detection** (``FLAGS_step_timeout_s`` > 0): a wall-clock
          guard around the fetch points raises a typed
          :class:`~paddle_tpu.core.exceptions.TrainStallError` when a step
          wedges, so a dead collective becomes a restartable crash instead
          of an infinite block.
        - **Resumable data** (``sampler=``, or auto-detected from ``data``
          when ``checkpoint`` is given): each trained batch advances the
          sampler's consumed-batch cursor, so a checkpoint written at a
          window boundary (``on_window`` or the preemption save) resumes
          the *exact* remaining batch sequence — prefetch read-ahead never
          skews it.
        - **Divergence sentinel** (``FLAGS_sentinel_action`` != 'none', or
          an explicit ``sentinel=`` :class:`TrainingSentinel`): every
          fetched window is judged by the loss-spike / grad-explosion /
          trend detectors — a pure host computation over the values the
          deferred fetch brings over anyway, so arming it adds ZERO
          per-step host syncs. On a spike verdict the response ladder
          runs: ``warn`` (RuntimeWarning), ``skip`` (also drop the next
          window of batches — a contiguous poisoned input region),
          ``rollback`` (restore model + this step's optimizer state from
          ``checkpoint.latest_healthy_step()`` while the sampler cursor
          stays exactly where the spike left it — every batch consumed
          since the healthy step, the poisoned window included, is never
          replayed and the in-flight epoch keeps its recorded shuffle
          seed; reset the prefetcher's read-ahead, apply the
          ``FLAGS_sentinel_lr_cooldown`` scale, drop newer poisoned
          checkpoints, and continue — budgeted by a leaky bucket that
          raises :class:`TrainDivergenceError` on exhaustion), ``raise``
          (typed error at the first verdict).
          Health metadata: each clean window credits the checkpoints
          ``checkpoint`` has committed (``note_window``), so a step only
          becomes a rollback target ``FLAGS_sentinel_healthy_windows``
          clean windows after it was written. Multi-process runs
          cross-check the verdict through the jax.distributed
          coordination service before responding, so every rank rolls
          back identically (a disagreeing rank is a split brain and
          raises).

        **Observability** (``metrics_every=``, ISSUE 10): every window
        boundary records registry metrics (``train_steps_total``,
        ``train_skipped_steps_total``, ``train_window_seconds``,
        ``train_items_per_sec`` — see ``paddle.observability.metrics``)
        and, when the tracer is enabled, emits per-window spans
        (``train.window`` / ``train.dispatch`` / ``train.fetch`` /
        ``train.guard`` / ``train.sentinel`` / ``train.checkpoint``).
        Everything is host-side arithmetic over values the deferred fetch
        already brought over, so instrumentation adds ZERO host syncs and
        the loss trajectory is bit-identical with observability on or
        off. ``metrics_every=N`` thins the registry updates to boundaries
        at least ``N`` steps apart; ``0`` disables them for this drive;
        ``None`` (default) records every window.

        Returns ``{"steps", "loss" (per-step floats), "skipped",
        "windows", "host_syncs", "log_every", "deferred", "prefetch",
        "rollbacks", "skipped_windows", "sentinel"}`` (``sentinel`` is the
        sentinel's ``stats()`` snapshot, or None when unarmed). (A
        preempted drive never returns: it exits via
        ``SystemExit(PREEMPT_EXIT_CODE)`` after its checkpoint.)
        """
        from ..core.flags import flag_value
        from ..io.prefetch import DevicePrefetcher

        if log_every is None:
            log_every = int(flag_value("metric_fetch_interval", 10))
        log_every = max(1, int(log_every))
        # divergence sentinel: explicit instance wins; else armed from
        # FLAGS_sentinel_action. Detection rides the window fetch, so an
        # armed sentinel costs zero additional per-step host syncs. The
        # flag-created instance is CACHED on this step across drive()
        # calls — the epoch-loop pattern (one drive per epoch) must keep
        # accumulating the rollback budget, spike history and EMA
        # baseline, or the leaky-bucket loop breaker could never fire
        if sentinel is None:
            if str(flag_value("sentinel_action", "none")) != "none":
                from .sentinel import TrainingSentinel

                cached = getattr(self, "_flag_sentinel", None)
                if cached is None or cached.action != str(
                        flag_value("sentinel_action", "none")):
                    cached = TrainingSentinel()
                    self._flag_sentinel = cached
                sentinel = cached
        elif not sentinel.armed:
            sentinel = None
        rollback_armed = sentinel is not None and \
            sentinel.action == "rollback"
        stream = data
        made_prefetcher = None
        if prefetch is None:
            prefetch = not isinstance(data, DevicePrefetcher)
        if prefetch and not isinstance(data, DevicePrefetcher):
            import itertools

            # cap the SOURCE at steps too: otherwise the transfer thread
            # reads ahead of the cap and discards up to depth+1 batches a
            # one-shot iterator's owner still wanted. A rollback-armed
            # sentinel needs the source RE-ITERABLE from the restored
            # cursor instead (islice would pin one half-consumed pass),
            # so there the while-loop's own cap does the bounding
            source = (itertools.islice(iter(data), steps)
                      if steps is not None and not rollback_armed else data)
            made_prefetcher = DevicePrefetcher(
                source, depth=prefetch_depth,
                shape_buckets=self._shape_buckets,
                bucket_args=self._bucket_args,
                name=f"{self._stats_name}.prefetch")
            stream = made_prefetcher
        history = {"steps": 0, "loss": [], "skipped": 0, "windows": 0,
                   "host_syncs": 0, "log_every": log_every,
                   "deferred": True, "prefetch": None, "rollbacks": 0,
                   "skipped_windows": 0, "sentinel": None}
        # window observability state: metrics_every=None records every
        # boundary, N thins to >=N-step gaps, 0 disables for this drive.
        # When the registry itself is disabled, recording is a no-op by
        # construction (every mutate checks the registry switch).
        import time as _obs_time

        obs_state = {
            "every": (None if metrics_every is None
                      else max(0, int(metrics_every))),
            "steps": 0, "bad": 0, "items_per_step": None,
            "t0": _obs_time.perf_counter()}

        # resumable-stream cursor: only armed on the resume-enabled path
        # (an explicit sampler=, or a checkpoint manager to persist into) —
        # plain perf-driving loops keep their batch streams untouched
        resumable = None
        if sampler is not None or checkpoint is not None:
            from ..io import resolve_resumable

            resumable = resolve_resumable(
                sampler if sampler is not None else data)
            if sampler is not None and resumable is None:
                raise TypeError(
                    f"sampler={type(sampler).__name__} is not a resumable "
                    "stream: it must expose (or wrap something exposing) "
                    "state_dict/set_state_dict/advance")
        step_timeout = float(flag_value("step_timeout_s", 0) or 0)

        scaler = (self._scaler if self._scaler is not None
                  and self._scaler.is_enable() else None)
        if scaler is not None:
            # dynamic loss scaling consumes the finite flag every step —
            # fall back to the per-step path (prefetch still overlaps H2D)
            import os as _os
            import signal as _signal
            import time as _time

            import numpy as np

            from ..core.exceptions import stall_guard
            from ..distributed.launch import heartbeat as hb
            from ..jit import cache as jit_cache
            from ..utils import fault_injection

            history["deferred"] = False
            # degrade-once semantics (mirroring io.prefetch): say WHY the
            # deferred fetch is off exactly once per step instance, and
            # count every degraded drive in jit.cache_stats() so an A/B
            # bench can see the fallback without scraping warnings
            jit_cache.record_scaler_fallback(self._stats_name)
            if not self._scaler_fallback_warned:
                import warnings

                self._scaler_fallback_warned = True
                warnings.warn(
                    "FusedTrainStep.drive: an enabled GradScaler forces "
                    "per-step metric fetch (the scale for step N+1 "
                    "consumes step N's finite flag on host), so the "
                    "FLAGS_metric_fetch_interval deferred-window path is "
                    "inactive for this drive. Detach the scaler (or "
                    "construct it with enable=False) and use "
                    "FLAGS_check_nan_inf_action=skip to keep non-finite "
                    "protection with deferred fetch; see jit.cache_stats()"
                    f"['{self._stats_name}']['scaler_fallbacks']",
                    RuntimeWarning, stacklevel=2)
            skipped_before = self._guard["skipped"]
            win_start, win_skips = 0, self._guard["skipped"]
            win_start_ns = _obs_time.perf_counter_ns()
            it = iter(stream)

            def scaler_window_end(final=False):
                # on_window still fires at every log boundary (it is the
                # documented checkpoint hook), just with per-step-fetched
                # values instead of a deferred stack
                nonlocal win_start, win_skips, win_start_ns, it
                from .sentinel import make_window

                history["windows"] += 1
                n_steps = len(history["loss"]) - win_start
                n_bad = self._guard["skipped"] - win_skips
                win = make_window(
                    history["loss"][win_start:],
                    non_finite=n_bad,
                    step=history["steps"])
                now_ns = _obs_time.perf_counter_ns()
                _obs_trace.add_complete(
                    "train.window", win_start_ns, now_ns, cat="train",
                    args={"instance": self._stats_name, "steps": n_steps,
                          "non_finite": n_bad})
                win_start_ns = now_ns
                self._record_window_obs(obs_state, n_steps, n_bad,
                                        _obs_time.perf_counter())
                if on_window is not None:
                    with _obs_trace.span("train.checkpoint", cat="train",
                                         args={"instance":
                                               self._stats_name}):
                        on_window(win)
                win_start = len(history["loss"])
                win_skips = self._guard["skipped"]
                if heartbeat:
                    hb.write(step=self._step_count)
                if sentinel is not None:
                    # trailing window: no stream left to rewind/skip —
                    # pass it=None like the deferred path, so a rollback
                    # only restores state for the NEXT drive
                    with _obs_trace.span("train.sentinel", cat="train",
                                         args={"instance":
                                               self._stats_name}):
                        new_it = self._sentinel_check(
                            sentinel, win, history, checkpoint, resumable,
                            stream, None if final else it, log_every,
                            scaler=scaler)
                    if new_it is not None:
                        it = new_it

            with hb.trap_preemption(enable=handle_preemption) as preempt:
                if heartbeat:
                    hb.write(step=self._step_count)
                try:
                    while steps is None or history["steps"] < steps:
                        if (preempt.triggered
                                and len(history["loss"]) == win_start):
                            break  # window boundary: ranks stop aligned
                        if fault_injection.should_fire("proc.kill"):
                            _os.kill(_os.getpid(), _signal.SIGKILL)
                        try:
                            with stall_guard(step_timeout,
                                             f"batch fetch after step "
                                             f"{history['steps']}"):
                                if fault_injection.should_fire(
                                        "train.stall"):
                                    _time.sleep(_STALL_SLEEP_S)
                                batch = next(it)
                        except StopIteration:
                            break
                        args, kw = self._call_form(batch)
                        if obs_state["items_per_step"] is None:
                            obs_state["items_per_step"] = \
                                self._batch_items(args, kw)
                        loss = self(*args, **kw)
                        if resumable is not None:
                            resumable.advance(1)
                        history["steps"] += 1
                        with stall_guard(step_timeout, "loss fetch"):
                            history["loss"].append(float(loss.numpy()))
                        history["host_syncs"] += 2  # finite flag + loss
                        if history["steps"] % log_every == 0:
                            scaler_window_end()
                    if len(history["loss"]) > win_start:
                        scaler_window_end(final=True)
                    history["skipped"] = (self._guard["skipped"]
                                          - skipped_before)
                finally:
                    # an exception (dataset error, action='raise') must
                    # not leak the staging thread parked on the queue,
                    # and the trailing sub-metrics_every accumulation
                    # must still count — *_total counters undercounting
                    # on a raise would misreport exactly the runs one
                    # debugs with these metrics
                    self._publish_window_obs(obs_state,
                                             _obs_time.perf_counter())
                    if made_prefetcher is not None:
                        made_prefetcher.close()
                        history["prefetch"] = made_prefetcher.stats()
                if preempt.triggered:
                    self._preempt_exit(checkpoint, resumable, heartbeat)
            if sentinel is not None:
                history["sentinel"] = sentinel.stats()
            return history

        # guard mode is pinned for the whole drive (one executable); flag
        # changes take effect at the next drive()/__call__
        import os as _os
        import signal as _signal
        import time as _time

        from ..core.exceptions import stall_guard
        from ..distributed.launch import heartbeat as hb
        from ..utils import fault_injection

        action = str(flag_value("check_nan_inf_action", "none"))
        protect = action in ("skip", "raise")
        guard = "protect" if protect else ("flag" if action != "none"
                                           else "off")
        # grad-norm tracking is a static graph choice (like guard): only
        # paid when the sentinel's ceiling is armed, and free when grad
        # clipping already computes the norm
        track_gnorm = bool(sentinel is not None
                           and sentinel.wants_grad_norm())
        window = []
        sched = (getattr(self.optimizer, "_learning_rate", None)
                 if self._step_lr_scheduler else None)
        win_start_ns = _obs_time.perf_counter_ns()

        def flush_and_observe(buf):
            """Flush one window and record its observability: dispatch +
            window spans bracketing timestamps the host already took, and
            the registry metrics at the metrics_every cadence."""
            nonlocal win_start_ns
            pre_ns = _obs_time.perf_counter_ns()
            _obs_trace.add_complete(
                "train.dispatch", win_start_ns, pre_ns, cat="train",
                args={"instance": self._stats_name, "steps": len(buf)})
            win = self._flush_window(buf, action, protect, history,
                                     on_window,
                                     stall_timeout=step_timeout,
                                     track_gnorm=track_gnorm)
            now_ns = _obs_time.perf_counter_ns()
            _obs_trace.add_complete(
                "train.window", win_start_ns, now_ns, cat="train",
                args={"instance": self._stats_name, "steps": len(buf),
                      "non_finite": win["non_finite"]})
            win_start_ns = now_ns
            self._record_window_obs(obs_state, len(buf),
                                    win["non_finite"],
                                    _obs_time.perf_counter())
            return win
        with hb.trap_preemption(enable=handle_preemption) as preempt:
            if heartbeat:
                hb.write(step=self._step_count)
            try:
                it = iter(stream)
                # count checked BEFORE pulling: a one-shot iterator keeps
                # its remaining batches when steps caps the run
                while steps is None or history["steps"] < steps:
                    if preempt.triggered and not window:
                        # stop only at window boundaries: every rank of a
                        # multi-process job reaches the same boundary, so
                        # the preemption checkpoint lands at one global
                        # step (windows are step-aligned across ranks)
                        break
                    if fault_injection.should_fire("proc.kill"):
                        # chaos site: simulate the OOM-killer/node loss
                        _os.kill(_os.getpid(), _signal.SIGKILL)
                    try:
                        with stall_guard(step_timeout,
                                         f"batch fetch after step "
                                         f"{history['steps']}"):
                            if fault_injection.should_fire("train.stall"):
                                _time.sleep(_STALL_SLEEP_S)
                            batch = next(it)
                    except StopIteration:
                        break
                    args, kw = self._call_form(batch)
                    if obs_state["items_per_step"] is None:
                        obs_state["items_per_step"] = \
                            self._batch_items(args, kw)
                    self._step_count += 1
                    self._guard["total"] += 1
                    loss, finite = self._dispatch(args, kw, guard, 1.0,
                                                  track_gnorm)
                    if resumable is not None:
                        resumable.advance(1)
                    window.append((loss, finite))
                    history["steps"] += 1
                    if hasattr(sched, "step"):
                        sched.step()
                    if len(window) >= log_every:
                        # swap-clear BEFORE flushing: if the flush raises
                        # (action='raise'), the trailing flush below must
                        # not replay the same window's bookkeeping
                        full, window = window, []
                        win = flush_and_observe(full)
                        if heartbeat:
                            hb.write(step=self._step_count)
                        if sentinel is not None:
                            with _obs_trace.span(
                                    "train.sentinel", cat="train",
                                    args={"instance": self._stats_name}):
                                new_it = self._sentinel_check(
                                    sentinel, win, history, checkpoint,
                                    resumable, stream, it, log_every)
                            if new_it is not None:
                                it = new_it
                # trailing partial window: flushed only on clean exit — an
                # exception escaping the loop must propagate, not be
                # replaced by a boundary FloatingPointError (the device
                # state is already correct either way; in-graph semantics
                # never needed the host)
                if window:
                    win = flush_and_observe(window)
                    if heartbeat:
                        hb.write(step=self._step_count)
                    if sentinel is not None:
                        # the loop is over, so a skip/rollback response
                        # has no iterator to rewind — but the restore /
                        # warn / raise / health bookkeeping still applies
                        # (the NEXT drive continues from the rolled-back
                        # state and cursor)
                        with _obs_trace.span(
                                "train.sentinel", cat="train",
                                args={"instance": self._stats_name}):
                            self._sentinel_check(
                                sentinel, win, history, checkpoint,
                                resumable, stream, None, log_every)
            except BaseException:
                # the unfetched window's finite flags are lost with the
                # exception — resync the host mirrors from the
                # authoritative device accumulator so guard_stats()/step
                # numbering stay exact for the rest of the process
                if protect:
                    try:
                        self.guard_stats(sync=True)
                    except Exception:
                        pass
                raise
            finally:
                # the trailing sub-metrics_every accumulation must still
                # count even when the loop exits on an exception
                self._publish_window_obs(obs_state,
                                         _obs_time.perf_counter())
                if made_prefetcher is not None:
                    made_prefetcher.close()
                    history["prefetch"] = made_prefetcher.stats()
            if preempt.triggered:
                self._preempt_exit(checkpoint, resumable, heartbeat)
        if sentinel is not None:
            history["sentinel"] = sentinel.stats()
        return history

    def _preempt_exit(self, checkpoint, resumable, heartbeat):
        """Graceful-preemption epilogue: the in-flight window is already
        flushed and the batch cursor is exact, so write one committed
        checkpoint (model + this step's optimizer state + data-stream
        cursor), heartbeat a final time, and exit with the distinguished
        code the supervisor treats as *clean* — relaunch without consuming
        restart budget."""
        from ..distributed.launch import heartbeat as hb

        if checkpoint is not None:
            step_now = self.device_metrics()["step_count"]
            handle = checkpoint.save(step_now, model=self.model,
                                     optimizer=self, sampler=resumable,
                                     plan=self._plan)
            if handle is not None:  # async save: the exit must not tear it
                checkpoint.wait()
        else:
            # the 123 contract promises the supervisor a lossless eviction;
            # without a manager here that promise rests entirely on the
            # caller's own on_window checkpointing — say so, loudly, so a
            # job that never saves cannot silently preempt-loop at step 0
            import warnings

            warnings.warn(
                "preempted without checkpoint=: exiting "
                f"{hb.PREEMPT_EXIT_CODE} (budget-free relaunch) but drive "
                "saved NOTHING — progress since your last own checkpoint "
                "(e.g. from on_window) will be retrained after the "
                "relaunch", RuntimeWarning, stacklevel=2)
        if heartbeat:
            hb.write(step=self._step_count)
        raise SystemExit(hb.PREEMPT_EXIT_CODE)

    def _sentinel_check(self, sentinel, win, history, checkpoint,
                        resumable, stream, it, log_every, scaler=None):
        """Judge one fetched window and run the divergence-response
        ladder. Returns a replacement batch iterator when the response
        rewound or skipped the stream (rollback restarts it from the
        restored-and-advanced cursor), else ``None``.

        The verdict is deterministic from replicated device values, so
        every rank computes it identically; multi-process runs still
        cross-check through the jax.distributed coordination service (the
        PR-4 checkpoint-barrier transport) — a rank whose replicated
        arithmetic diverged is exactly the failure under supervision and
        must not roll back alone."""
        import warnings

        verdict = sentinel.observe(win)
        spiked = sentinel.agree_verdict(verdict["verdict"] == "spike")
        # health bookkeeping: every clean window credits the committed
        # checkpoints; a bad window resets their pending counts — a step
        # becomes a rollback target only FLAGS_sentinel_healthy_windows
        # clean windows after it was written
        if checkpoint is not None and hasattr(checkpoint, "note_window"):
            checkpoint.note_window(clean=not spiked,
                                   k=sentinel.healthy_windows)
        if not spiked:
            return None
        why, where = sentinel.describe(verdict)
        if sentinel.action == "raise":
            sentinel.raise_divergence(
                f"divergence detected ({why}) at {where}")
        warnings.warn(
            f"divergence sentinel: spike verdict ({why}) at {where} — "
            f"responding with FLAGS_sentinel_action={sentinel.action}",
            RuntimeWarning, stacklevel=3)
        if sentinel.action == "warn":
            return None
        if sentinel.action == "skip":
            # bad-window skip: assume the poisoned input region continues
            # and drop the NEXT window of batches untrained (the cursor
            # advances over them — they are consumed, never replayed).
            # The offending window's updates stay applied: without a
            # checkpoint there is nothing to rewind to
            if it is None:
                return None  # trailing window: no stream left to skip
            from ..core.flags import flag_value
            from ..core.exceptions import stall_guard

            dropped = 0
            # the drain pulls from the same loader/collective path as a
            # normal fetch — keep it under the stall guard, or a wedge
            # while draining would block forever (FLAGS_step_timeout_s)
            with stall_guard(float(flag_value("step_timeout_s", 0) or 0),
                             "sentinel skip-window drain"):
                try:
                    for _ in range(log_every):
                        next(it)
                        dropped += 1
                        if resumable is not None:
                            resumable.advance(1)
                except StopIteration:
                    pass
            if dropped:
                history["skipped_windows"] += 1
            return it if dropped else None
        # rollback: restore the last HEALTHY checkpoint and skip every
        # batch consumed since it, so the poisoned window is not replayed
        if checkpoint is None or resumable is None:
            sentinel.raise_divergence(
                "FLAGS_sentinel_action=rollback needs drive(checkpoint=a "
                "CheckpointManager, sampler=/data=a resumable stream); "
                f"got checkpoint={type(checkpoint).__name__}, "
                f"resumable={type(resumable).__name__}")
        healthy = checkpoint.latest_healthy_step()
        admit = sentinel.agree_rollback(healthy)
        if healthy is None:
            sentinel.raise_divergence(
                "no HEALTHY checkpoint to roll back to (a step is tagged "
                "healthy only after FLAGS_sentinel_healthy_windows clean "
                "windows pass beyond it — the spike hit before any "
                "checkpoint earned the tag)")
        sentinel.acquire_rollback(admit=admit)  # raises on exhaustion
        # restore model + this step's optimizer state — but NOT the
        # sampler: its cursor already sits just past the poisoned window
        # (one advance() per trained batch), which IS the skip — every
        # batch consumed since the healthy checkpoint is never replayed,
        # and the in-flight epoch keeps its recorded shuffle seed (a
        # restore-then-re-advance round trip would re-draw an unseeded
        # epoch seed and resume a DIFFERENT permutation than the one the
        # consumed batches came from)
        pre_scale = self._lr_scale
        checkpoint.auto_resume(model=self.model, optimizer=self,
                               scaler=scaler, step=healthy,
                               plan=self._plan)
        # checkpoints written past the divergence point hold poisoned
        # states — they must never win a latest_valid_step race against
        # the healthy restore point on a later crash-restart
        checkpoint.drop_steps_after(healthy)
        if sentinel.lr_cooldown < 1.0:
            # compound on top of the PRE-restore scale: repeated spikes
            # in the same region restore the same (pre-cooldown)
            # checkpoint, and cooling down after EACH rollback must keep
            # escalating — 0.5, 0.25, ... — not reset to 0.5 every time
            self._lr_scale = pre_scale * sentinel.lr_cooldown
        # the rewind puts the trajectory at an earlier, higher-loss point;
        # re-baseline the detector or the rollback itself reads as the
        # next spike (budget-draining rollback loop)
        sentinel.notify_rollback()
        history["rollbacks"] += 1
        _M_TRAIN_ROLLBACKS.inc(instance=self._stats_name)
        if it is None:
            # trailing window: the loop is already over — params, moments
            # and cursor are rolled back, and the NEXT drive()/epoch
            # continues from the restored position
            return None
        # restart the stream: drop the prefetcher's read-ahead (staged
        # past the rollback point) and begin a fresh pass that honors the
        # untouched cursor (already just past the poisoned window)
        if hasattr(stream, "reset"):
            stream.reset()
        new_it = iter(stream)
        if new_it is it:
            sentinel.raise_divergence(
                "rollback needs a re-iterable batch stream (a DataLoader "
                "or DevicePrefetcher), got a bare one-shot iterator")
        return new_it

    def _flush_window(self, window, action, protect, history, on_window,
                      stall_timeout=0, track_gnorm=False):
        """Fetch one deferred window (O(1) host round-trips) and replay the
        per-step guard bookkeeping that per-step fetch would have done.
        Returns the window dict handed to ``on_window`` (the divergence
        sentinel judges it). With ``track_gnorm`` the accumulator's
        grad-norm peak rides in the SAME stacked fetch as the losses —
        same host-sync count armed or not — and the device-side peak is
        re-zeroed for the next window. ``stall_timeout`` arms the stall
        guard over the device fetches ONLY — ``on_window`` (user code:
        checkpointing, logging) runs outside it, so a slow checkpoint save
        is never mistaken for a wedge."""
        import warnings

        import numpy as np

        from ..core.exceptions import stall_guard

        with stall_guard(stall_timeout, "window metric fetch"), \
                _obs_trace.span("train.fetch", cat="train",
                                args={"instance": self._stats_name,
                                      "steps": len(window)}):
            vals = [jnp.asarray(l, jnp.float32) for l, _ in window]
            if track_gnorm:
                vals.append(jnp.asarray(self._acc[3], jnp.float32))
            stacked = np.asarray(jnp.stack(vals))
            history["host_syncs"] += 1
            gnorm_peak = None
            if track_gnorm:
                gnorm_peak = float(stacked[-1])
                losses = stacked[:-1]
                # fresh zero for the next window's peak (host-side tuple
                # rebuild — no device round-trip)
                self._acc = self._acc[:3] + (jnp.float32(0.0),)
            else:
                losses = stacked
            finite = None
            if action != "none":
                finite = np.asarray(jnp.stack([f for _, f in window]))
                history["host_syncs"] += 1
        n_bad = 0
        if finite is not None:
            with _obs_trace.span("train.guard", cat="train",
                                 args={"instance": self._stats_name}):
                for ok in finite:
                    if ok:
                        self._guard["consecutive_skips"] = 0
                    else:
                        n_bad += 1
                        if action == "warn":
                            self._guard["warned"] += 1
                        if protect:
                            self._guard["skipped"] += 1
                            self._guard["consecutive_skips"] += 1
                            # device step did not advance
                            self._step_count -= 1
            if n_bad and action == "warn":
                warnings.warn(
                    f"non-finite loss/grads on {n_bad} step(s) in the last "
                    f"{len(window)}-step window — updates applied anyway "
                    "(FLAGS_check_nan_inf_action=warn, deferred fetch)",
                    stacklevel=3)
        history["loss"].extend(float(v) for v in losses)
        if protect:
            history["skipped"] += n_bad
        history["windows"] += 1
        from .sentinel import make_window

        win = make_window(losses, non_finite=n_bad,
                          step=history["steps"], gnorm_peak=gnorm_peak)
        if on_window is not None:
            with _obs_trace.span("train.checkpoint", cat="train",
                                 args={"instance": self._stats_name}):
                on_window(win)
        if n_bad and action == "raise":
            raise FloatingPointError(
                f"non-finite loss/grads on {n_bad} step(s) detected at the "
                "metric-fetch boundary; the updates were already discarded "
                "in-graph (FLAGS_check_nan_inf_action=raise, deferred "
                "fetch)")
        return win


def fused_train_step(model, optimizer, loss_fn=None, step_lr_scheduler=True,
                     shape_buckets=None, bucket_args=None, grad_scaler=None):
    """Build a fused (single-dispatch, donated) train step callable:
    ``step(*inputs) -> loss``. See FusedTrainStep — with the default
    ``step_lr_scheduler=True`` the step owns LR-scheduler stepping; do not
    also step it in the loop. ``shape_buckets`` pads inputs up to registered
    boundaries before dispatch (paddle.jit bucket semantics) so variable
    shapes cost O(buckets) compiles; ``bucket_args`` (positional indices /
    kw names) pins which inputs pad when the dominant-length auto rule is
    ambiguous. ``grad_scaler`` fuses dynamic loss scaling in-graph and arms
    the step anomaly guard (see FLAGS_check_nan_inf_action): a non-finite
    step is discarded and the scale backs off, all inside the single
    dispatch plus one host sync for the finite flag."""
    return FusedTrainStep(model, optimizer, loss_fn, step_lr_scheduler,
                          shape_buckets=shape_buckets,
                          bucket_args=bucket_args, grad_scaler=grad_scaler)
