"""Fused training step: forward + backward + optimizer update in ONE
donated XLA executable.

TPU-native extension (no single reference counterpart — the reference's
equivalent is the fused CUDA optimizer kernels + multi-stream executor,
e.g. paddle/fluid/operators/fused/ and DistributedFusedLamb in
python/paddle/incubate/optimizer/). The eager path runs three dispatches
per step (to_static forward, backward, optimizer); this collapses them
into one jit with parameter/moment buffer donation, so weights are
updated in place in HBM and per-step dispatch overhead is one call.

Supported optimizers: SGD, Momentum, Adam, AdamW (the bench/optimizer
hot set). Learning-rate schedulers are honored by passing the current lr
as a traced scalar. ClipGradByGlobalNorm is fused in-graph when set on
the optimizer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..utils import functional_call, params_dict

__all__ = ["FusedTrainStep", "fused_train_step"]


def _f32(x):
    return x.astype(jnp.float32)


class FusedTrainStep:
    """``step_lr_scheduler=True`` (default) means the fused step OWNS
    scheduler stepping: it calls ``optimizer._learning_rate.step()`` once per
    invocation, and the caller must NOT also call ``lr_scheduler.step()`` in
    the training loop (that would advance the schedule twice per step). Pass
    ``step_lr_scheduler=False`` to keep the standard paddle pattern where the
    loop steps the scheduler itself."""

    _instance_count = 0

    def __init__(self, model, optimizer, loss_fn=None, step_lr_scheduler=True,
                 shape_buckets=None, bucket_args=None):
        from ..jit.cache import BucketSpec

        from ..optimizer.optimizers import SGD, Adam, AdamW, Momentum

        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self._step_lr_scheduler = step_lr_scheduler
        # pad-up shape buckets (paddle.jit semantics): data inputs are
        # zero-padded to the nearest registered boundary before dispatch so
        # a variable-length stream costs O(buckets) compiles, and the
        # compile/hit counters surface in paddle.jit.cache_stats().
        # bucket_args (positional indices / kw names) pins WHICH inputs pad;
        # default is the dominant-length rule — see paddle.jit.to_static.
        self._shape_buckets = BucketSpec.normalize(shape_buckets)
        self._bucket_args = (None if bucket_args is None
                             else frozenset(bucket_args))
        # per-instance stats row: each FusedTrainStep owns its own jax.jit
        # cache, so merging instances of one model class would both blur the
        # counters and false-trigger the recompile-cliff warning (9 steps
        # compiling once each is not a cliff)
        FusedTrainStep._instance_count += 1
        self._stats_name = (f"fused_train_step[{type(model).__name__}"
                            f"#{FusedTrainStep._instance_count}]")
        self._seen_sigs = set()
        self._names = sorted(params_dict(model))
        self._tensors = dict(model.named_parameters())
        # trainable params only (stop_gradient=True params stay frozen)
        self._names = [n for n in self._names
                       if n in self._tensors
                       and not self._tensors[n].stop_gradient]
        self._params = {n: self._tensors[n]._data for n in self._names}
        self._step_count = 0

        opt = optimizer
        if isinstance(opt, AdamW):
            self._kind = "adamw"
        elif isinstance(opt, Adam):
            self._kind = "adam"
        elif isinstance(opt, Momentum):
            self._kind = "momentum"
        elif isinstance(opt, SGD):
            self._kind = "sgd"
        else:
            raise TypeError(
                f"fused_train_step supports SGD/Momentum/Adam/AdamW, got "
                f"{type(opt).__name__}")
        if self._kind in ("adam", "adamw"):
            z = {n: jnp.zeros(self._params[n].shape, jnp.float32)
                 for n in self._names}
            self._m1 = z
            self._m2 = {n: jnp.zeros_like(v) for n, v in z.items()}
        elif self._kind == "momentum":
            self._m1 = {n: jnp.zeros(self._params[n].shape, jnp.float32)
                        for n in self._names}
            self._m2 = {}
        else:
            self._m1, self._m2 = {}, {}

        if self._kind in ("adam", "adamw"):
            # per-param decoupled decay honoring apply_decay_param_fun
            base_wd = float(opt._wd_coeff())
            fun = getattr(opt, "_apply_decay_param_fun", None)
            self._wds = {
                n: (base_wd if fun is None or fun(self._tensors[n].name)
                    else 0.0)
                for n in self._names
            }
            ratio_fun = getattr(opt, "_lr_ratio", None)
            self._lr_ratios = {
                n: (float(ratio_fun(self._tensors[n]))
                    if ratio_fun is not None else 1.0)
                for n in self._names
            }
        else:
            # coupled-L2 coefficients (SGD/Momentum regularizer path)
            self._wds = {n: float(opt._weight_decay_value(self._tensors[n]))
                         for n in self._names}
            self._lr_ratios = {n: 1.0 for n in self._names}

        clip = getattr(opt, "_grad_clip", None)
        from ..nn.clip import ClipGradByGlobalNorm

        if clip is None:
            self._clip_norm = None
        elif isinstance(clip, ClipGradByGlobalNorm):
            self._clip_norm = float(clip.clip_norm)
        else:
            raise TypeError(
                f"fused_train_step fuses ClipGradByGlobalNorm only; the "
                f"optimizer has {type(clip).__name__} — use the eager step "
                "for other clip types")
        self._jitted = jax.jit(self._step_impl, donate_argnums=(0, 1, 2))

    # -- pure step ------------------------------------------------------
    def _loss(self, params, data, kwdata):
        all_params = dict(params)
        # frozen params participate in forward with their current values
        for n, t in self._tensors.items():
            if n not in all_params:
                all_params[n] = t._data
        out = functional_call(self.model, all_params, *data, **kwdata)
        if self.loss_fn is not None:
            return self.loss_fn(out)
        if isinstance(out, (tuple, list)):
            return out[0]
        return out

    def _step_impl(self, params, m1, m2, step, lr, data, kwdata):
        loss, grads = jax.value_and_grad(self._loss)(params, data, kwdata)
        if self._clip_norm is not None:
            gnorm = jnp.sqrt(sum(
                jnp.sum(_f32(g) ** 2) for g in jax.tree.leaves(grads)))
            factor = jnp.minimum(1.0, self._clip_norm / (gnorm + 1e-12))
            grads = jax.tree.map(lambda g: (_f32(g) * factor).astype(g.dtype),
                                 grads)
        opt = self.optimizer
        kind = self._kind
        if kind in ("adam", "adamw"):
            b1 = jnp.float32(opt._beta1)
            b2 = jnp.float32(opt._beta2)
            eps = jnp.float32(opt._epsilon)
            b1p = jnp.power(b1, step)
            b2p = jnp.power(b2, step)

            def upd(p, g, m1_, m2_, wd, lr_ratio):
                gf, pf = _f32(g), _f32(p)
                if kind == "adam":
                    gf = gf + wd * pf
                m1n = b1 * m1_ + (1 - b1) * gf
                m2n = b2 * m2_ + (1 - b2) * gf * gf
                m1h = m1n / (1 - b1p)
                m2h = m2n / (1 - b2p)
                step_lr = lr * lr_ratio
                new = pf - step_lr * m1h / (jnp.sqrt(m2h) + eps)
                if kind == "adamw":
                    new = new - step_lr * wd * pf
                return new.astype(p.dtype), m1n, m2n

            out = {n: upd(params[n], grads[n], m1[n], m2[n],
                          self._wds[n], self._lr_ratios[n])
                   for n in params}
            return (loss, {n: v[0] for n, v in out.items()},
                    {n: v[1] for n, v in out.items()},
                    {n: v[2] for n, v in out.items()})
        if kind == "momentum":
            mu = jnp.float32(opt._momentum)

            def updm(p, g, v, wd):
                gf = _f32(g) + wd * _f32(p)
                vn = mu * v + gf
                return (_f32(p) - lr * vn).astype(p.dtype), vn

            out = {n: updm(params[n], grads[n], m1[n], self._wds[n])
                   for n in params}
            return (loss, {n: v[0] for n, v in out.items()},
                    {n: v[1] for n, v in out.items()}, m2)
        # sgd
        new = {n: (_f32(params[n])
                   - lr * (_f32(grads[n]) + self._wds[n] * _f32(params[n]))
                   ).astype(params[n].dtype)
               for n in params}
        return loss, new, m1, m2

    # -- public ---------------------------------------------------------
    def lowered_flops(self, *data, **kwdata):
        """FLOPs of one full fused step (forward + backward + update) from
        XLA's HLO cost analysis on the lowered program — self-measured, no
        hand-derived formula. Returns None when the backend provides no
        estimate. Used by bench.py for MFU accounting."""
        darrs, karrs = self._prepare_arrays(data, kwdata, record=False)
        try:
            lowered = self._jitted.lower(
                self._params, self._m1, self._m2, jnp.float32(1),
                jnp.float32(1e-3), darrs, karrs)
            cost = lowered.cost_analysis()
            if not (hasattr(cost, "get") and cost.get("flops")):
                # some backends only report cost post-compile; with the
                # step already compiled for these shapes this is a cache
                # hit, not a second compile
                cost = lowered.compile().cost_analysis()
            flops = cost.get("flops") if hasattr(cost, "get") else None
            return float(flops) if flops and flops > 0 else None
        except Exception:
            return None

    def _prepare_arrays(self, data, kwdata, record=True):
        """Unwrap call inputs to jax arrays, padding each up to its shape
        bucket when buckets are registered (per-step or global).
        ``record=False`` keeps estimation-only callers (lowered_flops) out
        of the dispatch telemetry."""
        from ..jit import cache as jit_cache

        darrs = tuple(d._data if isinstance(d, Tensor) else jnp.asarray(d)
                      for d in data)
        karrs = {k: (v._data if isinstance(v, Tensor) else jnp.asarray(v))
                 for k, v in kwdata.items()}
        spec = (self._shape_buckets if self._shape_buckets is not None
                else jit_cache.get_shape_buckets())
        if spec is not None:
            # selection: bucket_args pins the padded inputs explicitly;
            # otherwise the dominant-length rule (jit_cache
            # .infer_call_lengths) — the first input carrying the bucketed
            # axis defines the call's length and only matching inputs pad,
            # so [B, 1] labels / [B, n_features] dense vectors pass through
            # instead of gaining fabricated zeros. Use bucket_args when a
            # fixed field's width can coincide with a sequence length.
            sel = self._bucket_args
            lengths = (jit_cache.infer_call_lengths(
                list(darrs) + list(karrs.values()), spec)
                if sel is None else None)
            n_pad = 0
            padded = []
            for i, a in enumerate(darrs):
                if sel is None or i in sel:
                    a, p = jit_cache.pad_array_to_bucket(a, spec, lengths)
                    n_pad += p
                padded.append(a)
            darrs = tuple(padded)
            for k, a in karrs.items():
                if sel is None or k in sel:
                    a, p = jit_cache.pad_array_to_bucket(a, spec, lengths)
                    n_pad += p
                    karrs[k] = a
            if record:
                jit_cache.record_bucket_pads(self._stats_name, n_pad)
        return darrs, karrs

    def _count_dispatch(self, darrs, karrs):
        """Compile-vs-hit telemetry: a shape signature not seen before means
        jax.jit traces + XLA-compiles a fresh executable this dispatch."""
        from ..jit import cache as jit_cache

        sig = jit_cache.shape_signature(
            list(darrs) + [karrs[k] for k in sorted(karrs)])
        if sig in self._seen_sigs:
            jit_cache.record_hit(self._stats_name)
        else:
            self._seen_sigs.add(sig)
            jit_cache.record_compile(self._stats_name, sig)

    def __call__(self, *data, **kwdata):
        self._step_count += 1
        lr = jnp.float32(self.optimizer.get_lr())
        darrs, karrs = self._prepare_arrays(data, kwdata)
        self._count_dispatch(darrs, karrs)
        loss, self._params, self._m1, self._m2 = self._jitted(
            self._params, self._m1, self._m2,
            jnp.float32(self._step_count), lr, darrs, karrs)
        # donation invalidated the old buffers — rebind the live Tensors
        for n in self._names:
            self._tensors[n]._rebind(self._params[n])
        if self._step_lr_scheduler:
            sched = getattr(self.optimizer, "_learning_rate", None)
            if hasattr(sched, "step"):
                sched.step()
        return Tensor._wrap(loss)


def fused_train_step(model, optimizer, loss_fn=None, step_lr_scheduler=True,
                     shape_buckets=None, bucket_args=None):
    """Build a fused (single-dispatch, donated) train step callable:
    ``step(*inputs) -> loss``. See FusedTrainStep — with the default
    ``step_lr_scheduler=True`` the step owns LR-scheduler stepping; do not
    also step it in the loop. ``shape_buckets`` pads inputs up to registered
    boundaries before dispatch (paddle.jit bucket semantics) so variable
    shapes cost O(buckets) compiles; ``bucket_args`` (positional indices /
    kw names) pins which inputs pad when the dominant-length auto rule is
    ambiguous."""
    return FusedTrainStep(model, optimizer, loss_fn, step_lr_scheduler,
                          shape_buckets=shape_buckets,
                          bucket_args=bucket_args)
