"""Divergence sentinel: loss-spike detection over deferred metric windows.

The NaN/Inf step guard (PR 2) and the elastic supervision layer (PR 4)
cover *hard* failures — non-finite steps, crashes, hangs. The failure mode
that actually ruins long runs at scale is finite-but-wrong training: a
poisoned data window, a grad explosion the clip ceiling absorbs into a
wrong direction, or slow divergence — the job keeps running and the
checkpoint lifecycle keeps committing poisoned states. The reference
Paddle stack pairs its elastic launcher with training-health supervision
for exactly this reason.

:class:`TrainingSentinel` is the detector half of that supervision. It
consumes the per-window statistics ``FusedTrainStep.drive`` already
fetches at every metric-fetch boundary (stacked losses + the device-side
grad-norm peak that rides in the donated accumulator), so arming it adds
**zero per-step host syncs** — detection is a pure host-side computation
over values the deferred-fetch pipeline brings over anyway. Three
detectors, all deterministic functions of replicated device values (every
rank computes the identical verdict, which the response layer cross-checks
through the jax.distributed coordination service before a multi-rank
rollback):

- **EMA z-score spike**: a window whose mean loss sits more than
  ``FLAGS_sentinel_zscore`` EMA standard deviations above the running EMA
  mean (one-sided — a *drop* is never a spike). Spike windows never update
  the EMA, so one spike cannot normalize the next. Armed after
  ``FLAGS_sentinel_warmup_windows`` clean windows.
- **grad-norm ceiling**: the window's peak global grad norm (tracked
  in-graph) exceeds ``FLAGS_sentinel_grad_norm_ceiling``.
- **patience trend**: ``FLAGS_sentinel_patience`` consecutive windows of
  strictly rising mean loss — the slow-divergence signature no single
  window's z-score catches.

The response ladder (``FLAGS_sentinel_action``: warn → skip → rollback →
raise) lives in the consumer — ``FusedTrainStep.drive`` and the hapi
``DivergenceSentinel`` callback — this module only judges and budgets.
:class:`RollbackBudget` is the leaky-bucket rollback cap mirroring the
launcher's ``RestartBudget``; exhaustion raises the typed
:class:`~paddle_tpu.core.exceptions.TrainDivergenceError` carrying the
full spike history.
"""

from __future__ import annotations

import math
import time

from ..core.exceptions import TrainDivergenceError
from ..core.flags import flag_value

__all__ = ["TrainingSentinel", "RollbackBudget", "make_window"]

_EPS = 1e-12


def make_window(losses, non_finite=0, step=-1, gnorm_peak=None):
    """The window dict every metric-fetch boundary hands ``on_window``
    and the sentinel — ONE construction site for its semantics. The
    judged ``mean_loss`` is computed over FINITE losses only: a routine
    non-finite step (scaler overflow, NaN-guard skip) is the NaN guard's
    event and must not read as a divergence spike on top."""
    import numpy as np

    losses = np.asarray(losses, np.float32)
    applied = losses[np.isfinite(losses)]
    return {"losses": losses,
            "mean_loss": (float(applied.mean()) if applied.size
                          else float("nan")),
            "non_finite": int(non_finite), "step": int(step),
            "gnorm_peak": gnorm_peak}


class RollbackBudget:
    """Leaky-bucket rollback cap mirroring the launcher's RestartBudget:
    at most ``max_rollbacks`` within a rolling ``window_s`` window (old
    rollbacks age out; ``window_s=0`` makes the budget lifetime-scoped).
    No backoff — a rollback is an in-process recovery, not a scheduler
    relaunch. ``clock`` is injectable for tests."""

    def __init__(self, max_rollbacks=None, window_s=None,
                 clock=time.monotonic):
        self.max_rollbacks = int(
            flag_value("sentinel_rollback_budget", 3)
            if max_rollbacks is None else max_rollbacks)
        self.window_s = float(
            flag_value("sentinel_budget_window_s", 3600.0)
            if window_s is None else window_s)
        self._clock = clock
        self._events: list[float] = []
        self.total = 0

    def _prune(self, now):
        if self.window_s > 0:
            self._events = [t for t in self._events
                            if now - t <= self.window_s]

    @property
    def used(self):
        """Rollbacks currently counted against the budget (in-window)."""
        self._prune(self._clock())
        return len(self._events)

    def try_acquire(self):
        """Record one rollback; False when the bucket is full (the caller
        must escalate to TrainDivergenceError instead of rolling back)."""
        now = self._clock()
        self._prune(now)
        if len(self._events) >= self.max_rollbacks:
            return False
        self.record()
        return True

    def record(self):
        """Unconditionally record one rollback event — used when an
        agreed cross-rank admission decision binds this rank regardless
        of what its local clock's pruning would say."""
        self._events.append(self._clock())
        self.total += 1


class TrainingSentinel:
    """Window-level divergence detector + response budget.

    Construct with no arguments to read every knob from the
    ``FLAGS_sentinel_*`` registry at that moment; keyword arguments
    override individual knobs (tests, notebooks). The object is cheap,
    host-only state: EMA mean/variance of window mean losses, the trend
    counter, the spike history, and the rollback budget.

    ``observe(win)`` takes the window dict ``drive`` hands ``on_window``
    (``mean_loss`` required; ``gnorm_peak`` and ``step`` optional) and
    returns a verdict dict::

        {"verdict": "ok" | "spike", "reasons": [...], "zscore": float|None,
         "mean_loss": float, "gnorm_peak": float|None, "step": int,
         "window": int}

    Determinism contract: the verdict is a pure function of the observed
    window statistics and prior observations — given identical replicated
    device values, every rank's sentinel reaches the identical verdict in
    the same window. Consumers performing a distributed response must
    still cross-check (``drive`` does, through the jax.distributed
    coordination service) so a rank whose arithmetic diverged — the very
    failure being supervised — cannot roll back alone.
    """

    #: lower bound on the z-score denominator, as a fraction of |EMA mean|:
    #: early in a run (or on a plateau) the EMA variance is ~0 and any
    #: uptick would otherwise divide by nothing and read as an infinite
    #: z-score — with the floor, a spike must exceed the baseline by at
    #: least ``zscore * MIN_SIGMA_FRAC`` relatively, however quiet the
    #: history (override per-instance for unusually noisy/flat losses)
    MIN_SIGMA_FRAC = 0.05

    def __init__(self, action=None, zscore=None, ema_beta=None,
                 warmup_windows=None, grad_norm_ceiling=None, patience=None,
                 lr_cooldown=None, healthy_windows=None, budget=None,
                 min_sigma_frac=None, clock=time.monotonic):
        def _flag(v, name, default):
            return flag_value(name, default) if v is None else v

        self.action = str(_flag(action, "sentinel_action", "none"))
        self.zscore = float(_flag(zscore, "sentinel_zscore", 6.0))
        self.ema_beta = float(_flag(ema_beta, "sentinel_ema_beta", 0.9))
        self.warmup_windows = int(
            _flag(warmup_windows, "sentinel_warmup_windows", 3))
        self.grad_norm_ceiling = float(
            _flag(grad_norm_ceiling, "sentinel_grad_norm_ceiling", 0.0))
        self.patience = int(_flag(patience, "sentinel_patience", 0))
        self.lr_cooldown = float(
            _flag(lr_cooldown, "sentinel_lr_cooldown", 1.0))
        self.healthy_windows = int(
            _flag(healthy_windows, "sentinel_healthy_windows", 2))
        self.min_sigma_frac = float(
            self.MIN_SIGMA_FRAC if min_sigma_frac is None
            else min_sigma_frac)
        self.budget = (RollbackBudget(clock=clock) if budget is None
                       else budget)
        # EMA of window mean losses + EMA of squared deviation (variance)
        self._ema_mean = None
        self._ema_var = 0.0
        self._clean_windows = 0
        self._prev_mean = None
        self._rising = 0  # consecutive strictly-rising windows
        self.windows = 0  # total windows observed
        self.spikes: list[dict] = []  # spike records (TrainDivergenceError
        #                               .history carries these)
        self.rollbacks = 0  # consumer-reported successful rollbacks
        self._warned_no_gnorm = False

    # -- detection -------------------------------------------------------
    @property
    def armed(self):
        return self.action != "none"

    def wants_grad_norm(self):
        """Whether the consumer should track the in-graph grad-norm peak
        for this sentinel (drives the fused step's static graph choice)."""
        return self.armed and self.grad_norm_ceiling > 0

    def observe(self, win):
        """Judge one metric-fetch window; returns the verdict dict (see
        class docstring). Mutates detector state: clean windows feed the
        EMA / trend counters, spike windows are recorded in ``spikes``
        and deliberately kept OUT of the EMA."""
        mean = float(win["mean_loss"])
        gnorm = win.get("gnorm_peak")
        gnorm = None if gnorm is None else float(gnorm)
        step = int(win.get("step", -1))
        self.windows += 1
        verdict = {"verdict": "ok", "reasons": [], "zscore": None,
                   "mean_loss": mean, "gnorm_peak": gnorm, "step": step,
                   "window": self.windows}

        # a non-finite window mean is the NaN guard's domain
        # (FLAGS_check_nan_inf_action) — but with that guard off it would
        # otherwise poison the EMA silently, so treat it as a spike here
        if not math.isfinite(mean):
            verdict["reasons"].append("non_finite_mean")
        else:
            if self._ema_mean is not None \
                    and self._clean_windows >= self.warmup_windows \
                    and self.zscore > 0:
                sigma = max(math.sqrt(self._ema_var + _EPS),
                            self.min_sigma_frac * abs(self._ema_mean),
                            _EPS)
                z = (mean - self._ema_mean) / sigma
                verdict["zscore"] = z
                if z > self.zscore:
                    verdict["reasons"].append("loss_zscore")
            if self.grad_norm_ceiling > 0:
                if gnorm is None and not self._warned_no_gnorm:
                    # this consumer does not track grad norms (GradScaler
                    # per-step drive, hapi fit): the armed ceiling can
                    # never fire — say so once instead of silently
                    # degrading to loss-only detection
                    import warnings

                    self._warned_no_gnorm = True
                    warnings.warn(
                        "divergence sentinel: FLAGS_sentinel_grad_norm_"
                        "ceiling is armed but this training path does not "
                        "track grad norms (windows arrive with gnorm_peak"
                        "=None) — the ceiling detector is inactive; only "
                        "the loss z-score/patience detectors run. Use "
                        "FusedTrainStep.drive without an enabled "
                        "GradScaler for in-graph norm tracking",
                        RuntimeWarning, stacklevel=3)
                if gnorm is not None and gnorm > self.grad_norm_ceiling:
                    verdict["reasons"].append("grad_norm_ceiling")
            if self.patience > 0:
                if self._prev_mean is not None and mean > self._prev_mean:
                    self._rising += 1
                else:
                    self._rising = 0
                if self._rising >= self.patience:
                    verdict["reasons"].append("divergence_trend")

        if verdict["reasons"]:
            verdict["verdict"] = "spike"
            self.spikes.append(dict(verdict))
            # the spiked mean does NOT update the EMA, and the trend
            # counter restarts — post-response windows are judged against
            # the pre-spike baseline
            self._rising = 0
            self._prev_mean = None
            return verdict

        # clean window: fold into the EMA baseline
        if self._ema_mean is None:
            self._ema_mean = mean
            self._ema_var = 0.0
        else:
            b = self.ema_beta
            delta = mean - self._ema_mean
            self._ema_mean = b * self._ema_mean + (1 - b) * mean
            self._ema_var = b * self._ema_var + (1 - b) * delta * delta
        self._clean_windows += 1
        self._prev_mean = mean
        return verdict

    def describe(self, verdict):
        """``(why, where)`` strings for a spike verdict — one formatting
        source for every response surface (drive, hapi callback):
        ``why`` = joined reasons, ``where`` = step/window/mean/z/gnorm."""
        why = "+".join(verdict["reasons"])
        where = (f"step {verdict['step']}, window {verdict['window']}, "
                 f"mean_loss {verdict['mean_loss']:.6g}")
        if verdict.get("zscore") is not None:
            where += f", zscore {verdict['zscore']:.3g}"
        if verdict.get("gnorm_peak") is not None:
            where += f", gnorm_peak {verdict['gnorm_peak']:.6g}"
        return why, where

    # -- cross-rank agreement (multi-process consumers) ------------------
    def agree_verdict(self, spiked):
        """Cross-check this window's spike verdict across ranks (no-op
        single-process). Verdicts are deterministic from replicated
        device values, but a rank whose replicated arithmetic diverged is
        exactly the failure under supervision — disagreement raises a
        typed split-brain error on every rank instead of letting one
        respond alone. Returns the agreed verdict."""
        import jax

        if jax.process_count() <= 1:
            return bool(spiked)
        from ..distributed.checkpoint import allgather_ints

        bits = allgather_ints(int(bool(spiked)),
                              f"sentinel_w{self.windows}")
        if len(set(bits)) > 1:
            self.raise_divergence(
                f"sentinel verdicts disagree across ranks at window "
                f"{self.windows} (split brain: replicated metrics differ "
                "between processes)")
        return bool(bits[0])

    def agree_rollback(self, healthy):
        """Cross-check the rollback decision — the TARGET step and the
        budget admit bit — before any rank restores. A shared
        filesystem's attribute cache can show ranks different HEALTHY
        markers, and budget pruning runs on each rank's local clock; a
        rank restoring a different step (or raising exhaustion alone
        while the others continue) is a silent split brain that wedges
        the next collective.

        Returns the admit decision that MUST be passed to
        :meth:`acquire_rollback` so the agreed bit — not a second local
        clock read — is what admits or refuses the rollback on every
        rank: ``None`` single-process (decide locally at acquire time),
        else the agreed boolean."""
        import jax

        if jax.process_count() <= 1:
            return None
        from ..distributed.checkpoint import allgather_ints

        admit = int(self.budget.used < self.budget.max_rollbacks)
        decisions = allgather_ints(
            (-1 if healthy is None else int(healthy)) * 2 + admit,
            f"sentinel_rb{self.windows}")
        if len(set(decisions)) > 1:
            self.raise_divergence(
                "ranks disagree on the rollback decision (target*2+admit "
                f"= {decisions}) — refusing a split-brain restore")
        return bool(decisions[0] % 2)  # Python: -1 % 2 == 1, -2 % 2 == 0

    def notify_rollback(self):
        """Reset the detector baseline after a rollback: the restored
        trajectory legitimately sits at an earlier (higher-loss) point,
        and judging it against the pre-spike EMA would read the rewind
        itself as a fresh spike — a budget-draining rollback loop. The
        z-score detector re-arms after ``warmup_windows`` new clean
        windows; the budget and spike history are NOT reset (they are
        the loop breaker)."""
        self._ema_mean = None
        self._ema_var = 0.0
        self._clean_windows = 0
        self._prev_mean = None
        self._rising = 0

    # -- response bookkeeping -------------------------------------------
    def acquire_rollback(self, admit=None):
        """Charge one rollback against the leaky-bucket budget; raises
        :class:`TrainDivergenceError` (carrying the spike history) on
        exhaustion. ``admit`` is the cross-rank-agreed decision from
        :meth:`agree_rollback` — when given, it BINDS (the event is
        recorded unconditionally on admission, and refusal raises on
        every rank), so a local clock that prunes differently in the
        microseconds since the agreement cannot split the ranks."""
        if admit is None:
            admit = self.budget.try_acquire()
        elif admit:
            self.budget.record()
        if not admit:
            raise TrainDivergenceError(
                f"divergence-sentinel rollback budget exhausted: "
                f"{self.budget.max_rollbacks} rollbacks within "
                f"{self.budget.window_s:g}s "
                f"(FLAGS_sentinel_rollback_budget / "
                f"FLAGS_sentinel_budget_window_s); {len(self.spikes)} "
                f"spike(s) observed", history=self.spikes,
                rollbacks=self.rollbacks)
        self.rollbacks += 1
        return self.budget.total

    def raise_divergence(self, why):
        """The terminal rung: raise the typed error with full history."""
        raise TrainDivergenceError(
            f"{why}; {len(self.spikes)} spike(s) observed "
            f"(FLAGS_sentinel_action={self.action})",
            history=self.spikes, rollbacks=self.rollbacks)

    def stats(self):
        """Telemetry snapshot: windows seen, spikes, rollbacks, budget."""
        return {"windows": self.windows, "spikes": len(self.spikes),
                "rollbacks": self.rollbacks,
                "budget_used": self.budget.used,
                "budget_max": self.budget.max_rollbacks,
                "clean_windows": self._clean_windows,
                "ema_mean": self._ema_mean,
                "ema_std": math.sqrt(self._ema_var + _EPS)
                if self._ema_mean is not None else None,
                "action": self.action}
