"""paddle.incubate.optimizer — DistributedFusedLamb.

Reference: python/paddle/incubate/optimizer/distributed_fused_lamb.py:115 —
a multi-tensor Lamb whose flattened param/grad buffers and sharded optimizer
states ride fused CUDA kernels + NCCL.

TPU-native redesign: the base Lamb already updates every parameter inside
one jitted multi-tensor call (optimizer/optimizers.py:_lamb_update — the
"fused kernel" is XLA fusion), so this subclass adds the DISTRIBUTED part:
moment buffers laid out sharded over the sharding/dp mesh axis (ZeRO
stage-1, via distributed.sharding.shard_accumulators) the first time they
exist. ``alignment`` / chunking knobs are meaningless under XLA (it owns
buffer layout) and are accepted + recorded only.
"""

from __future__ import annotations

from ...optimizer.optimizers import Lamb

__all__ = ["DistributedFusedLamb"]


class DistributedFusedLamb(Lamb):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, use_master_param_norm=True,
                 gradient_accumulation_steps=1, use_master_acc_grad=True,
                 nproc_per_node=None, name=None):
        super().__init__(learning_rate=learning_rate,
                         lamb_weight_decay=lamb_weight_decay, beta1=beta1,
                         beta2=beta2, epsilon=epsilon, parameters=parameters,
                         grad_clip=grad_clip,
                         exclude_from_weight_decay_fn=
                         exclude_from_weight_decay_fn)
        # recorded for API parity; XLA owns buffer layout and the grad
        # allreduce placement, so these knobs have no TPU effect
        self._clip_after_allreduce = clip_after_allreduce
        self._is_grad_scaled_by_nranks = is_grad_scaled_by_nranks
        self._alignment = alignment
        self._use_master_param_norm = use_master_param_norm
        self._gradient_accumulation_steps = gradient_accumulation_steps
        self._acc_step = 0
        self._sharded = False

    def _maybe_shard_accumulators(self):
        if self._sharded:
            return
        self._sharded = True
        try:
            from ...distributed.sharding import shard_accumulators

            shard_accumulators(self)
        except Exception:
            pass  # no mesh/fleet initialized: single-device layout

    def step(self):
        self._acc_step += 1
        if self._acc_step % max(self._gradient_accumulation_steps, 1):
            return  # accumulate: grads keep summing into .grad
        super().step()
        self._maybe_shard_accumulators()
