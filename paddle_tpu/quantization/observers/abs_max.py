"""Abs-max observer (PTQ).

Reference: python/paddle/quantization/observers/abs_max.py:22 —
AbsmaxObserver collects the running max(|x|) during calibration forwards;
``cal_thresholds`` freezes it into the quantization scale.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor
from ..base import BaseObserver, fake_quant, per_channel_int8
from ..factory import ObserverFactory

__all__ = ["AbsmaxObserver", "AbsmaxObserverLayer",
           "PerChannelAbsmaxObserver", "PerChannelAbsmaxObserverLayer"]


class AbsmaxObserver(ObserverFactory):
    """reference observers/abs_max.py:22."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits=quant_bits)

    def _get_class(self):
        return AbsmaxObserverLayer


class AbsmaxObserverLayer(BaseObserver):
    """reference observers/abs_max.py:48: forward records abs-max and
    passes the input through untouched (observation, not simulation)."""

    def __init__(self, layer=None, quant_bits=8):
        super().__init__(quant_bits=quant_bits)
        self._max = 1e-9
        self._scale = None

    def forward(self, x):
        data = x._data if isinstance(x, Tensor) else x
        self._max = max(self._max,
                        float(jnp.max(jnp.abs(data.astype(jnp.float32)))))
        return x

    def cal_thresholds(self):
        self._scale = self._max

    def scales(self):
        if self._scale is None:
            self.cal_thresholds()
        return Tensor(np.asarray(self._scale, np.float32))

    def quantize_weight(self, w):
        """int8 weight + f32 scale for the converted inference model."""
        scale = self.scales().numpy()
        arr = w._data if isinstance(w, Tensor) else w
        q = jnp.clip(jnp.round(arr.astype(jnp.float32) / max(scale, 1e-9)
                               * self.qmax), -self.qmax, self.qmax)
        return q.astype(jnp.int8), float(scale)

    def fake_quant(self, x):
        return fake_quant(x, self.scales(), qmax=self.qmax)


class PerChannelAbsmaxObserver(ObserverFactory):
    """Per-channel PTQ observer (ISSUE 14): one abs-max scale per
    channel along ``quant_axis`` (restricted to the LAST axis so the
    fake-quant/dequant broadcast is a plain trailing-dim multiply —
    ``Linear``'s ``[in, out]`` weight quantizes per OUTPUT channel, the
    granularity the int8 serving artifacts use)."""

    def __init__(self, quant_bits=8, quant_axis=-1):
        super().__init__(quant_bits=quant_bits, quant_axis=quant_axis)

    def _get_class(self):
        return PerChannelAbsmaxObserverLayer


class PerChannelAbsmaxObserverLayer(BaseObserver):
    """Per-channel running abs-max: forward records the elementwise max
    of per-channel abs-maxes across calibration batches and passes the
    input through untouched; ``cal_thresholds`` freezes the vector."""

    def __init__(self, layer=None, quant_bits=8, quant_axis=-1):
        super().__init__(quant_bits=quant_bits, quant_axis=quant_axis)
        if quant_axis not in (-1,):
            raise ValueError(
                "PerChannelAbsmaxObserver supports quant_axis=-1 (last "
                f"axis) only; got {quant_axis} — transpose the tensor or "
                "use the per-tensor AbsmaxObserver")
        self._max = None          # np [C], running per-channel abs-max
        self._scale = None

    def forward(self, x):
        data = x._data if isinstance(x, Tensor) else x
        arr = jnp.abs(data.astype(jnp.float32))
        cur = np.asarray(jnp.max(
            arr.reshape(-1, arr.shape[-1]), axis=0))
        self._max = cur if self._max is None else np.maximum(self._max,
                                                             cur)
        return x

    def cal_thresholds(self):
        if self._max is None:
            raise RuntimeError(
                "PerChannelAbsmaxObserver never observed data — run "
                "calibration forwards (PTQ.calibrate) before convert()")
        self._scale = np.maximum(self._max, 1e-9).astype(np.float32)

    def scales(self):
        if self._scale is None:
            self.cal_thresholds()
        return Tensor(np.asarray(self._scale, np.float32))

    def quantize_weight(self, w):
        """int8 weight + f32 per-channel scale vector [C] (quantized
        against the CALIBRATED thresholds via the shared
        :func:`~paddle_tpu.quantization.base.per_channel_int8`)."""
        arr = w._data if isinstance(w, Tensor) else w
        codes, absmax = per_channel_int8(
            np.asarray(arr), absmax=self.scales().numpy(),
            qmax=self.qmax)
        return jnp.asarray(codes), absmax

    def fake_quant(self, x):
        return fake_quant(x, self.scales(), qmax=self.qmax)
