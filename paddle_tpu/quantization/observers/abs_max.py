"""Abs-max observer (PTQ).

Reference: python/paddle/quantization/observers/abs_max.py:22 —
AbsmaxObserver collects the running max(|x|) during calibration forwards;
``cal_thresholds`` freezes it into the quantization scale.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor
from ..base import BaseObserver, fake_quant
from ..factory import ObserverFactory

__all__ = ["AbsmaxObserver", "AbsmaxObserverLayer"]


class AbsmaxObserver(ObserverFactory):
    """reference observers/abs_max.py:22."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits=quant_bits)

    def _get_class(self):
        return AbsmaxObserverLayer


class AbsmaxObserverLayer(BaseObserver):
    """reference observers/abs_max.py:48: forward records abs-max and
    passes the input through untouched (observation, not simulation)."""

    def __init__(self, layer=None, quant_bits=8):
        super().__init__(quant_bits=quant_bits)
        self._max = 1e-9
        self._scale = None

    def forward(self, x):
        data = x._data if isinstance(x, Tensor) else x
        self._max = max(self._max,
                        float(jnp.max(jnp.abs(data.astype(jnp.float32)))))
        return x

    def cal_thresholds(self):
        self._scale = self._max

    def scales(self):
        if self._scale is None:
            self.cal_thresholds()
        return Tensor(np.asarray(self._scale, np.float32))

    def quantize_weight(self, w):
        """int8 weight + f32 scale for the converted inference model."""
        scale = self.scales().numpy()
        arr = w._data if isinstance(w, Tensor) else w
        q = jnp.clip(jnp.round(arr.astype(jnp.float32) / max(scale, 1e-9)
                               * self.qmax), -self.qmax, self.qmax)
        return q.astype(jnp.int8), float(scale)

    def fake_quant(self, x):
        return fake_quant(x, self.scales(), qmax=self.qmax)
