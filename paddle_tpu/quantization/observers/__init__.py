from .abs_max import (  # noqa: F401
    AbsmaxObserver,
    AbsmaxObserverLayer,
    PerChannelAbsmaxObserver,
    PerChannelAbsmaxObserverLayer,
)

__all__ = ["AbsmaxObserver", "AbsmaxObserverLayer",
           "PerChannelAbsmaxObserver", "PerChannelAbsmaxObserverLayer"]
