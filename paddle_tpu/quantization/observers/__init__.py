from .abs_max import AbsmaxObserver, AbsmaxObserverLayer  # noqa: F401

__all__ = ["AbsmaxObserver", "AbsmaxObserverLayer"]
