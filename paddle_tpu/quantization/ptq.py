"""Post-training quantization (reference ptq.py:24 — PTQ.quantize inserts
observers; calibration forwards collect abs-max; convert freezes scales)."""

from __future__ import annotations

from .quantize import Quantization

__all__ = ["PTQ"]


class PTQ(Quantization):
    def __init__(self, config):
        super().__init__(config)

    def convert(self, model, inplace=False):
        # freeze observer thresholds before conversion
        from .base import BaseObserver

        for layer in model.sublayers(include_self=True):
            if isinstance(layer, BaseObserver):
                layer.cal_thresholds()
        return super().convert(model, inplace=inplace)
