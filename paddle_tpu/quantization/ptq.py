"""Post-training quantization (reference ptq.py:24 — PTQ.quantize inserts
observers; calibration forwards collect abs-max; convert freezes scales).

ISSUE 14 finishes the stub into the real PTQ flow::

    ptq = PTQ(QuantConfig(activation=AbsmaxObserver(),
                          weight=PerChannelAbsmaxObserver()))
    qmodel = ptq.quantize(model)          # observers wrap Linears/Convs
    ptq.calibrate(qmodel, batches)        # observer-driven calibration
    int8_model = ptq.convert(qmodel)      # genuine int8 weight freeze

``calibrate`` drives eval-mode forwards over real data so every observer
sees the activation/weight ranges it will freeze; ``convert`` then
``cal_thresholds()``-freezes every observer and swaps each simulated
``QuantedLinear`` for an ``Int8InferenceLinear`` holding int8 codes +
the dequant epilogue scale (scalar per-tensor or per-output-channel
vector, depending on the observer). The converted forward must agree
with the SIMULATED (fake-quant) forward to float-assoc precision — that
parity is the convert contract tests/test_quantization.py pins.
"""

from __future__ import annotations

from .quantize import Quantization

__all__ = ["PTQ"]


class PTQ(Quantization):
    def __init__(self, config):
        super().__init__(config)

    def calibrate(self, model, data, max_batches=None):
        """Run observer-collection forwards over ``data`` (an iterable of
        input batches; a tuple/list batch is splatted into ``model(*b)``)
        with the model in eval mode. Returns the number of batches
        observed; zero batches is an error — silent no-op calibration is
        exactly the dead-stub failure mode this replaces."""
        was_training = model.training
        model.eval()
        n = 0
        try:
            for batch in data:
                if max_batches is not None and n >= int(max_batches):
                    break
                if isinstance(batch, (tuple, list)):
                    model(*batch)
                else:
                    model(batch)
                n += 1
        finally:
            if was_training:
                model.train()
        if n == 0:
            raise ValueError(
                "PTQ.calibrate saw no batches — observers would freeze "
                "their init scales and convert() would emit garbage int8 "
                "weights; pass at least one calibration batch")
        return n

    def convert(self, model, inplace=False):
        # freeze observer thresholds before conversion
        from .base import BaseObserver

        for layer in model.sublayers(include_self=True):
            if isinstance(layer, BaseObserver):
                layer.cal_thresholds()
        return super().convert(model, inplace=inplace)
