"""Quantization base classes.

Reference: python/paddle/quantization/base_quanter.py:1 and
base_observer.py:1 — abstract Layer subclasses exposing ``scales()``,
``zero_points()``, ``bit_length`` and ``quant_axis``. TPU-native design:
fake-quantization is a pure jax op with a straight-through estimator
(x + stop_gradient(fq(x) - x)), so QAT trains through XLA with zero custom
gradients; the int8 conversion produces jnp int8 weights with a dequant
epilogue fused by XLA into the following matmul.
"""

from __future__ import annotations

import abc

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import op
from ..nn.layer.layers import Layer

__all__ = ["BaseQuanter", "BaseObserver", "fake_quant", "quant_dequant_ste",
           "per_channel_int8"]


def per_channel_int8(arr, absmax=None, qmax=127.0, floor=1e-9):
    """THE per-channel symmetric int8 quantizer (host-side numpy) —
    shared by ``PerChannelAbsmaxObserverLayer.quantize_weight`` and the
    serving artifact packer (``serving.engine.quantize_state_dict``), so
    the clipping/floor/rounding rules can never drift between the PTQ
    path and the artifact path.

    Channels are the LAST axis; ``absmax`` (per-channel [C]) defaults to
    the array's own abs-max — pass calibrated scales to quantize against
    frozen thresholds. Returns ``(codes int8, absmax f32 [C])``; dequant
    is ``codes * (absmax / qmax)`` (callers choose whether to STORE
    absmax or the pre-divided multiplier)."""
    a = np.asarray(arr, np.float32)
    if a.ndim < 2:
        raise ValueError(
            f"per_channel_int8 needs >= 2 dims (got shape {a.shape}); "
            "per-channel scales over a 1-D tensor are per-element — use "
            "a per-tensor scheme")
    if absmax is None:
        absmax = np.abs(a).max(axis=tuple(range(a.ndim - 1)))
    absmax = np.maximum(np.asarray(absmax, np.float32), floor)
    codes = np.clip(np.round(a / absmax * qmax), -qmax,
                    qmax).astype(np.int8)
    return codes, absmax


@op("fake_quant_dequant")
def fake_quant(x, scale, qmax=127.0):
    """Simulated int quantization: round(clip(x/scale*qmax)) * scale/qmax."""
    s = jnp.maximum(scale, 1e-9).astype(jnp.float32)
    xf = x.astype(jnp.float32)
    q = jnp.clip(jnp.round(xf / s * qmax), -qmax, qmax)
    return (q * (s / qmax)).astype(x.dtype)


@op("fake_quant_ste")
def quant_dequant_ste(x, scale, qmax=127.0):
    """Fake quant with a straight-through gradient (d out/d x = 1)."""
    import jax

    return x + jax.lax.stop_gradient(
        fake_quant.raw_fn(x, scale, qmax=qmax) - x)


class _QBase(Layer):
    def __init__(self, quant_bits=8, quant_axis=None):
        super().__init__()
        self._quant_bits = int(quant_bits)
        self._quant_axis = quant_axis

    @property
    def bit_length(self):
        return self._quant_bits

    @property
    def quant_axis(self):
        return self._quant_axis if self._quant_axis is not None else -1

    @property
    def qmax(self):
        return float(2 ** (self._quant_bits - 1) - 1)

    @abc.abstractmethod
    def scales(self):
        ...

    def zero_points(self):
        return None  # symmetric schemes only (abs-max family)


class BaseQuanter(_QBase):
    """reference base_quanter.py:24 — trains/simulates quantization."""


class BaseObserver(_QBase):
    """reference base_observer.py:20 — collects statistics only."""

    @abc.abstractmethod
    def cal_thresholds(self):
        ...
