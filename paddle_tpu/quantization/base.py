"""Quantization base classes.

Reference: python/paddle/quantization/base_quanter.py:1 and
base_observer.py:1 — abstract Layer subclasses exposing ``scales()``,
``zero_points()``, ``bit_length`` and ``quant_axis``. TPU-native design:
fake-quantization is a pure jax op with a straight-through estimator
(x + stop_gradient(fq(x) - x)), so QAT trains through XLA with zero custom
gradients; the int8 conversion produces jnp int8 weights with a dequant
epilogue fused by XLA into the following matmul.
"""

from __future__ import annotations

import abc

import jax.numpy as jnp

from ..core.dispatch import op
from ..nn.layer.layers import Layer

__all__ = ["BaseQuanter", "BaseObserver", "fake_quant", "quant_dequant_ste"]


@op("fake_quant_dequant")
def fake_quant(x, scale, qmax=127.0):
    """Simulated int quantization: round(clip(x/scale*qmax)) * scale/qmax."""
    s = jnp.maximum(scale, 1e-9).astype(jnp.float32)
    xf = x.astype(jnp.float32)
    q = jnp.clip(jnp.round(xf / s * qmax), -qmax, qmax)
    return (q * (s / qmax)).astype(x.dtype)


@op("fake_quant_ste")
def quant_dequant_ste(x, scale, qmax=127.0):
    """Fake quant with a straight-through gradient (d out/d x = 1)."""
    import jax

    return x + jax.lax.stop_gradient(
        fake_quant.raw_fn(x, scale, qmax=qmax) - x)


class _QBase(Layer):
    def __init__(self, quant_bits=8, quant_axis=None):
        super().__init__()
        self._quant_bits = int(quant_bits)
        self._quant_axis = quant_axis

    @property
    def bit_length(self):
        return self._quant_bits

    @property
    def quant_axis(self):
        return self._quant_axis if self._quant_axis is not None else -1

    @property
    def qmax(self):
        return float(2 ** (self._quant_bits - 1) - 1)

    @abc.abstractmethod
    def scales(self):
        ...

    def zero_points(self):
        return None  # symmetric schemes only (abs-max family)


class BaseQuanter(_QBase):
    """reference base_quanter.py:24 — trains/simulates quantization."""


class BaseObserver(_QBase):
    """reference base_observer.py:20 — collects statistics only."""

    @abc.abstractmethod
    def cal_thresholds(self):
        ...
