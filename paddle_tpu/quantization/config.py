"""Quantization configuration.

Reference: python/paddle/quantization/config.py:60 (QuantConfig —
layer/type/global quanter assignment, DEFAULT_QAT_LAYER_MAPPINGS at :33).
"""

from __future__ import annotations

from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from .wrapper import QuantedConv2D, QuantedLinear

__all__ = ["QuantConfig", "SingleLayerConfig", "DEFAULT_QAT_LAYER_MAPPINGS"]

DEFAULT_QAT_LAYER_MAPPINGS = {
    Linear: QuantedLinear,
    Conv2D: QuantedConv2D,
}


class SingleLayerConfig:
    """reference config.py:39."""

    def __init__(self, activation, weight):
        self._activation = activation
        self._weight = weight

    @property
    def activation(self):
        return self._activation

    @property
    def weight(self):
        return self._weight

    def __str__(self):
        return f"activation: {self._activation}\nweight: {self._weight}"


class QuantConfig:
    """reference config.py:60 — resolution order: per-layer (by object) >
    per-type > global default."""

    def __init__(self, activation=None, weight=None):
        if activation is None and weight is None:
            self._global_config = None
        else:
            self._global_config = SingleLayerConfig(activation, weight)
        self._layer_configs = {}  # id(layer) -> SingleLayerConfig
        self._type_configs = {}  # type -> SingleLayerConfig
        self._qat_layer_mappings = dict(DEFAULT_QAT_LAYER_MAPPINGS)

    # ---- assignment (reference add_layer_config/add_name_config etc.) ----
    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_configs[id(l)] = SingleLayerConfig(activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = (layer_type if isinstance(layer_type, (list, tuple))
                 else [layer_type])
        for t in types:
            self._type_configs[t] = SingleLayerConfig(activation, weight)

    def add_qat_layer_mapping(self, source, target):
        self._qat_layer_mappings[source] = target

    @property
    def qat_layer_mappings(self):
        return self._qat_layer_mappings

    # ---- resolution ------------------------------------------------------
    def _config_for(self, layer):
        if id(layer) in self._layer_configs:
            return self._layer_configs[id(layer)]
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        return self._global_config

    def quanted_layer_for(self, layer):
        """The wrapper class for ``layer``, or None if not quantizable."""
        for src, target in self._qat_layer_mappings.items():
            if type(layer) is src:
                return target
        return None
