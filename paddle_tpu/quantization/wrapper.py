"""Quantized layer wrappers.

Reference: python/paddle/quantization/wrapper.py:1 (ObserveWrapper) and
python/paddle/nn/quant/qat/ (QuantedLinear / QuantedConv2D — the QAT
simulation layers referenced by DEFAULT_QAT_LAYER_MAPPINGS in config.py:33).

TPU-native convert path: ``QuantedLinear.convert()`` re-expresses the layer
as int8 storage + a dequant epilogue (``(x_q · w_q) * (sx·sw/qmax²)``);
XLA fuses the dequant into the matmul consumer, which is the analog of the
reference's fused int8 gemm + dequant kernels.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import op
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.layer.layers import Layer

__all__ = ["ObserveWrapper", "QuantedLinear", "QuantedConv2D",
           "Int8InferenceLinear"]


class ObserveWrapper(Layer):
    """reference wrapper.py:23 — observes the output of a leaf layer."""

    def __init__(self, observer, observed, observe_input=False):
        super().__init__()
        self._observer = observer
        self._observed = observed
        self._observe_input = observe_input

    def forward(self, *args, **kwargs):
        if self._observe_input and args:
            args = (self._observer(args[0]),) + args[1:]
            return self._observed(*args, **kwargs)
        out = self._observed(*args, **kwargs)
        return self._observer(out)


class QuantedLinear(Layer):
    """Simulated-quantization Linear (reference nn/quant/qat/linear)."""

    def __init__(self, layer, q_config):
        super().__init__()
        self._inner = layer
        self.weight_quanter = (q_config.weight._instance(layer)
                               if q_config.weight is not None else None)
        self.activation_quanter = (q_config.activation._instance(layer)
                                   if q_config.activation is not None
                                   else None)

    # QAT/PTQ simulation forward
    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.linear(x, w, self._inner.bias)

    def convert(self):
        """Freeze into an int8-weight inference layer. ``wscale`` is a
        scalar (per-tensor quanters) or a per-output-channel vector [out]
        (``PerChannelAbsmaxObserver``) — both broadcast through the
        dequant epilogue."""
        wq, wscale = self.weight_quanter.quantize_weight(self._inner.weight)
        ascale = (self.activation_quanter.scales().numpy()
                  if self.activation_quanter is not None else None)
        return Int8InferenceLinear(wq, wscale, self._inner.bias, ascale,
                                   qmax=self.weight_quanter.qmax)


@op("int8_linear_dequant")
def _int8_linear(x, wq, wdeq, bias=None):
    """int8-weight matmul with dequant epilogue; accumulation in f32/int32
    is XLA's choice — the dequant scale folds into the epilogue. ``wdeq``
    is ``wscale / qmax`` as a traced array (0-d per-tensor, or [out]
    per-channel — both broadcast over the matmul's last dim; it rides as
    a positional tensor arg because the op dispatch keys executables on
    kwargs, which must stay hashable)."""
    xf = x.astype(jnp.float32)
    wf = wq.astype(jnp.float32)  # int8 storage; MXU consumes the upcast
    out = jnp.matmul(xf, wf) * wdeq
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


class Int8InferenceLinear(Layer):
    """Converted inference layer: int8 weights resident in HBM (4x smaller
    than f32), dequant fused into the matmul epilogue. ``wscale`` is a
    scalar (per-tensor) or a per-output-channel vector [out] — the
    epilogue multiply broadcasts either."""

    def __init__(self, wq, wscale, bias, ascale=None, qmax=127.0):
        super().__init__()
        self.register_buffer("weight_q", Tensor._wrap(wq))
        self._wscale = np.asarray(wscale, np.float32)  # () or [out]
        self._ascale = ascale
        self._qmax = float(qmax)
        # the dequant epilogue multiplier, precomputed once
        self.register_buffer(
            "weight_deq", Tensor._wrap(jnp.asarray(
                self._wscale / self._qmax, jnp.float32)))
        self.bias = bias

    @property
    def wscale(self):
        return self._wscale

    def forward(self, x):
        return _int8_linear(x, self.weight_q, self.weight_deq, self.bias)


class QuantedConv2D(Layer):
    """Simulated-quantization Conv2D (reference nn/quant/qat/conv)."""

    def __init__(self, layer, q_config):
        super().__init__()
        self._inner = layer
        self.weight_quanter = (q_config.weight._instance(layer)
                               if q_config.weight is not None else None)
        self.activation_quanter = (q_config.activation._instance(layer)
                                   if q_config.activation is not None
                                   else None)

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        inner = self._inner
        w = inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.conv2d(x, w, inner.bias, inner._stride, inner._padding,
                        inner._dilation, inner._groups, inner._data_format)
