"""Quanter/Observer factories (reference factory.py:1 — a QuanterFactory is
a picklable recipe; ``_instance(layer)`` builds the concrete quanter Layer
for one host layer).

ISSUE 14: factories are the calibration entry point — a configured
factory stamps one observer Layer per wrapped host layer, and those
instances are what ``PTQ.calibrate`` drives data through. ``_instance``
validates the recipe eagerly (a typo'd kwarg fails at quantize() time,
at the offending layer, instead of surfacing as a mid-calibration
TypeError deep in a forward).
"""

from __future__ import annotations

import inspect

__all__ = ["QuanterFactory", "ObserverFactory"]


class ObserverFactory:
    def __init__(self, **kwargs):
        self._kwargs = dict(kwargs)

    @property
    def kwargs(self):
        """The recipe (picklable plain dict) this factory stamps
        instances from."""
        return dict(self._kwargs)

    def _get_class(self):
        raise NotImplementedError(
            f"{type(self).__name__} must implement _get_class() returning "
            "the observer Layer class this factory instantiates")

    def _instance(self, layer):
        cls = self._get_class()
        # validate the SIGNATURE up front, so only genuine recipe/
        # constructor mismatches wear the "recipe" error — a TypeError
        # raised inside the constructor BODY (validating values, a
        # downstream call) propagates untouched with its real message
        try:
            inspect.signature(cls).bind(layer, **self._kwargs)
        except TypeError as e:
            raise TypeError(
                f"{type(self).__name__} recipe {self._kwargs!r} does not "
                f"match {cls.__name__}'s constructor: {e}") from e
        return cls(layer, **self._kwargs)


class QuanterFactory(ObserverFactory):
    pass
