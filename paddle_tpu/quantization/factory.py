"""Quanter/Observer factories (reference factory.py:1 — a QuanterFactory is
a picklable recipe; ``_instance(layer)`` builds the concrete quanter Layer
for one host layer)."""

from __future__ import annotations

__all__ = ["QuanterFactory", "ObserverFactory"]


class ObserverFactory:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def _get_class(self):
        raise NotImplementedError

    def _instance(self, layer):
        return self._get_class()(layer, **self._kwargs)


class QuanterFactory(ObserverFactory):
    pass
