"""Quantization-aware training (reference qat.py:23 — QAT.quantize inserts
fake quanters; training then runs with the straight-through estimator)."""

from __future__ import annotations

from .quantize import Quantization

__all__ = ["QAT"]


class QAT(Quantization):
    def __init__(self, config):
        super().__init__(config)
