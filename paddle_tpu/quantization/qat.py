"""Quantization-aware training (reference qat.py:23 — QAT.quantize inserts
fake quanters; training then runs with the straight-through estimator).

ISSUE 14 satellite: the 13-line stub silently imported as a no-op —
``QAT.convert`` now genuinely freezes the TRAINED moving-average scales
into int8 inference layers, and converting a model whose quanters never
observed data raises a typed error instead of emitting garbage codes
quantized against the init scale.
"""

from __future__ import annotations

from .quantize import Quantization

__all__ = ["QAT", "UncalibratedQuanterError"]


class UncalibratedQuanterError(RuntimeError):
    """A fake quanter reached ``convert`` without ever observing a
    batch — no training/calibration forward updated its moving-average
    abs-max, so the frozen int8 weights would be quantized against a
    meaningless range. (The check is the quanter's observed-batch
    count, not a scale sentinel: all-zero training data legitimately
    leaves the scale at its floor and must still convert.)"""


class QAT(Quantization):
    def __init__(self, config):
        super().__init__(config)

    def convert(self, model, inplace=False):
        """Freeze the trained quanters into int8 inference layers.

        The fake quanters' moving-average abs-max IS the calibration —
        training forwards updated it — so convert is a plain freeze; the
        guard below catches the silent-no-op shape (quantize() -> never
        trained -> convert()) with a typed error pointing at the fix.
        """
        from .quanters.abs_max import FakeQuanterWithAbsMaxObserverLayer

        for name, layer in model.named_sublayers(include_self=True):
            if isinstance(layer, FakeQuanterWithAbsMaxObserverLayer) \
                    and layer._observed == 0:
                raise UncalibratedQuanterError(
                    f"quanter at {name!r} never observed a batch — run "
                    "training (or at least one forward pass in train "
                    "mode) between QAT.quantize() and QAT.convert() so "
                    "the moving-average abs-max observes real data")
        return super().convert(model, inplace=inplace)
