"""Fake quanter with moving-average abs-max observer (QAT).

Reference: python/paddle/quantization/quanters/abs_max.py:27
(FakeQuanterWithAbsMaxObserver, moving_rate ema of abs-max; dynamic_forward
updates state in training, static_forward uses the frozen scale).

TPU-native: the quant-dequant runs as one fused jax op with a
straight-through estimator, so QAT backprop is ordinary XLA; the ema scale
is host state updated from the (eager) forward — under ``jit``/to_static
the frozen scale is traced as a constant, matching the reference's
static_forward semantics.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor
from ..base import BaseQuanter, fake_quant, quant_dequant_ste
from ..factory import QuanterFactory

__all__ = ["FakeQuanterWithAbsMaxObserver",
           "FakeQuanterWithAbsMaxObserverLayer"]


class FakeQuanterWithAbsMaxObserver(QuanterFactory):
    """reference quanters/abs_max.py:27."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__(moving_rate=moving_rate, bit_length=bit_length)

    def _get_class(self):
        return FakeQuanterWithAbsMaxObserverLayer


class FakeQuanterWithAbsMaxObserverLayer(BaseQuanter):
    """reference quanters/abs_max.py:96."""

    def __init__(self, layer=None, moving_rate=0.9, bit_length=8):
        super().__init__(quant_bits=bit_length)
        self._moving_rate = float(moving_rate)
        self._state = 1.0
        self._accum = 1.0
        self._scale = 1e-9
        # batches this quanter has observed — QAT.convert's calibration
        # guard checks THIS, not a magic scale value (all-zero training
        # data legitimately leaves the scale at its floor)
        self._observed = 0

    def _update(self, x):
        self._observed += 1
        data = x._data if isinstance(x, Tensor) else x
        cur = float(jnp.max(jnp.abs(data.astype(jnp.float32))))
        r = self._moving_rate
        # reference dynamic_forward accumulator form: scale is a bias-
        # corrected ema of the per-batch abs-max
        self._state = r * self._state + 1.0
        self._accum = r * self._accum + cur
        self._scale = max(self._accum / self._state, 1e-9)

    def forward(self, x):
        import jax

        data = x._data if isinstance(x, Tensor) else x
        if self.training and not isinstance(data, jax.core.Tracer):
            self._update(x)
        scale = Tensor(np.asarray(self._scale, np.float32))
        if self.training:
            return quant_dequant_ste(x, scale, qmax=self.qmax)
        return fake_quant(x, scale, qmax=self.qmax)

    def scales(self):
        return Tensor(np.asarray(self._scale, np.float32))

    def cal_thresholds(self):
        pass

    def quantize_weight(self, w):
        scale = float(self._scale)
        arr = w._data if isinstance(w, Tensor) else w
        q = jnp.clip(jnp.round(arr.astype(jnp.float32) / max(scale, 1e-9)
                               * self.qmax), -self.qmax, self.qmax)
        return q.astype(jnp.int8), scale
