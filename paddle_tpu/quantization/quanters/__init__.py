from .abs_max import (  # noqa: F401
    FakeQuanterWithAbsMaxObserver,
    FakeQuanterWithAbsMaxObserverLayer,
)

__all__ = ["FakeQuanterWithAbsMaxObserver",
           "FakeQuanterWithAbsMaxObserverLayer"]
