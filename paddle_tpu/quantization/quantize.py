"""Quantization driver: walk the model, wrap quantizable layers.

Reference: python/paddle/quantization/quantize.py:1 (Quantization base —
quantize()/convert() over the layer tree).
"""

from __future__ import annotations

import copy

from ..nn.layer.layers import Layer
from .wrapper import QuantedConv2D, QuantedLinear

__all__ = ["Quantization"]


class Quantization:
    def __init__(self, config):
        self._config = config

    def quantize(self, model: Layer, inplace=False):
        if not inplace:
            model = copy.deepcopy(model)
        self._wrap_children(model)
        return model

    def _wrap_children(self, module: Layer):
        for name, child in list(module._sub_layers.items()):
            target = self._config.quanted_layer_for(child)
            cfg = self._config._config_for(child)
            if target is not None and cfg is not None:
                module._sub_layers[name] = target(child, cfg)
            else:
                self._wrap_children(child)

    def convert(self, model: Layer, inplace=False):
        """Freeze simulated quantization into int8 inference layers."""
        if not inplace:
            model = copy.deepcopy(model)
        self._convert_children(model)
        return model

    def _convert_children(self, module: Layer):
        for name, child in list(module._sub_layers.items()):
            if isinstance(child, (QuantedLinear,)) and \
                    child.weight_quanter is not None:
                module._sub_layers[name] = child.convert()
            else:
                self._convert_children(child)
