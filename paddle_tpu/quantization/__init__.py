"""paddle.quantization — QAT + PTQ over the layer tree.

Reference package: python/paddle/quantization/ (config.py, qat.py, ptq.py,
quanters/, observers/, wrapper.py). The imperative pre-2.0 API
(quantization/imperative/qat.py) collapses into the same wrappers here.
"""

from .base import BaseObserver, BaseQuanter  # noqa: F401
from .config import (  # noqa: F401
    DEFAULT_QAT_LAYER_MAPPINGS,
    QuantConfig,
    SingleLayerConfig,
)
from .factory import ObserverFactory, QuanterFactory  # noqa: F401
from .ptq import PTQ  # noqa: F401
from .qat import QAT, UncalibratedQuanterError  # noqa: F401
from .quantize import Quantization  # noqa: F401
from .wrapper import (  # noqa: F401
    Int8InferenceLinear,
    ObserveWrapper,
    QuantedConv2D,
    QuantedLinear,
)
from . import observers, quanters  # noqa: F401

__all__ = [
    "QuantConfig", "SingleLayerConfig", "QAT", "PTQ", "Quantization",
    "UncalibratedQuanterError",
    "BaseQuanter", "BaseObserver", "QuanterFactory", "ObserverFactory",
    "ObserveWrapper", "QuantedLinear", "QuantedConv2D",
    "Int8InferenceLinear", "observers", "quanters",
]


def quanter(name):
    """Class decorator registering a custom quanter factory (reference
    quantization/factory.py quanter: creates a <name> QuanterFactory bound
    to the decorated BaseQuanter subclass). The factory is a module-level
    QuanterFactory subclass, so configured instances stay picklable."""
    def deco(cls):
        import sys

        from .factory import QuanterFactory

        mod = sys.modules[__name__]
        factory = type(name, (QuanterFactory,),
                       {"_get_class": lambda self, _cls=cls: _cls,
                        "__module__": __name__})
        setattr(mod, name, factory)
        if name not in __all__:
            __all__.append(name)
        return cls

    return deco


__all__.append("quanter")
