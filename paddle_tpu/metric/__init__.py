"""paddle.metric — streaming metrics (reference: python/paddle/metric/metrics.py).

Metrics accumulate on the host in numpy: they sit outside the compiled step
(device work ends at logits/loss), so there is nothing TPU-specific to do —
per-batch tensors sync once and the O(batch) bookkeeping stays off-chip.
"""

from __future__ import annotations

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


def _to_np(x):
    return x.numpy() if hasattr(x, "numpy") else np.asarray(x)


class Metric:
    """Base class (ref metrics.py Metric): reset/update/accumulate/name,
    plus compute() preprocessing logits+labels into update() inputs."""

    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    """Top-k accuracy (ref metrics.py:183)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _to_np(pred)
        label = _to_np(label)
        idx = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:
            if label.shape[-1] == pred.shape[-1] and pred.shape[-1] > 1:
                label = label.argmax(-1)  # one-hot -> index
            else:
                label = label.reshape(label.shape[:-1])  # [N, 1] -> [N]
        correct = idx == label.reshape(label.shape + (1,))
        return correct

    def update(self, correct, *args):
        correct = _to_np(correct)
        accs = []
        num = int(np.prod(correct.shape[:-1]))
        for k in self.topk:
            c = correct[..., :k].sum()
            accs.append(c / max(num, 1))
            self.total[self.topk.index(k)] += c
            self.count[self.topk.index(k)] += num
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0 for t, c in zip(self.total,
                                                       self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision over 0/1 preds at 0.5 (ref metrics.py:300)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds).reshape(-1)
        labels = _to_np(labels).reshape(-1)
        pred_pos = preds > 0.5
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fp += int(np.sum(pred_pos & (labels == 0)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (ref metrics.py:384)."""

    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds).reshape(-1)
        labels = _to_np(labels).reshape(-1)
        pred_pos = preds > 0.5
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fn += int(np.sum(~pred_pos & (labels == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via histogram buckets (ref metrics.py:459 — same
    thresholded-statistics approach, numpy instead of CUDA kernels)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.reshape(-1)
        idx = np.clip((pos_prob * self.num_thresholds).astype(np.int64),
                      0, self.num_thresholds)
        pos = labels == 1
        np.add.at(self._stat_pos, idx[pos], 1)
        np.add.at(self._stat_neg, idx[~pos], 1)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        # walk thresholds high->low accumulating TP/FP; trapezoid area
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        tpr = np.concatenate([[0.0], tpr])
        fpr = np.concatenate([[0.0], fpr])
        trapezoid = getattr(np, "trapezoid", None) or np.trapz
        return float(trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference python/paddle/metric/metrics.py
    accuracy :~800): input [N, C] scores, label [N, 1] or [N] int ids."""
    from ..ops.manipulation import topk

    _, pred = topk(input, int(k), axis=-1)
    lab = label.reshape([-1, 1])
    hit = (pred.astype("int64") == lab.astype("int64"))
    acc = hit.astype("float32").sum(axis=-1).mean()
    return acc


__all__.append("accuracy")
