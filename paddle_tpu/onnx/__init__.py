"""paddle.onnx — ONNX model export (reference python/paddle/onnx/export.py).

Dependency-free: the wire bytes are written directly (the image has no
``onnx``/``paddle2onnx``); see wire.py / export.py.
"""

from .export import export  # noqa: F401

__all__ = ["export"]
