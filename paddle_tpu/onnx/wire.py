"""Minimal protobuf wire-format writer/reader for ONNX serialization.

The image ships no ``onnx`` package and the local protoc's gencode is
rejected by the installed protobuf runtime, so the exporter writes the ONNX
``ModelProto`` wire bytes directly. Only the message fields ONNX needs are
modeled (the ONNX IR spec, onnx/onnx.proto): varint, length-delimited and
fixed32 wire types.

The generic reader exists for tests (and debugging): it parses any wire
stream back into {field_number: [values]} dicts without a schema.
"""

from __future__ import annotations

import struct

__all__ = ["Msg", "parse", "TensorDtype"]


class TensorDtype:
    """ONNX TensorProto.DataType values."""

    FLOAT = 1
    UINT8 = 2
    INT8 = 3
    INT16 = 5
    INT32 = 6
    INT64 = 7
    BOOL = 9
    FLOAT16 = 10
    DOUBLE = 11
    UINT32 = 12
    UINT64 = 13
    BFLOAT16 = 16

    _NP = {
        "float32": FLOAT, "uint8": UINT8, "int8": INT8, "int16": INT16,
        "int32": INT32, "int64": INT64, "bool": BOOL, "float16": FLOAT16,
        "float64": DOUBLE, "uint32": UINT32, "uint64": UINT64,
        "bfloat16": BFLOAT16,
    }

    @classmethod
    def from_numpy(cls, dtype):
        name = str(dtype)
        if name not in cls._NP:
            raise ValueError(f"no ONNX dtype for {name}")
        return cls._NP[name]


def _varint(v: int) -> bytes:
    if v < 0:
        v &= (1 << 64) - 1  # two's complement, 64-bit
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class Msg:
    """A protobuf message under construction."""

    def __init__(self):
        self._buf = bytearray()

    def _tag(self, field: int, wire: int):
        self._buf += _varint((field << 3) | wire)

    def int(self, field: int, v: int):
        self._tag(field, 0)
        self._buf += _varint(int(v))
        return self

    def ints(self, field: int, vs):
        for v in vs:
            self.int(field, v)
        return self

    def float(self, field: int, v: float):
        self._tag(field, 5)
        self._buf += struct.pack("<f", float(v))
        return self

    def bytes(self, field: int, v: bytes):
        self._tag(field, 2)
        self._buf += _varint(len(v))
        self._buf += v
        return self

    def str(self, field: int, v: str):
        return self.bytes(field, v.encode("utf-8"))

    def msg(self, field: int, m: "Msg"):
        return self.bytes(field, m.tobytes())

    def msgs(self, field: int, ms):
        for m in ms:
            self.msg(field, m)
        return self

    def tobytes(self) -> bytes:
        return bytes(self._buf)


def parse(data: bytes):
    """Schema-less decode: {field: [raw values]}; length-delimited values
    stay bytes (recurse with parse() where a submessage is expected)."""
    out: dict[int, list] = {}
    i, n = 0, len(data)

    def rv():
        nonlocal i
        shift, val = 0, 0
        while True:
            b = data[i]
            i += 1
            val |= (b & 0x7F) << shift
            if not b & 0x80:
                return val
            shift += 7

    while i < n:
        key = rv()
        field, wire = key >> 3, key & 7
        if wire == 0:
            val = rv()
        elif wire == 2:
            ln = rv()
            val = data[i: i + ln]
            i += ln
        elif wire == 5:
            val = struct.unpack("<f", data[i: i + 4])[0]
            i += 4
        elif wire == 1:
            val = struct.unpack("<d", data[i: i + 8])[0]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append(val)
    return out
