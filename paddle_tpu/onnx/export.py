"""ONNX export: tape slice -> ONNX ModelProto bytes.

Reference: python/paddle/onnx/export.py:1 (delegates to paddle2onnx over a
static Program). TPU-native: the eager tape (core/engine.py GradNode DAG —
the same graph paddle.static.Executor replays) is converted node-by-node to
ONNX operators and serialized with the dependency-free wire writer. Layer
parameters become named initializers; unmapped ops raise listing the op, so
an unsupported model fails loudly instead of exporting garbage.
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from .wire import Msg, TensorDtype

__all__ = ["export"]

_OPSET = 17


def _tensor_proto(name, arr):
    arr = np.ascontiguousarray(arr)
    dt = TensorDtype.from_numpy(arr.dtype)
    if str(arr.dtype) == "bfloat16":  # raw little-endian u16 payload
        arr = arr.view(np.uint16)
    t = Msg()
    t.ints(1, arr.shape)
    t.int(2, dt)
    t.str(8, name)
    t.bytes(9, arr.tobytes())
    return t


def _value_info(name, shape, dtype, dynamic_batch=False):
    shp = Msg()
    for i, s in enumerate(shape):
        d = Msg()
        if dynamic_batch and i == 0:
            d.str(2, "batch")
        else:
            d.int(1, int(s))
        shp.msg(1, d)
    tt = Msg().int(1, TensorDtype.from_numpy(np.dtype(dtype))).msg(2, shp)
    return Msg().str(1, name).msg(2, Msg().msg(1, tt))


def _attr_i(name, v):
    return Msg().str(1, name).int(3, int(v)).int(20, 2)


def _attr_f(name, v):
    return Msg().str(1, name).float(2, float(v)).int(20, 1)


def _attr_ints(name, vs):
    return Msg().str(1, name).ints(8, [int(v) for v in vs]).int(20, 7)


def _node(op_type, inputs, outputs, attrs=(), name=""):
    n = Msg()
    for i in inputs:
        n.str(1, i)
    for o in outputs:
        n.str(2, o)
    if name:
        n.str(3, name)
    n.str(4, op_type)
    for a in attrs:
        n.msg(5, a)
    return n


class _Ctx:
    """Conversion state: value names, shapes, collected nodes/initializers."""

    def __init__(self):
        self.nodes = []
        self.initializers = []
        self.shapes = {}  # value name -> shape tuple
        self._const_cache = {}  # id(arr) -> name
        self._keepalive = []  # pins cached arrays: id() reuse after free
        # would alias different constants to one initializer
        self._tmp = 0
        self.param_names = {}  # id(arr) -> friendly name
        self.min_opset = 13  # raised by emitters that need newer ops

    def tmp(self, hint="t"):
        self._tmp += 1
        return f"{hint}_{self._tmp}"

    def const(self, arr, hint="const"):
        key = id(arr)
        if key in self._const_cache:
            return self._const_cache[key]
        self._keepalive.append(arr)
        name = self.param_names.get(key) or self.tmp(hint)
        self.initializers.append(_tensor_proto(name, np.asarray(arr)))
        self._const_cache[key] = name
        self.shapes[name] = tuple(np.asarray(arr).shape)
        return name

    def need_opset(self, v):
        self.min_opset = max(self.min_opset, v)

    def const_i64(self, values, hint="shape"):
        return self.const(np.asarray(values, np.int64), hint)

    def emit(self, op_type, inputs, n_out=1, attrs=(), hint=None):
        outs = [self.tmp(hint or op_type.lower()) for _ in range(n_out)]
        self.nodes.append(_node(op_type, inputs, outs, attrs))
        return outs[0] if n_out == 1 else outs


def _perm_swap_last(rank):
    p = list(range(rank))
    p[-1], p[-2] = p[-2], p[-1]
    return p


# --- op emitters: (ctx, in_names, kwargs, node) -> output value name -------

def _e_linear(ctx, ins, kw, node):
    out = ctx.emit("MatMul", [ins[0], ins[1]])
    if len(ins) > 2 and ins[2] is not None:
        out = ctx.emit("Add", [out, ins[2]])
    return out


def _e_matmul(ctx, ins, kw, node):
    x, y = ins[0], ins[1]
    if kw.get("transpose_x"):
        x = ctx.emit("Transpose", [x],
                     attrs=[_attr_ints("perm",
                                       _perm_swap_last(len(ctx.shapes[x])))])
        ctx.shapes[x] = ctx.shapes[ins[0]][:-2] + ctx.shapes[ins[0]][-1:] \
            + ctx.shapes[ins[0]][-2:-1]
    if kw.get("transpose_y"):
        y0 = y
        y = ctx.emit("Transpose", [y],
                     attrs=[_attr_ints("perm",
                                       _perm_swap_last(len(ctx.shapes[y0])))])
    return ctx.emit("MatMul", [x, y])


def _e_binary(onnx_op):
    def e(ctx, ins, kw, node):
        return ctx.emit(onnx_op, [ins[0], ins[1]])
    return e


def _e_unary(onnx_op):
    def e(ctx, ins, kw, node):
        return ctx.emit(onnx_op, [ins[0]])
    return e


def _e_softmax(onnx_op):
    def e(ctx, ins, kw, node):
        return ctx.emit(onnx_op, [ins[0]],
                        attrs=[_attr_i("axis", kw.get("axis", -1))])
    return e


def _reshape_target(ctx, in_name, kw, node):
    """Batch-safe Reshape target: a leading dim the op preserves becomes 0
    (ONNX 'copy input dim'), so a symbolic batch survives export instead of
    being baked to the traced batch=1."""
    shape = list(kw.get("shape") or node.out_avals[0][0])
    shape = [-1 if s in (None, -1) else int(s) for s in shape]
    in_shape = ctx.shapes.get(in_name)
    if (in_shape and shape and -1 not in shape
            and shape[0] == in_shape[0]):
        shape[0] = 0
    return shape


def _e_reshape(ctx, ins, kw, node):
    return ctx.emit("Reshape",
                    [ins[0],
                     ctx.const_i64(_reshape_target(ctx, ins[0], kw, node))])


def _e_flatten(ctx, ins, kw, node):
    start = kw.get("start_axis", 1)
    stop = kw.get("stop_axis", -1)
    ndim = len(ctx.shapes.get(ins[0], node.out_avals[0][0]))
    if stop in (-1, ndim - 1):
        # [0]*start + [-1]: copies every leading dim, infers the rest
        target = [0] * int(start) + [-1]
    else:
        target = _reshape_target(ctx, ins[0], {}, node)
    return ctx.emit("Reshape", [ins[0], ctx.const_i64(target)])


def _e_transpose(ctx, ins, kw, node):
    return ctx.emit("Transpose", [ins[0]],
                    attrs=[_attr_ints("perm", kw["perm"])])


def _e_concat(ctx, ins, kw, node):
    return ctx.emit("Concat", [i for i in ins if i is not None],
                    attrs=[_attr_i("axis", kw.get("axis", 0))])


def _e_embedding(ctx, ins, kw, node):
    # ONNX Gather(data=weight, indices=ids)
    return ctx.emit("Gather", [ins[1], ins[0]], attrs=[_attr_i("axis", 0)])


def _e_cast(ctx, ins, kw, node):
    to = TensorDtype.from_numpy(np.dtype(str(node.out_avals[0][1])))
    return ctx.emit("Cast", [ins[0]], attrs=[_attr_i("to", to)])


def _e_scale(ctx, ins, kw, node):
    dt = np.dtype(str(node.out_avals[0][1]))
    s = kw.get("scale", 1.0)
    b = kw.get("bias", 0.0)
    out = ins[0]
    if not kw.get("bias_after_scale", True):
        out = ctx.emit("Add", [out, ctx.const(np.asarray(b, dt))])
        return ctx.emit("Mul", [out, ctx.const(np.asarray(s, dt))])
    out = ctx.emit("Mul", [out, ctx.const(np.asarray(s, dt))])
    if b:
        out = ctx.emit("Add", [out, ctx.const(np.asarray(b, dt))])
    return out


def _e_reduce(onnx_op):
    def e(ctx, ins, kw, node):
        ctx.need_opset(18)  # axes-as-input reduce signatures
        axis = kw.get("axis")
        keep = 1 if kw.get("keepdim") else 0
        if axis is None:
            return ctx.emit(onnx_op, [ins[0]],
                            attrs=[_attr_i("keepdims", keep)])
        axes = [axis] if isinstance(axis, int) else list(axis)
        return ctx.emit(onnx_op, [ins[0], ctx.const_i64(axes, "axes")],
                        attrs=[_attr_i("keepdims", keep)])
    return e


def _e_conv(ctx, ins, kw, node):
    if kw.get("channel_last"):
        raise NotImplementedError("ONNX export supports NCHW conv only")
    w_shape = ctx.shapes[ins[1]]
    nd = len(w_shape) - 2
    stride = list(kw.get("stride", (1,) * nd))
    dil = list(kw.get("dilation", (1,) * nd))
    padding = kw.get("padding", "VALID")
    attrs = [_attr_ints("strides", stride), _attr_ints("dilations", dil),
             _attr_i("group", kw.get("groups", 1)),
             _attr_ints("kernel_shape", w_shape[2:])]
    if isinstance(padding, str):
        attrs.append(Msg().str(1, "auto_pad").bytes(
            4, (b"SAME_UPPER" if padding.upper() == "SAME"
                else b"VALID")).int(20, 3))
    else:
        begins = [p[0] for p in padding]
        ends = [p[1] for p in padding]
        attrs.append(_attr_ints("pads", begins + ends))
    inputs = [ins[0], ins[1]]
    if len(ins) > 2 and ins[2] is not None:
        inputs.append(ins[2])
    return ctx.emit("Conv", inputs, attrs=attrs)


def _e_pool(onnx_op):
    def e(ctx, ins, kw, node):
        ksize = list(kw.get("ksize", (2, 2)))
        stride = list(kw.get("stride", ksize))
        padding = kw.get("padding", ((0, 0),) * len(ksize))
        attrs = [_attr_ints("kernel_shape", ksize),
                 _attr_ints("strides", stride)]
        if isinstance(padding, str):
            attrs.append(Msg().str(1, "auto_pad").bytes(
                4, (b"SAME_UPPER" if padding.upper() == "SAME"
                    else b"VALID")).int(20, 3))
        else:
            begins = [p[0] for p in padding]
            ends = [p[1] for p in padding]
            attrs.append(_attr_ints("pads", begins + ends))
        if kw.get("ceil_mode"):
            attrs.append(_attr_i("ceil_mode", 1))
        return ctx.emit(onnx_op, [ins[0]], attrs=attrs)
    return e


def _e_batch_norm(ctx, ins, kw, node):
    x, mean, var = ins[0], ins[1], ins[2]
    ch = ctx.shapes[mean][0]
    dt = np.dtype(str(node.out_avals[0][1]))
    scale = (ins[3] if len(ins) > 3 and ins[3] is not None
             else ctx.const(np.ones(ch, dt), "bn_scale"))
    bias = (ins[4] if len(ins) > 4 and ins[4] is not None
            else ctx.const(np.zeros(ch, dt), "bn_bias"))
    return ctx.emit("BatchNormalization", [x, scale, bias, mean, var],
                    attrs=[_attr_f("epsilon", kw.get("epsilon", 1e-5))])


def _e_layer_norm(ctx, ins, kw, node):
    dt = np.dtype(str(node.out_avals[0][1]))
    axis = kw.get("begin_norm_axis", -1)
    norm_shape = node.out_avals[0][0][axis:] if axis != -1 \
        else node.out_avals[0][0][-1:]
    scale = (ins[1] if len(ins) > 1 and ins[1] is not None
             else ctx.const(np.ones(norm_shape, dt), "ln_scale"))
    inputs = [ins[0], scale]
    if len(ins) > 2 and ins[2] is not None:
        inputs.append(ins[2])
    ctx.need_opset(17)  # LayerNormalization
    return ctx.emit("LayerNormalization", inputs,
                    attrs=[_attr_i("axis", axis),
                           _attr_f("epsilon", kw.get("epsilon", 1e-5))])


def _e_rms_norm(ctx, ins, kw, node):
    # decompose: x * rsqrt(mean(x^2) + eps) * w
    ctx.need_opset(18)  # axes-as-input ReduceMean
    dt = np.dtype(str(node.out_avals[0][1]))
    sq = ctx.emit("Mul", [ins[0], ins[0]])
    mean = ctx.emit("ReduceMean", [sq, ctx.const_i64([-1], "axes")],
                    attrs=[_attr_i("keepdims", 1)])
    eps = ctx.const(np.asarray(kw.get("epsilon", 1e-6), dt), "eps")
    denom = ctx.emit("Sqrt", [ctx.emit("Add", [mean, eps])])
    out = ctx.emit("Div", [ins[0], denom])
    if len(ins) > 1 and ins[1] is not None:
        out = ctx.emit("Mul", [out, ins[1]])
    return out


def _e_dropout(ctx, ins, kw, node):
    if kw.get("training", False) and kw.get("p", 0.0) > 0:
        raise NotImplementedError(
            "export the model in eval() mode (dropout was traced training)")
    return ctx.emit("Identity", [ins[0]])


def _e_squeeze(onnx_op):
    def e(ctx, ins, kw, node):
        axis = kw.get("axis")
        if axis is None:
            return ctx.emit(onnx_op, [ins[0]])
        axes = [axis] if isinstance(axis, int) else list(axis)
        return ctx.emit(onnx_op, [ins[0], ctx.const_i64(axes, "axes")])
    return e


_EMITTERS = {
    "linear_op": _e_linear,
    "matmul": _e_matmul,
    "add": _e_binary("Add"),
    "subtract": _e_binary("Sub"),
    "multiply": _e_binary("Mul"),
    "divide": _e_binary("Div"),
    "elementwise_pow": _e_binary("Pow"),
    "maximum": _e_binary("Max"),
    "minimum": _e_binary("Min"),
    "relu": _e_unary("Relu"),
    "sigmoid": _e_unary("Sigmoid"),
    "tanh": _e_unary("Tanh"),
    "gelu": lambda ctx, ins, kw, node: (
        ctx.need_opset(20) or ctx.emit("Gelu", [ins[0]])),
    "exp": _e_unary("Exp"),
    "log": _e_unary("Log"),
    "sqrt": _e_unary("Sqrt"),
    "abs": _e_unary("Abs"),
    "neg": _e_unary("Neg"),
    "floor": _e_unary("Floor"),
    "ceil": _e_unary("Ceil"),
    "erf": _e_unary("Erf"),
    "reciprocal": _e_unary("Reciprocal"),
    "sign": _e_unary("Sign"),
    "softplus": _e_unary("Softplus"),
    "leaky_relu": _e_unary("LeakyRelu"),
    "softmax_f": _e_softmax("Softmax"),
    "log_softmax_f": _e_softmax("LogSoftmax"),
    "reshape": _e_reshape,
    "flatten": _e_flatten,
    "transpose": _e_transpose,
    "concat_n": _e_concat,
    "embedding_op": _e_embedding,
    "cast": _e_cast,
    "scale": _e_scale,
    "mean": _e_reduce("ReduceMean"),
    "sum": _e_reduce("ReduceSum"),
    "max": _e_reduce("ReduceMax"),
    "min": _e_reduce("ReduceMin"),
    "conv_nd": _e_conv,
    "max_pool_nd": _e_pool("MaxPool"),
    "avg_pool_nd": _e_pool("AveragePool"),
    "batch_norm_infer": _e_batch_norm,
    "layer_norm_op": _e_layer_norm,
    "rms_norm_op": _e_rms_norm,
    "dropout_op": _e_dropout,
    "squeeze": _e_squeeze("Squeeze"),
    "unsqueeze": _e_squeeze("Unsqueeze"),
}


def export(layer, path, input_spec=None, opset_version=_OPSET, **configs):
    """reference onnx/export.py:export — write ``path + '.onnx'``.

    Traces ``layer`` with placeholders from ``input_spec`` (InputSpec or
    example Tensors; dynamic dims become a symbolic 'batch' dimension in the
    ONNX graph), converts the tape to ONNX nodes, and serializes."""
    from ..static import _collect_nodes
    from ..static.input_spec import InputSpec

    assert input_spec, "onnx.export requires input_spec"
    placeholders = []
    dynamic = []
    for i, sp in enumerate(input_spec):
        if isinstance(sp, Tensor):
            sp = InputSpec.from_tensor(sp)
        shape = [1 if (s is None or s == -1) else int(s) for s in sp.shape]
        dynamic.append(any(s is None or s == -1 for s in sp.shape))
        t = Tensor(np.zeros(shape, sp.dtype.name if hasattr(sp.dtype, "name")
                            else str(sp.dtype)))
        t.stop_gradient = False
        t.name = getattr(sp, "name", None) or f"x{i}"
        placeholders.append(t)

    # plain eager forward: the ops land on the autograd tape, which is the
    # graph being exported (NOT a jax trace — arrays must stay concrete so
    # constants become initializers)
    was_training = layer.training
    layer.eval()
    try:
        out = layer(*placeholders)
    finally:
        if was_training:
            layer.train()
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    outs = [o for o in outs if isinstance(o, Tensor)]

    ctx = _Ctx()
    for pname, p in layer.named_parameters():
        ctx.param_names[id(p._data)] = pname
    for bname, b in layer.named_buffers():
        ctx.param_names[id(b._data)] = bname

    feed_ids = {id(t._data): t.name for t in placeholders}
    nodes = _collect_nodes(outs)
    if not nodes:
        raise ValueError("the traced forward recorded no differentiable ops "
                         "— nothing to export")
    value_of = {}  # (node_id, out_idx) -> onnx value name
    for t in placeholders:
        ctx.shapes[t.name] = tuple(t._data.shape)

    for n in nodes:
        from ..core.dispatch import _unhash_dtype

        kw = {k: _unhash_dtype(v) for k, v in (n.op_kwargs or ())}
        ins = []
        for p, e in zip(n.primals, n.edges):
            if e.node is not None:
                ins.append(value_of[(e.node.id, e.out_idx)])
            elif p is None:
                ins.append(None)
            elif id(p) in feed_ids:
                ins.append(feed_ids[id(p)])
            else:
                ins.append(ctx.const(p, "w"))
        if n.name not in _EMITTERS:
            raise NotImplementedError(
                f"ONNX export has no emitter for op {n.name!r} (supported: "
                f"{sorted(_EMITTERS)})")
        if n.n_out > 1:
            raise NotImplementedError(
                f"multi-output op {n.name!r} in ONNX export")
        out_name = _EMITTERS[n.name](ctx, ins, kw, n)
        value_of[(n.id, 0)] = out_name
        ctx.shapes[out_name] = tuple(n.out_avals[0][0])

    graph = Msg()
    for nd in ctx.nodes:
        graph.msg(1, nd)
    graph.str(2, "paddle_tpu_graph")
    for init in ctx.initializers:
        graph.msg(5, init)
    for i, t in enumerate(placeholders):
        graph.msg(11, _value_info(t.name, t._data.shape,
                                  str(t._data.dtype), dynamic[i]))
    out_names = []
    for i, t in enumerate(outs):
        name = (value_of[(t._node.id, t._out_idx)]
                if t._node is not None else feed_ids.get(id(t._data)))
        out_names.append(name)
        graph.msg(12, _value_info(name, t._data.shape, str(t._data.dtype)))

    model = Msg()
    model.int(1, 8)  # ir_version
    model.str(2, "paddle_tpu")
    model.msg(7, graph)
    # ops used may require a newer opset than requested (Gelu: 20,
    # axes-as-input reduces: 18) — declare what the graph actually needs
    model.msg(8, Msg().str(1, "").int(
        2, max(int(opset_version), ctx.min_opset)))

    out_path = path if path.endswith(".onnx") else path + ".onnx"
    import os

    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "wb") as f:
        f.write(model.tobytes())
    return out_path
