"""paddle.inference.serving — TPU-native LLM serving engine (ISSUE 7).

A real serving path for the flagship llama models: block-allocated paged
KV cache (``kv_cache``), a ragged paged-attention decode kernel with a
pure-lax CPU fallback (``paged_attention`` + ``ops/pallas``), a
continuous-batching scheduler with prefill/decode split (``scheduler``),
and the ``LLMEngine`` front-end (``engine``). Device-resident decode
(ISSUE 18) keeps the steady-state loop on the accelerator: greedy
sampling runs in-graph (``in_graph_sampling=True``) and
``decode_steps_per_sync=k`` fuses k decode iterations into one compiled
window so the host fetches ``[B, k]`` int32 tokens per round-trip
instead of ``[B, V]`` f32 logits per token. See DESIGN_DECISIONS.md
"Paged KV cache & continuous batching" + "Device-resident decode" and
the README serving recipe.
"""

from .errors import (  # noqa: F401
    DeadlineInfeasibleError, EngineClosedError, FleetOverloadedError,
    KVTransferError, ReplicaCrashLoopError, RequestTimeoutError,
    TenantQuotaExceededError,
)
from .kv_cache import (  # noqa: F401
    BlockAllocator, HostKVTier, KV_QMAX, PagedKVCache, PageSnapshot,
    PrefixCache, kv_pool_bytes_per_block, pack_kv_pages,
    quantize_kv_rows, unpack_kv_pages,
)
from .prefix_store import (  # noqa: F401
    PrefixStoreMismatch, load_prefix_store, pool_geometry,
    save_prefix_store, weights_fingerprint,
)
from .scheduler import (  # noqa: F401
    Request, SamplingParams, Scheduler, TenantQuota, TIER_BATCH,
    TIER_LATENCY,
)
from .paged_attention import (  # noqa: F401
    paged_decode_attention, paged_multiquery_attention,
)
from .engine import (  # noqa: F401
    LLMEngine, StepOutput, dequantize_state_dict, is_llama_artifact,
    is_quantized_artifact, load_llama_artifact, load_llama_state_dict,
    quantize_state_dict, save_llama_artifact,
)
from . import fleet  # noqa: F401  (fleet.Router — the ISSUE-12 layer)

__all__ = [
    "BlockAllocator", "PagedKVCache", "PrefixCache", "Request",
    "SamplingParams", "Scheduler", "paged_decode_attention",
    "paged_multiquery_attention", "LLMEngine", "StepOutput",
    "save_llama_artifact", "load_llama_artifact", "is_llama_artifact",
    "is_quantized_artifact", "load_llama_state_dict",
    "quantize_state_dict", "dequantize_state_dict", "KV_QMAX",
    "quantize_kv_rows", "kv_pool_bytes_per_block", "pack_kv_pages",
    "unpack_kv_pages",
    "HostKVTier", "PageSnapshot", "PrefixStoreMismatch",
    "weights_fingerprint", "pool_geometry", "save_prefix_store",
    "load_prefix_store",
    "fleet", "RequestTimeoutError", "FleetOverloadedError",
    "EngineClosedError", "ReplicaCrashLoopError", "KVTransferError",
    "TenantQuota", "TIER_LATENCY", "TIER_BATCH",
    "TenantQuotaExceededError", "DeadlineInfeasibleError",
]
