"""Block-allocated paged KV cache (ISSUE 7 tentpole, part a; prefix
sharing added by ISSUE 11).

The flagship decode loop used to grow its cache by ``concat`` — a fresh
XLA compile and a full cache copy per generated token, and worse, memory
sized for every request's MAXIMUM length up front. The serving fix
(vLLM-style, per PAPERS.md "Ragged Paged Attention … for TPU") is a
static block pool:

* one ``[num_blocks, block_size, num_kv_heads, head_dim]`` K and V array
  per layer, allocated ONCE — shapes never change, so one compiled decode
  graph serves any mix of request lengths;
* a host-side free-list ``BlockAllocator`` hands blocks to requests as
  they grow, token by token — memory is proportional to tokens actually
  alive, not to worst-case lengths;
* per-request **block tables** (host lists, shipped to the device as a
  small int32 array each step) map logical token positions to pool
  blocks; all pool writes happen in-graph via ``lax.dynamic_update_slice``
  so the decode executable is reused forever.

Block 0 is reserved as the **null block**: padded table entries point at
it, so in-graph writes for padding land somewhere harmless instead of
clobbering a live request's block. It is never handed out.

ISSUE 11 extends the allocator with **ref-counted block identity** so N
requests sharing a prompt prefix hold the SAME pool blocks:

* every allocated block carries a refcount; ``acquire`` increfs a block
  another request already filled, ``free`` decrefs — a block returns to
  circulation only at refcount 0 (eviction of a shared block waits for
  the last holder);
* a refcount-0 block whose content is registered in a :class:`PrefixCache`
  is not recycled immediately: it parks in an LRU *reusable* pool, still
  holding its K/V, so a later request with the same prefix can revive it.
  ``allocate`` reclaims reusable blocks (oldest first, dropping their
  hash entries) only after the free list runs dry;
* ``PrefixCache`` maps hash *chains* — ``sha1(parent_hash ‖ block's
  tokens)`` — to block ids, so block identity is positional content, not
  raw bytes: the same 16 tokens at a different prefix offset hash
  differently, exactly like vLLM's prefix tree flattened into a dict.

A partially-filled tail block is never registered (only FULL blocks enter
the hash index), so in-place writes always land in private blocks; the
copy-on-write helpers (``BlockAllocator.is_shared`` +
``PagedKVCache.copy_block``) guard the invariant anyway — a divergent
write to a block some other request can see must copy first, never
mutate.

ISSUE 14 adds **quantized pools** (``kv_dtype="int8"``): the K/V payload
is stored as int8 codes with a float32 abs-max scale per (block,
position, kv-head) row kept in sidecar scale pools the engine threads
through its compiled steps exactly like the payload pools. The scale
granularity is deliberately PER ROW (one scalar per written token per
head), not one scalar per block: a row's codes are then a pure function
of that row's values alone, so prefill (whole pages at once), decode
(one token at a time), eviction re-prefill and fleet redispatch replay
all quantize a given token identically — greedy decode stays
deterministic and bit-reproducible across every write path, which a
block-scalar scale (write-order-dependent rescaling) cannot guarantee.
Block identity, refcounts, prefix hashes and COW never touch payload
dtype, so sharing/eviction/speculation compose unchanged.

ISSUE 15 adds **page export/import** for disaggregated prefill/decode
serving: ``export_request_pages`` gathers one request's pool blocks
(codes AND scale rows for int8 pools) into host arrays, and
``import_request_pages`` writes such a payload into another pool's
blocks — the prefill→decode KV handoff. Because per-row quantization is
a pure function of the row, an imported page is byte-identical to the
page local prefill would have written, so the handoff preserves greedy
determinism by construction. ``pack_kv_pages``/``unpack_kv_pages``
serialize the payload for the transfer channel (the fleet frames the
bytes with CRCs; corruption is the CHANNEL's problem, detected there).
"""

from __future__ import annotations

import hashlib
import io
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["BlockAllocator", "PagedKVCache", "PrefixCache", "KV_QMAX",
           "quantize_kv_rows", "kv_pool_bytes_per_block",
           "pack_kv_pages", "unpack_kv_pages"]

# symmetric int8: codes in [-127, 127], scale = absmax/127 per row.
# -128 is deliberately unused so the scheme stays symmetric (dequant is
# a single multiply, no zero point).
KV_QMAX = 127.0


def quantize_kv_rows(x):
    """Quantize K/V rows ``[..., Hkv, D]`` to int8 codes + per-row scales.

    Returns ``(codes int8 [..., Hkv, D], scales f32 [..., Hkv])`` with
    ``scale = max(|row|) / 127`` (floored at 1e-8 so an all-zero row
    dequantizes to exact zeros instead of NaN). Pure per-row function —
    the SAME row values always produce the SAME codes regardless of how
    many tokens share the block or which write path (prefill chunk,
    decode step, verify window, re-prefill) materializes them. That
    purity is the determinism contract the fleet's redispatch replay and
    the scheduler's eviction re-prefill rely on.
    """
    xf = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(xf), axis=-1) / KV_QMAX
    s = jnp.maximum(s, 1e-8)
    codes = jnp.clip(jnp.round(xf / s[..., None]), -KV_QMAX, KV_QMAX)
    return codes.astype(jnp.int8), s


def kv_pool_bytes_per_block(block_size, num_kv_heads, head_dim,
                            kv_dtype=None, base_dtype=None):
    """Bytes ONE pool block costs (K and V together, one layer),
    including the f32 scale sidecar rows for ``kv_dtype="int8"``. The
    bench's same-memory-budget capacity A/B and the engine's
    ``serving_kv_bytes_saved_total`` accounting both use this, so the
    claim and the telemetry can never disagree."""
    payload = block_size * num_kv_heads * head_dim
    if kv_dtype == "int8":
        return 2 * (payload + block_size * num_kv_heads * 4)
    itemsize = jnp.dtype(base_dtype or jnp.float32).itemsize
    return 2 * payload * itemsize


class BlockAllocator:
    """Ref-counted LIFO free-list over ``num_blocks`` pool blocks.

    Block 0 is the reserved null block (see module docstring) and is never
    allocated. ``allocate`` is all-or-nothing: asking for more blocks than
    are available returns ``None`` and takes nothing — the scheduler's
    signal to queue (or evict), never a partial grab to unwind. ``free``
    is all-or-nothing too: the whole id list is validated up front, so a
    bad id (double-free, foreign block, duplicate in one call) raises
    BEFORE any refcount moves and the allocator is never left
    half-mutated.
    """

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (one is the reserved null "
                             f"block), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        # LIFO: recently-freed (cache-warm) blocks are reused first
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._ref = {}                     # block id -> refcount (>= 1)
        # refcount-0 blocks still registered in a PrefixCache: content is
        # intact and revivable; reclaimed LRU-first when the free list is
        # empty. Insertion order = least recently released first.
        self._reusable = OrderedDict()
        # PrefixCache hooks (set by PrefixCache.__init__): ``on_reclaim``
        # is called with a block id when a reusable block is reclaimed for
        # a fresh allocation (its cached identity dies); ``cache_probe``
        # answers ``registered(block_id)`` so ``free`` knows which
        # refcount-0 blocks are worth parking instead of recycling
        self.on_reclaim = None
        self.cache_probe = None
        self.high_water = 0

    @property
    def _allocated(self):
        """Set view of live (refcount >= 1) blocks — kept for tests and
        invariant checks that predate refcounting."""
        return set(self._ref)

    @property
    def num_free(self):
        """Blocks available to ``allocate``: the free list plus reusable
        (refcount-0, cached-content) blocks that can be reclaimed."""
        return len(self._free) + len(self._reusable)

    def ref(self, block_id):
        """Current refcount of ``block_id`` (0 if not live)."""
        return self._ref.get(block_id, 0)

    def is_shared(self, block_id):
        """True when more than one holder references the block — an
        in-place write would be visible to another request (COW trigger)."""
        return self._ref.get(block_id, 0) > 1

    def allocate(self, n=1):
        """``n`` fresh private blocks (refcount 1), or ``None`` (and no
        state change) if fewer than ``n`` are available. Reusable cached
        blocks are reclaimed (oldest first) only after the free list runs
        dry — reclaiming drops their prefix-cache identity via
        ``on_reclaim``."""
        if n > self.num_free:
            return None
        ids = []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
            else:
                b, _ = self._reusable.popitem(last=False)  # LRU reclaim
                if self.on_reclaim is not None:
                    self.on_reclaim(b)
            self._ref[b] = 1
            ids.append(b)
        self.high_water = max(self.high_water, len(self._ref))
        return ids

    def acquire(self, ids):
        """Share existing blocks: incref each id, reviving reusable
        (refcount-0 cached) blocks. Raises on ids that are neither live
        nor reusable — validated up front, all-or-nothing."""
        for b in ids:
            if b not in self._ref and b not in self._reusable:
                raise ValueError(f"acquire of free/foreign block {b}")
        for b in ids:
            if b in self._ref:
                self._ref[b] += 1
            else:
                del self._reusable[b]
                self._ref[b] = 1
        self.high_water = max(self.high_water, len(self._ref))

    def free(self, ids):
        """Decref every id; a block reaching refcount 0 returns to the
        free list, or — when the attached :class:`PrefixCache` (via
        ``cache_probe``) says its content is registered — parks in the
        reusable pool instead, revivable by a later prefix match.

        All-or-nothing (ISSUE 11 satellite): the WHOLE list is validated
        before any mutation, so a duplicate id in one call or a foreign/
        double-freed block raises with the allocator untouched.
        """
        seen = set()
        for b in ids:
            if b in seen:
                raise ValueError(f"duplicate block {b} in one free() call")
            if b not in self._ref:
                raise ValueError(f"double-free or foreign block {b}")
            seen.add(b)
        probe = self.cache_probe
        for b in ids:
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                if probe is not None and probe.registered(b):
                    self._reusable[b] = None
                else:
                    self._free.append(b)


class PrefixCache:
    """Content-hashed block identity: hash chains -> pool block ids.

    A block's identity is ``sha1(parent_chain_hash ‖ its block_size
    tokens)`` — the chain makes identity positional (the same tokens
    after a different prefix are a different block), so a lookup walking
    chunks from position 0 finds exactly the blocks whose ENTIRE causal
    content matches. Only FULL blocks are ever registered: the partially
    filled tail of a prompt stays private (its content is still growing),
    which is what makes in-place decode writes safe without copies in the
    common path.
    """

    def __init__(self, allocator, block_size):
        self.allocator = allocator
        self.block_size = int(block_size)
        self._by_hash = {}      # chain hash (bytes) -> block id
        self._block_hash = {}   # block id -> chain hash
        allocator.on_reclaim = self._forget
        allocator.cache_probe = self

    def __len__(self):
        return len(self._by_hash)

    def registered(self, block_id):
        return block_id in self._block_hash

    def _chunk_hash(self, parent, chunk):
        return hashlib.sha1(
            parent + np.asarray(chunk, np.int64).tobytes()).digest()

    def match(self, tokens):
        """Longest chain of cached full blocks covering a PROPER prefix
        of ``tokens``; returns ``(block_ids, tokens_covered)``. The match
        is capped at ``len(tokens) - 1`` so admission always has at least
        one token left to prefill — the last position's logits must be
        computed to sample the first output token."""
        tokens = np.asarray(tokens)
        bs = self.block_size
        max_chunks = max((len(tokens) - 1) // bs, 0)
        blocks, parent = [], b""
        for i in range(max_chunks):
            h = self._chunk_hash(parent, tokens[i * bs:(i + 1) * bs])
            b = self._by_hash.get(h)
            if b is None:
                break
            blocks.append(b)
            parent = h
        return blocks, len(blocks) * bs

    def register(self, tokens, blocks, upto):
        """Publish the identity of every FULL block among ``blocks`` whose
        tokens (``tokens[:upto]``) are materialized in the pool. First
        writer wins: a chain hash already mapping to a (different) block
        keeps its mapping and the duplicate block simply stays private;
        a block already registered under another chain is never re-keyed.
        """
        tokens = np.asarray(tokens)
        bs = self.block_size
        n_chunks = min(int(upto) // bs, len(blocks))
        parent = b""
        for i in range(n_chunks):
            h = self._chunk_hash(parent, tokens[i * bs:(i + 1) * bs])
            cur = self._by_hash.get(h)
            if cur is None and blocks[i] not in self._block_hash:
                self._by_hash[h] = blocks[i]
                self._block_hash[blocks[i]] = h
            parent = h

    def forget(self, block_id):
        """Drop a block's cached identity (divergent write to a
        refcount-1 registered block — its content no longer matches the
        published hash)."""
        self._forget(block_id)

    def _forget(self, block_id):
        h = self._block_hash.pop(block_id, None)
        if h is not None:
            self._by_hash.pop(h, None)


class PagedKVCache:
    """Static per-layer K/V block pools + the allocator that carves them.

    ``k``/``v`` are lists (one per layer) of
    ``[num_blocks, block_size, num_kv_heads, head_dim]`` arrays. They are
    plain jax arrays deliberately: the engine threads them through its
    compiled step functions (donated on TPU) and rebinds the returned
    buffers, exactly like ``FusedTrainStep`` handles optimizer state.

    ``kv_dtype="int8"`` (ISSUE 14) stores the payload as int8 codes and
    adds per-layer ``k_scale``/``v_scale`` pools of shape
    ``[num_blocks, block_size, num_kv_heads]`` f32 — one abs-max scale
    per written row per head (see :func:`quantize_kv_rows` for why the
    granularity is per-row, not per-block-scalar). Scale pools are
    threaded through compiled steps exactly like the payload pools;
    ``kv_dtype=None`` keeps ``k_scale``/``v_scale`` as empty lists so
    the fp path's pytrees carry zero extra leaves.
    """

    def __init__(self, config, num_blocks, block_size, dtype=None,
                 allocator=None, kv_dtype=None):
        if dtype is None:
            dtype = jnp.float32
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None (model dtype) or 'int8'; got "
                f"{kv_dtype!r}")
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.kv_dtype = kv_dtype
        self.quantized = kv_dtype == "int8"
        self.base_dtype = dtype
        shape = (self.num_blocks, self.block_size,
                 config.num_key_value_heads, config.head_dim)
        L = config.num_hidden_layers
        pool_dtype = jnp.int8 if self.quantized else dtype
        self.k = [jnp.zeros(shape, pool_dtype) for _ in range(L)]
        self.v = [jnp.zeros(shape, pool_dtype) for _ in range(L)]
        if self.quantized:
            sshape = shape[:-1]
            self.k_scale = [jnp.zeros(sshape, jnp.float32)
                            for _ in range(L)]
            self.v_scale = [jnp.zeros(sshape, jnp.float32)
                            for _ in range(L)]
        else:
            self.k_scale = []
            self.v_scale = []
        # a draft-model pool (speculative decoding) shares the target
        # pool's allocator: one block table indexes both pools
        self.allocator = (allocator if allocator is not None
                          else BlockAllocator(num_blocks))

    def bytes_saved_vs_unquantized(self, config):
        """Total pool bytes an int8 cache saves versus the SAME pool in
        the model's dtype (0 for an unquantized cache) — scale sidecars
        charged against the saving."""
        if not self.quantized:
            return 0
        fp = kv_pool_bytes_per_block(
            self.block_size, config.num_key_value_heads, config.head_dim,
            kv_dtype=None, base_dtype=self.base_dtype)
        q8 = kv_pool_bytes_per_block(
            self.block_size, config.num_key_value_heads, config.head_dim,
            kv_dtype="int8")
        return (fp - q8) * self.num_blocks * config.num_hidden_layers

    def blocks_for_tokens(self, n_tokens):
        """Blocks needed to hold ``n_tokens``."""
        return -(-int(n_tokens) // self.block_size)

    def table_array(self, block_lists, max_blocks):
        """Host block tables -> device int32 [len(block_lists), max_blocks],
        padded with the null block."""
        out = np.zeros((len(block_lists), max_blocks), np.int32)
        for i, blocks in enumerate(block_lists):
            out[i, :len(blocks)] = blocks
        return jax.device_put(out)

    def copy_block(self, src, dst):
        """Copy one pool block's K/V from ``src`` to ``dst`` across all
        layers (the COW move: the writer gets a private copy, the shared
        original is never mutated). Host-triggered and rare — this is NOT
        inside the compiled step. Quantized pools copy the scale rows
        too: codes without their scales are not a copy."""
        self.k = [kp.at[dst].set(kp[src]) for kp in self.k]
        self.v = [vp.at[dst].set(vp[src]) for vp in self.v]
        if self.quantized:
            self.k_scale = [s.at[dst].set(s[src]) for s in self.k_scale]
            self.v_scale = [s.at[dst].set(s[src]) for s in self.v_scale]

    # -- disaggregated prefill/decode page handoff (ISSUE 15) -----------
    def export_request_pages(self, blocks, covered):
        """Gather the pool content of ``blocks`` (one request's pages, in
        table order) into host arrays: ``{"k": [L, n, block, Hkv, D],
        "v": ..., covered, block_size, kv_dtype}``, plus
        ``k_scale``/``v_scale`` ``[L, n, block, Hkv]`` rows for int8
        pools (codes without their scales are not a page). ``covered``
        records how many leading tokens the pages actually hold — the
        tail block may be partial; its trailing rows are whatever the
        pool holds and are masked by context lengths on the other side,
        exactly as they are here."""
        idx = np.asarray(blocks, np.int32)
        out = {
            "covered": int(covered),
            "block_size": self.block_size,
            "kv_dtype": self.kv_dtype,
            "k": np.stack([np.asarray(kp[idx]) for kp in self.k]),
            "v": np.stack([np.asarray(vp[idx]) for vp in self.v]),
        }
        if self.quantized:
            out["k_scale"] = np.stack(
                [np.asarray(s[idx]) for s in self.k_scale])
            out["v_scale"] = np.stack(
                [np.asarray(s[idx]) for s in self.v_scale])
        return out

    def validate_request_pages(self, pages):
        """Typed geometry validation of an import payload WITHOUT
        mutating anything: dtype/block-size match, payload shapes fit
        this pool, and — on quantized pools — the scale rows exist and
        fit too. The decode engine calls this at admission (before any
        blocks are allocated); :meth:`import_request_pages` calls it
        again before writing, so a bad payload can never leave the pool
        half-imported. Returns the number of payload blocks."""
        if pages.get("kv_dtype") != self.kv_dtype:
            raise ValueError(
                f"imported pages carry kv_dtype={pages.get('kv_dtype')!r} "
                f"but this pool stores {self.kv_dtype!r}")
        if int(pages.get("block_size", -1)) != self.block_size:
            raise ValueError(
                f"imported pages use block_size={pages.get('block_size')} "
                f"but this pool uses {self.block_size}")
        k, v = pages["k"], pages["v"]
        want = (len(self.k),) + self.k[0].shape[1:]
        if k.shape[:1] + k.shape[2:] != want or k.shape != v.shape:
            raise ValueError(
                f"imported page shape {k.shape} does not fit this pool "
                f"(layers+block geometry {want})")
        n = k.shape[1]
        if self.quantized:
            swant = want[:-1]
            for nm in ("k_scale", "v_scale"):
                s = pages.get(nm)
                if s is None:
                    raise ValueError(
                        f"int8 pages are missing their {nm} rows — "
                        "codes without scales are not a page")
                if (s.shape[:1] + s.shape[2:] != swant
                        or s.shape[1] != n):
                    raise ValueError(
                        f"imported {nm} shape {s.shape} does not fit "
                        f"this pool (layers+block geometry {swant}, "
                        f"{n} payload blocks)")
        return n

    def import_request_pages(self, blocks, pages):
        """Write an :meth:`export_request_pages` payload into ``blocks``
        of THIS pool (host-triggered, like :meth:`copy_block` — not
        inside a compiled step). ``blocks`` may be longer than the
        payload (admission also allocates room for the next token);
        only the payload's blocks are written. Raises ``ValueError`` on
        any pool-geometry mismatch BEFORE any pool array moves —
        importing pages of the wrong shape/dtype would decode garbage
        silently, and a mid-write failure would be worse."""
        n = self.validate_request_pages(pages)
        if n > len(blocks):
            raise ValueError(
                f"payload holds {n} blocks but only {len(blocks)} were "
                "allocated for the import")
        k, v = pages["k"], pages["v"]
        idx = jnp.asarray(np.asarray(blocks[:n], np.int32))
        self.k = [kp.at[idx].set(jnp.asarray(k[i], kp.dtype))
                  for i, kp in enumerate(self.k)]
        self.v = [vp.at[idx].set(jnp.asarray(v[i], vp.dtype))
                  for i, vp in enumerate(self.v)]
        if self.quantized:
            ks, vs = pages["k_scale"], pages["v_scale"]
            self.k_scale = [s.at[idx].set(jnp.asarray(ks[i], s.dtype))
                            for i, s in enumerate(self.k_scale)]
            self.v_scale = [s.at[idx].set(jnp.asarray(vs[i], s.dtype))
                            for i, s in enumerate(self.v_scale)]


def pack_kv_pages(pages):
    """Serialize an ``export_request_pages`` payload to bytes (npz,
    pickle-free) for the fleet's CRC-framed transfer channel."""
    buf = io.BytesIO()
    arrays = {k: v for k, v in pages.items()
              if isinstance(v, np.ndarray)}
    arrays["covered"] = np.int64(pages["covered"])
    arrays["block_size"] = np.int64(pages["block_size"])
    arrays["kv_dtype"] = np.frombuffer(
        (pages["kv_dtype"] or "").encode(), np.uint8)
    np.savez(buf, **arrays)
    return buf.getvalue()


def unpack_kv_pages(data):
    """Inverse of :func:`pack_kv_pages`. Raises ``ValueError`` on a
    payload that does not parse as the page format — the caller treats
    that as a corrupt transfer (the CRC framing should have caught it
    first)."""
    try:
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            out = {k: z[k] for k in z.files}
    except Exception as e:
        raise ValueError(f"undecodable KV page payload: {e}") from e
    for key in ("covered", "block_size", "kv_dtype", "k", "v"):
        if key not in out:
            raise ValueError(f"KV page payload missing field {key!r}")
    out["covered"] = int(out["covered"])
    out["block_size"] = int(out["block_size"])
    dt = bytes(out["kv_dtype"]).decode() or None
    out["kv_dtype"] = dt
    if dt == "int8":
        for key in ("k_scale", "v_scale"):
            if key not in out:
                raise ValueError(
                    f"int8 KV page payload missing field {key!r} — "
                    "codes without scales are not a page")
    return out
