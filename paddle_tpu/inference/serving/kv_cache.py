"""Block-allocated paged KV cache (ISSUE 7 tentpole, part a; prefix
sharing added by ISSUE 11).

The flagship decode loop used to grow its cache by ``concat`` — a fresh
XLA compile and a full cache copy per generated token, and worse, memory
sized for every request's MAXIMUM length up front. The serving fix
(vLLM-style, per PAPERS.md "Ragged Paged Attention … for TPU") is a
static block pool:

* one ``[num_blocks, block_size, num_kv_heads, head_dim]`` K and V array
  per layer, allocated ONCE — shapes never change, so one compiled decode
  graph serves any mix of request lengths;
* a host-side free-list ``BlockAllocator`` hands blocks to requests as
  they grow, token by token — memory is proportional to tokens actually
  alive, not to worst-case lengths;
* per-request **block tables** (host lists, shipped to the device as a
  small int32 array each step) map logical token positions to pool
  blocks; all pool writes happen in-graph via ``lax.dynamic_update_slice``
  so the decode executable is reused forever.

Block 0 is reserved as the **null block**: padded table entries point at
it, so in-graph writes for padding land somewhere harmless instead of
clobbering a live request's block. It is never handed out.

ISSUE 11 extends the allocator with **ref-counted block identity** so N
requests sharing a prompt prefix hold the SAME pool blocks:

* every allocated block carries a refcount; ``acquire`` increfs a block
  another request already filled, ``free`` decrefs — a block returns to
  circulation only at refcount 0 (eviction of a shared block waits for
  the last holder);
* a refcount-0 block whose content is registered in a :class:`PrefixCache`
  is not recycled immediately: it parks in an LRU *reusable* pool, still
  holding its K/V, so a later request with the same prefix can revive it.
  ``allocate`` reclaims reusable blocks (oldest first, dropping their
  hash entries) only after the free list runs dry;
* ``PrefixCache`` maps hash *chains* — ``sha1(parent_hash ‖ block's
  tokens)`` — to block ids, so block identity is positional content, not
  raw bytes: the same 16 tokens at a different prefix offset hash
  differently, exactly like vLLM's prefix tree flattened into a dict.

A partially-filled tail block is never registered (only FULL blocks enter
the hash index), so in-place writes always land in private blocks; the
copy-on-write helpers (``BlockAllocator.is_shared`` +
``PagedKVCache.copy_block``) guard the invariant anyway — a divergent
write to a block some other request can see must copy first, never
mutate.

ISSUE 14 adds **quantized pools** (``kv_dtype="int8"``): the K/V payload
is stored as int8 codes with a float32 abs-max scale per (block,
position, kv-head) row kept in sidecar scale pools the engine threads
through its compiled steps exactly like the payload pools. The scale
granularity is deliberately PER ROW (one scalar per written token per
head), not one scalar per block: a row's codes are then a pure function
of that row's values alone, so prefill (whole pages at once), decode
(one token at a time), eviction re-prefill and fleet redispatch replay
all quantize a given token identically — greedy decode stays
deterministic and bit-reproducible across every write path, which a
block-scalar scale (write-order-dependent rescaling) cannot guarantee.
Block identity, refcounts, prefix hashes and COW never touch payload
dtype, so sharing/eviction/speculation compose unchanged.

ISSUE 15 adds **page export/import** for disaggregated prefill/decode
serving: ``export_request_pages`` gathers one request's pool blocks
(codes AND scale rows for int8 pools) into host arrays, and
``import_request_pages`` writes such a payload into another pool's
blocks — the prefill→decode KV handoff. Because per-row quantization is
a pure function of the row, an imported page is byte-identical to the
page local prefill would have written, so the handoff preserves greedy
determinism by construction. ``pack_kv_pages``/``unpack_kv_pages``
serialize the payload for the transfer channel (the fleet frames the
bytes with CRCs; corruption is the CHANNEL's problem, detected there).

ISSUE 16 adds the **host-RAM tier** (:class:`HostKVTier`): when the
device free list dries up, cold pages — a preempted request's blocks, or
a refcount-0 registered block being reclaimed out of the reusable pool —
are snapshotted (:meth:`PagedKVCache.snapshot_request_pages`, a zero-copy
device-side gather) and drained to host numpy arrays on a transfer
thread (the ``DevicePrefetcher`` idiom: async D2H that never blocks the
step loop, dies once and degrades to synchronous conversion). The tier
is budget-bounded (``max_host_blocks``) with its own LRU, so host RAM is
a sized cache, not a leak. Revival is ``import_request_pages`` instead
of re-prefill — bit-exact by construction (PR 15) — and spilled prefix
blocks keep their chain hashes as tier keys, so
:meth:`PrefixCache.match_with_tier` extends a device chain walk into the
host tier and the scheduler revives host-resident prefixes on admission.
"""

from __future__ import annotations

import hashlib
import io
import queue
import threading
import time
import warnings
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from ...observability import metrics as _obs_metrics
from ...utils import fault_injection as _fi
from . import integrity as _integrity
from .errors import KVIntegrityError

__all__ = ["BlockAllocator", "PagedKVCache", "PrefixCache", "HostKVTier",
           "PageSnapshot", "KV_QMAX",
           "quantize_kv_rows", "kv_pool_bytes_per_block",
           "pack_kv_pages", "unpack_kv_pages"]

# KV tiering observability (ISSUE 16): spills/revives are counted per
# EVENT (one preempted request's page set, or one reclaimed prefix
# block); bytes counters carry the volume, the gauge tracks host-tier
# residency, and the histograms time the actual transfers (D2H
# materialization on spill, pool import on revive). Instance-labeled by
# engine, like every serving metric.
_M_SPILLS = _obs_metrics.counter(
    "serving_kv_spills_total",
    "KV page-spill events into the host tier (one per preempted request "
    "or per reclaimed prefix block)")
_M_REVIVES = _obs_metrics.counter(
    "serving_kv_revives_total",
    "KV revive events out of the host tier (import_request_pages instead "
    "of re-prefill: one per revived request or prefix block)")
_M_SPILL_BYTES = _obs_metrics.counter(
    "serving_kv_spill_bytes_total",
    "bytes moved device->host by KV tier spills (codes + scale sidecars "
    "for int8 pools)")
_M_REVIVE_BYTES = _obs_metrics.counter(
    "serving_kv_revive_bytes_total",
    "bytes moved host->device by KV tier revivals")
_M_HOST_EVICT = _obs_metrics.counter(
    "serving_kv_host_evictions_total",
    "entries LRU-dropped from the host tier to fit its block budget "
    "(the spilled content is recomputable; dropping costs a re-prefill, "
    "never correctness)")
_G_HOST_BLOCKS = _obs_metrics.gauge(
    "serving_kv_host_blocks",
    "KV blocks currently resident in the host-RAM tier")
_H_SPILL_MS = _obs_metrics.histogram(
    "serving_kv_spill_ms",
    "device->host materialization latency per spill event",
    buckets=_obs_metrics.DEFAULT_MS_BUCKETS)
_H_REVIVE_MS = _obs_metrics.histogram(
    "serving_kv_revive_ms",
    "host->device import latency per revive event",
    buckets=_obs_metrics.DEFAULT_MS_BUCKETS)

# symmetric int8: codes in [-127, 127], scale = absmax/127 per row.
# -128 is deliberately unused so the scheme stays symmetric (dequant is
# a single multiply, no zero point).
KV_QMAX = 127.0


def quantize_kv_rows(x):
    """Quantize K/V rows ``[..., Hkv, D]`` to int8 codes + per-row scales.

    Returns ``(codes int8 [..., Hkv, D], scales f32 [..., Hkv])`` with
    ``scale = max(|row|) / 127`` (floored at 1e-8 so an all-zero row
    dequantizes to exact zeros instead of NaN). Pure per-row function —
    the SAME row values always produce the SAME codes regardless of how
    many tokens share the block or which write path (prefill chunk,
    decode step, verify window, re-prefill) materializes them. That
    purity is the determinism contract the fleet's redispatch replay and
    the scheduler's eviction re-prefill rely on.
    """
    xf = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(xf), axis=-1) / KV_QMAX
    s = jnp.maximum(s, 1e-8)
    codes = jnp.clip(jnp.round(xf / s[..., None]), -KV_QMAX, KV_QMAX)
    return codes.astype(jnp.int8), s


def kv_pool_bytes_per_block(block_size, num_kv_heads, head_dim,
                            kv_dtype=None, base_dtype=None):
    """Bytes ONE pool block costs (K and V together, one layer),
    including the f32 scale sidecar rows for ``kv_dtype="int8"``. The
    bench's same-memory-budget capacity A/B and the engine's
    ``serving_kv_bytes_saved_total`` accounting both use this, so the
    claim and the telemetry can never disagree."""
    payload = block_size * num_kv_heads * head_dim
    if kv_dtype == "int8":
        return 2 * (payload + block_size * num_kv_heads * 4)
    itemsize = jnp.dtype(base_dtype or jnp.float32).itemsize
    return 2 * payload * itemsize


class BlockAllocator:
    """Ref-counted LIFO free-list over ``num_blocks`` pool blocks.

    Block 0 is the reserved null block (see module docstring) and is never
    allocated. ``allocate`` is all-or-nothing: asking for more blocks than
    are available returns ``None`` and takes nothing — the scheduler's
    signal to queue (or evict), never a partial grab to unwind. ``free``
    is all-or-nothing too: the whole id list is validated up front, so a
    bad id (double-free, foreign block, duplicate in one call) raises
    BEFORE any refcount moves and the allocator is never left
    half-mutated.
    """

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (one is the reserved null "
                             f"block), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        # LIFO: recently-freed (cache-warm) blocks are reused first
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._ref = {}                     # block id -> refcount (>= 1)
        # refcount-0 blocks still registered in a PrefixCache: content is
        # intact and revivable; reclaimed LRU-first when the free list is
        # empty. Insertion order = least recently released first.
        self._reusable = OrderedDict()
        # PrefixCache hooks (set by PrefixCache.__init__): ``on_reclaim``
        # is called with a block id when a reusable block is reclaimed for
        # a fresh allocation (its cached identity dies); ``cache_probe``
        # answers ``registered(block_id)`` so ``free`` knows which
        # refcount-0 blocks are worth parking instead of recycling
        self.on_reclaim = None
        self.cache_probe = None
        self.high_water = 0

    @property
    def _allocated(self):
        """Set view of live (refcount >= 1) blocks — kept for tests and
        invariant checks that predate refcounting."""
        return set(self._ref)

    @property
    def num_free(self):
        """Blocks available to ``allocate``: the free list plus reusable
        (refcount-0, cached-content) blocks that can be reclaimed."""
        return len(self._free) + len(self._reusable)

    def ref(self, block_id):
        """Current refcount of ``block_id`` (0 if not live)."""
        return self._ref.get(block_id, 0)

    def is_shared(self, block_id):
        """True when more than one holder references the block — an
        in-place write would be visible to another request (COW trigger)."""
        return self._ref.get(block_id, 0) > 1

    def allocate(self, n=1):
        """``n`` fresh private blocks (refcount 1), or ``None`` (and no
        state change) if fewer than ``n`` are available. Reusable cached
        blocks are reclaimed (oldest first) only after the free list runs
        dry — reclaiming drops their prefix-cache identity via
        ``on_reclaim``."""
        if n > self.num_free:
            return None
        ids, reclaimed = [], []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
            else:
                b, _ = self._reusable.popitem(last=False)  # LRU reclaim
                reclaimed.append(b)
            self._ref[b] = 1
            ids.append(b)
        if reclaimed and self.on_reclaim is not None:
            # one notification for the whole wave: the ISSUE-16 spill
            # path turns each wave into ONE device gather + ONE queued
            # D2H, so reclaim cost is per-allocate, not per-block
            self.on_reclaim(reclaimed)
        self.high_water = max(self.high_water, len(self._ref))
        return ids

    def unpark(self, block_id):
        """Move a parked reusable block back to the plain free list —
        its cached identity was retracted (per-tenant share enforcement,
        ISSUE 17), so it is no longer worth reclaim bookkeeping. A block
        that is live or already free is left alone."""
        if block_id in self._reusable:
            del self._reusable[block_id]
            self._free.append(block_id)

    def acquire(self, ids):
        """Share existing blocks: incref each id, reviving reusable
        (refcount-0 cached) blocks. Raises on ids that are neither live
        nor reusable — validated up front, all-or-nothing."""
        for b in ids:
            if b not in self._ref and b not in self._reusable:
                raise ValueError(f"acquire of free/foreign block {b}")
        for b in ids:
            if b in self._ref:
                self._ref[b] += 1
            else:
                del self._reusable[b]
                self._ref[b] = 1
        self.high_water = max(self.high_water, len(self._ref))

    def free(self, ids):
        """Decref every id; a block reaching refcount 0 returns to the
        free list, or — when the attached :class:`PrefixCache` (via
        ``cache_probe``) says its content is registered — parks in the
        reusable pool instead, revivable by a later prefix match.

        All-or-nothing (ISSUE 11 satellite): the WHOLE list is validated
        before any mutation, so a duplicate id in one call or a foreign/
        double-freed block raises with the allocator untouched.
        """
        seen = set()
        for b in ids:
            if b in seen:
                raise ValueError(f"duplicate block {b} in one free() call")
            if b not in self._ref:
                raise ValueError(f"double-free or foreign block {b}")
            seen.add(b)
        probe = self.cache_probe
        for b in ids:
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                if probe is not None and probe.registered(b):
                    self._reusable[b] = None
                else:
                    self._free.append(b)


class PrefixCache:
    """Content-hashed block identity: hash chains -> pool block ids.

    A block's identity is ``sha1(parent_chain_hash ‖ its block_size
    tokens)`` — the chain makes identity positional (the same tokens
    after a different prefix are a different block), so a lookup walking
    chunks from position 0 finds exactly the blocks whose ENTIRE causal
    content matches. Only FULL blocks are ever registered: the partially
    filled tail of a prompt stays private (its content is still growing),
    which is what makes in-place decode writes safe without copies in the
    common path.
    """

    def __init__(self, allocator, block_size):
        self.allocator = allocator
        self.block_size = int(block_size)
        self._by_hash = {}      # chain hash (bytes) -> block id
        self._block_hash = {}   # block id -> chain hash
        # ISSUE 16: optional spill hook ``on_spill(pairs)`` taking a
        # batch of ``(block_id, chain_hash)`` pairs (set by the engine
        # when a HostKVTier is attached). Reclaiming reusable blocks out
        # of the device pool offers their content to the host tier
        # BEFORE the identities are forgotten — a reclaim becomes a
        # demotion, not a loss. A divergent-write ``forget`` never
        # spills: that content no longer matches its published hash.
        self.on_spill = None
        allocator.on_reclaim = self._reclaim
        allocator.cache_probe = self
        # per-tenant accounting (ISSUE 17): how many registered blocks
        # each tenant has published, oldest-first, plus optional shares.
        # A tenant over its share demotes ITS OWN oldest identities to
        # the host tier (and unparks the blocks) — it can grow the warm
        # set only up to its budget, never by evicting another tenant's
        # published blocks past theirs.
        self._block_tenant = {}     # block id -> tenant name
        self._tenant_lru = {}       # tenant -> OrderedDict[block id, None]
        self._tenant_share = {}     # tenant -> max registered blocks

    def __len__(self):
        return len(self._by_hash)

    def set_tenant_share(self, name, max_blocks):
        """Cap tenant ``name`` at ``max_blocks`` registered (published)
        blocks; ``None`` removes the cap."""
        if max_blocks is None:
            self._tenant_share.pop(str(name), None)
        else:
            if int(max_blocks) < 1:
                raise ValueError(
                    f"tenant prefix share must be >= 1, got {max_blocks}")
            self._tenant_share[str(name)] = int(max_blocks)

    def tenant_blocks(self, name):
        """Registered blocks currently attributed to tenant ``name``."""
        return len(self._tenant_lru.get(str(name), ()))

    def _tag(self, block_id, tenant):
        if tenant is None:
            return
        self._block_tenant[block_id] = tenant
        self._tenant_lru.setdefault(tenant, OrderedDict())[block_id] = None

    def _enforce_share(self, tenant):
        share = self._tenant_share.get(tenant)
        if share is None:
            return
        lru = self._tenant_lru.get(tenant)
        while lru and len(lru) > share:
            b = next(iter(lru))  # tenant's oldest published block
            h = self._block_hash.get(b)
            if self.on_spill is not None and h is not None:
                self.on_spill([(b, h)], [tenant])  # demote, don't lose
            self._forget(b)
            self.allocator.unpark(b)

    def registered(self, block_id):
        return block_id in self._block_hash

    def _chunk_hash(self, parent, chunk):
        return hashlib.sha1(
            parent + np.asarray(chunk, np.int64).tobytes()).digest()

    def match(self, tokens):
        """Longest chain of cached full blocks covering a PROPER prefix
        of ``tokens``; returns ``(block_ids, tokens_covered)``. The match
        is capped at ``len(tokens) - 1`` so admission always has at least
        one token left to prefill — the last position's logits must be
        computed to sample the first output token."""
        tokens = np.asarray(tokens)
        bs = self.block_size
        max_chunks = max((len(tokens) - 1) // bs, 0)
        blocks, parent = [], b""
        for i in range(max_chunks):
            h = self._chunk_hash(parent, tokens[i * bs:(i + 1) * bs])
            b = self._by_hash.get(h)
            if b is None:
                break
            blocks.append(b)
            parent = h
        return blocks, len(blocks) * bs

    def register(self, tokens, blocks, upto, tenant=None):
        """Publish the identity of every FULL block among ``blocks`` whose
        tokens (``tokens[:upto]``) are materialized in the pool. First
        writer wins: a chain hash already mapping to a (different) block
        keeps its mapping and the duplicate block simply stays private;
        a block already registered under another chain is never re-keyed.
        Newly published blocks are attributed to ``tenant`` (ISSUE 17);
        a tenant over its share demotes its own oldest identities.
        """
        tokens = np.asarray(tokens)
        bs = self.block_size
        n_chunks = min(int(upto) // bs, len(blocks))
        parent = b""
        tagged = False
        for i in range(n_chunks):
            h = self._chunk_hash(parent, tokens[i * bs:(i + 1) * bs])
            cur = self._by_hash.get(h)
            if cur is None and blocks[i] not in self._block_hash:
                self._by_hash[h] = blocks[i]
                self._block_hash[blocks[i]] = h
                self._tag(blocks[i], tenant)
                tagged = True
            parent = h
        if tagged and tenant is not None:
            self._enforce_share(tenant)

    def match_with_tier(self, tokens, tier):
        """:meth:`match`, extended into the host tier (ISSUE 16): after
        the device chain walk stops, keep hashing chunks and probing
        ``tier`` for host-resident continuations of the SAME chain.
        Returns ``(block_ids, device_covered, host_hashes)`` — the host
        hashes cover the chunks immediately after ``device_covered``;
        the caller allocates fresh blocks for them and revives their
        pages via ``import_request_pages``. The combined coverage obeys
        the same proper-prefix cap as :meth:`match`."""
        tokens = np.asarray(tokens)
        bs = self.block_size
        max_chunks = max((len(tokens) - 1) // bs, 0)
        blocks, parent = [], b""
        host = []
        i = 0
        while i < max_chunks:
            h = self._chunk_hash(parent, tokens[i * bs:(i + 1) * bs])
            b = self._by_hash.get(h)
            if b is None:
                break
            blocks.append(b)
            parent = h
            i += 1
        while tier is not None and i < max_chunks:
            h = self._chunk_hash(parent, tokens[i * bs:(i + 1) * bs])
            if not tier.has_prefix(h):
                break
            host.append(h)
            parent = h
            i += 1
        return blocks, len(blocks) * bs, host

    def adopt(self, block_id, chain_hash, tenant=None):
        """Publish a revived block under its KNOWN chain hash (host-tier
        or prefix-store revival: the pages just imported are
        byte-identical to what the chain's original writer produced, so
        the identity transfers with them — no token rehash needed).
        First writer wins, exactly like :meth:`register`."""
        if chain_hash in self._by_hash or block_id in self._block_hash:
            return
        self._by_hash[chain_hash] = block_id
        self._block_hash[block_id] = chain_hash
        self._tag(block_id, tenant)
        if tenant is not None:
            self._enforce_share(tenant)

    def registered_chains(self):
        """Snapshot of ``(chain_hash, block_id)`` pairs currently
        published — the prefix store serializes these (plus the host
        tier's entries) on save."""
        return list(self._by_hash.items())

    def invalidate(self):
        """Drop EVERY cached identity (``reload_weights`` with a
        different weight fingerprint: pool content no longer corresponds
        to any chain under the new model). Blocks parked in the
        allocator's reusable pool stay parked — with their hashes gone
        they recycle as plain free blocks and are never spilled."""
        self._by_hash.clear()
        self._block_hash.clear()
        self._block_tenant.clear()
        self._tenant_lru.clear()

    def forget(self, block_id):
        """Drop a block's cached identity (divergent write to a
        refcount-1 registered block — its content no longer matches the
        published hash)."""
        self._forget(block_id)

    def _reclaim(self, block_ids):
        """Allocator ``on_reclaim`` hook: a WAVE of reusable blocks is
        being handed to new owners. Offer their (still intact) content
        to the host tier in one batch — one device gather and one queued
        D2H for the whole wave — then forget the device identities."""
        if self.on_spill is not None:
            pairs = [(b, self._block_hash[b]) for b in block_ids
                     if b in self._block_hash]
            if pairs:
                tenants = [self._block_tenant.get(b) for b, _ in pairs]
                self.on_spill(pairs, tenants)
        for b in block_ids:
            self._forget(b)

    def _forget(self, block_id):
        h = self._block_hash.pop(block_id, None)
        if h is not None:
            self._by_hash.pop(h, None)
        t = self._block_tenant.pop(block_id, None)
        if t is not None:
            lru = self._tenant_lru.get(t)
            if lru is not None:
                lru.pop(block_id, None)


class PagedKVCache:
    """Static per-layer K/V block pools + the allocator that carves them.

    ``k``/``v`` are lists (one per layer) of
    ``[num_blocks, block_size, num_kv_heads, head_dim]`` arrays. They are
    plain jax arrays deliberately: the engine threads them through its
    compiled step functions (donated on TPU) and rebinds the returned
    buffers, exactly like ``FusedTrainStep`` handles optimizer state.

    ``kv_dtype="int8"`` (ISSUE 14) stores the payload as int8 codes and
    adds per-layer ``k_scale``/``v_scale`` pools of shape
    ``[num_blocks, block_size, num_kv_heads]`` f32 — one abs-max scale
    per written row per head (see :func:`quantize_kv_rows` for why the
    granularity is per-row, not per-block-scalar). Scale pools are
    threaded through compiled steps exactly like the payload pools;
    ``kv_dtype=None`` keeps ``k_scale``/``v_scale`` as empty lists so
    the fp path's pytrees carry zero extra leaves.
    """

    # ISSUE 20: when armed (``LLMEngine(kv_page_checksums=True)`` sets
    # it), every :meth:`PageSnapshot.materialize` — the single choke
    # point behind export_request_pages, host-tier spills and the
    # prefix-store save pass — seals the payload with per-block CRC32s
    # (``integrity.seal_pages``); read-back boundaries verify them.
    page_checksums = False

    def __init__(self, config, num_blocks, block_size, dtype=None,
                 allocator=None, kv_dtype=None):
        if dtype is None:
            dtype = jnp.float32
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None (model dtype) or 'int8'; got "
                f"{kv_dtype!r}")
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.kv_dtype = kv_dtype
        self.quantized = kv_dtype == "int8"
        self.base_dtype = dtype
        shape = (self.num_blocks, self.block_size,
                 config.num_key_value_heads, config.head_dim)
        L = config.num_hidden_layers
        pool_dtype = jnp.int8 if self.quantized else dtype
        self.k = [jnp.zeros(shape, pool_dtype) for _ in range(L)]
        self.v = [jnp.zeros(shape, pool_dtype) for _ in range(L)]
        if self.quantized:
            sshape = shape[:-1]
            self.k_scale = [jnp.zeros(sshape, jnp.float32)
                            for _ in range(L)]
            self.v_scale = [jnp.zeros(sshape, jnp.float32)
                            for _ in range(L)]
        else:
            self.k_scale = []
            self.v_scale = []
        # a draft-model pool (speculative decoding) shares the target
        # pool's allocator: one block table indexes both pools
        self.allocator = (allocator if allocator is not None
                          else BlockAllocator(num_blocks))

    def bytes_saved_vs_unquantized(self, config):
        """Total pool bytes an int8 cache saves versus the SAME pool in
        the model's dtype (0 for an unquantized cache) — scale sidecars
        charged against the saving."""
        if not self.quantized:
            return 0
        fp = kv_pool_bytes_per_block(
            self.block_size, config.num_key_value_heads, config.head_dim,
            kv_dtype=None, base_dtype=self.base_dtype)
        q8 = kv_pool_bytes_per_block(
            self.block_size, config.num_key_value_heads, config.head_dim,
            kv_dtype="int8")
        return (fp - q8) * self.num_blocks * config.num_hidden_layers

    def blocks_for_tokens(self, n_tokens):
        """Blocks needed to hold ``n_tokens``."""
        return -(-int(n_tokens) // self.block_size)

    def table_array(self, block_lists, max_blocks):
        """Host block tables -> device int32 [len(block_lists), max_blocks],
        padded with the null block."""
        out = np.zeros((len(block_lists), max_blocks), np.int32)
        for i, blocks in enumerate(block_lists):
            out[i, :len(blocks)] = blocks
        return jax.device_put(out)

    def copy_block(self, src, dst):
        """Copy one pool block's K/V from ``src`` to ``dst`` across all
        layers (the COW move: the writer gets a private copy, the shared
        original is never mutated). Host-triggered and rare — this is NOT
        inside the compiled step. Quantized pools copy the scale rows
        too: codes without their scales are not a copy."""
        self.k = [kp.at[dst].set(kp[src]) for kp in self.k]
        self.v = [vp.at[dst].set(vp[src]) for vp in self.v]
        if self.quantized:
            self.k_scale = [s.at[dst].set(s[src]) for s in self.k_scale]
            self.v_scale = [s.at[dst].set(s[src]) for s in self.v_scale]

    # -- disaggregated prefill/decode page handoff (ISSUE 15) -----------
    def export_request_pages(self, blocks, covered):
        """Gather the pool content of ``blocks`` (one request's pages, in
        table order) into host arrays: ``{"k": [L, n, block, Hkv, D],
        "v": ..., covered, block_size, kv_dtype}``, plus
        ``k_scale``/``v_scale`` ``[L, n, block, Hkv]`` rows for int8
        pools (codes without their scales are not a page). ``covered``
        records how many leading tokens the pages actually hold — the
        tail block may be partial; its trailing rows are whatever the
        pool holds and are masked by context lengths on the other side,
        exactly as they are here."""
        return self.snapshot_request_pages(blocks, covered).materialize()

    def snapshot_request_pages(self, blocks, covered):
        """Device-side capture of ``blocks`` for the host tier (ISSUE
        16): the per-layer gathers are DISPATCHED now — against the pool
        arrays as they are at this instant, which jax's immutability
        makes safe no matter how soon the allocator hands the blocks to
        a new owner — but the D2H transfer is deferred to
        :meth:`PageSnapshot.materialize` (normally run on the tier's
        transfer thread). The materialized payload is exactly an
        :meth:`export_request_pages` dict."""
        return PageSnapshot(self, blocks, covered)

    def validate_request_pages(self, pages):
        """Typed geometry validation of an import payload WITHOUT
        mutating anything: dtype/block-size match, payload shapes fit
        this pool, and — on quantized pools — the scale rows exist and
        fit too. The decode engine calls this at admission (before any
        blocks are allocated); :meth:`import_request_pages` calls it
        again before writing, so a bad payload can never leave the pool
        half-imported. Returns the number of payload blocks."""
        if pages.get("kv_dtype") != self.kv_dtype:
            raise ValueError(
                f"imported pages carry kv_dtype={pages.get('kv_dtype')!r} "
                f"but this pool stores {self.kv_dtype!r}")
        if int(pages.get("block_size", -1)) != self.block_size:
            raise ValueError(
                f"imported pages use block_size={pages.get('block_size')} "
                f"but this pool uses {self.block_size}")
        k, v = pages["k"], pages["v"]
        want = (len(self.k),) + self.k[0].shape[1:]
        if k.shape[:1] + k.shape[2:] != want or k.shape != v.shape:
            raise ValueError(
                f"imported page shape {k.shape} does not fit this pool "
                f"(layers+block geometry {want})")
        n = k.shape[1]
        if self.quantized:
            swant = want[:-1]
            for nm in ("k_scale", "v_scale"):
                s = pages.get(nm)
                if s is None:
                    raise ValueError(
                        f"int8 pages are missing their {nm} rows — "
                        "codes without scales are not a page")
                if (s.shape[:1] + s.shape[2:] != swant
                        or s.shape[1] != n):
                    raise ValueError(
                        f"imported {nm} shape {s.shape} does not fit "
                        f"this pool (layers+block geometry {swant}, "
                        f"{n} payload blocks)")
        return n

    def import_request_pages(self, blocks, pages):
        """Write an :meth:`export_request_pages` payload into ``blocks``
        of THIS pool (host-triggered, like :meth:`copy_block` — not
        inside a compiled step). ``blocks`` may be longer than the
        payload (admission also allocates room for the next token);
        only the payload's blocks are written. Raises ``ValueError`` on
        any pool-geometry mismatch BEFORE any pool array moves —
        importing pages of the wrong shape/dtype would decode garbage
        silently, and a mid-write failure would be worse."""
        n = self.validate_request_pages(pages)
        if n > len(blocks):
            raise ValueError(
                f"payload holds {n} blocks but only {len(blocks)} were "
                "allocated for the import")
        k, v = pages["k"], pages["v"]
        idx = jnp.asarray(np.asarray(blocks[:n], np.int32))
        self.k = [kp.at[idx].set(jnp.asarray(k[i], kp.dtype))
                  for i, kp in enumerate(self.k)]
        self.v = [vp.at[idx].set(jnp.asarray(v[i], vp.dtype))
                  for i, vp in enumerate(self.v)]
        if self.quantized:
            ks, vs = pages["k_scale"], pages["v_scale"]
            self.k_scale = [s.at[idx].set(jnp.asarray(ks[i], s.dtype))
                            for i, s in enumerate(self.k_scale)]
            self.v_scale = [s.at[idx].set(jnp.asarray(vs[i], s.dtype))
                            for i, s in enumerate(self.v_scale)]


# One compiled gather for a whole spill: every pool array of a capture
# (all layers' k, v and — on int8 pools — scales) goes through a single
# jitted dispatch instead of one eager fancy-index per array. jit's own
# aval cache keys on (pool count, shapes, dtypes, index length), so the
# same callable serves every pool geometry; spilling under device-pressure
# is pure dispatch overhead and this turns ~8 slow eager gathers per
# spill into one fast-path call.
_POOL_GATHER = jax.jit(lambda pools, idx: [p[idx] for p in pools])


class PageSnapshot:
    """Lazily-materialized page capture (see
    :meth:`PagedKVCache.snapshot_request_pages`). ``materialize`` is
    idempotent and thread-safe: the transfer thread races the consumer
    only for who PAYS the D2H, never for what the payload contains."""

    def __init__(self, cache, blocks, covered):
        idx = np.asarray(blocks, np.int32)
        self.nblocks = len(blocks)
        self.covered = int(covered)
        # capture the arming flag NOW: the seal must reflect the policy
        # at snapshot time, not whenever the transfer thread gets around
        # to materializing
        self._seal = bool(cache.page_checksums)
        self._meta = {"covered": int(covered),
                      "block_size": cache.block_size,
                      "kv_dtype": cache.kv_dtype}
        # gathers dispatch against the CURRENT pool bindings; results are
        # device arrays the pool can no longer mutate
        groups = [("k", cache.k), ("v", cache.v)]
        if cache.quantized:
            groups += [("k_scale", cache.k_scale),
                       ("v_scale", cache.v_scale)]
        flat = _POOL_GATHER([p for _, g in groups for p in g],
                            jnp.asarray(idx))
        self._parts, off = {}, 0
        for name, g in groups:
            self._parts[name] = flat[off:off + len(g)]
            off += len(g)
        self._pages = None
        self._lock = threading.Lock()
        # set by the tier: called exactly once, under the snapshot lock,
        # with (nbytes, ms) when the D2H actually runs — whichever of the
        # transfer thread / a consumer gets there first
        self.on_materialized = None

    @property
    def ready(self):
        return self._pages is not None

    def materialize(self):
        """Host payload dict (``export_request_pages`` format); first
        caller pays the D2H and the spill byte/latency telemetry is
        recorded exactly once."""
        with self._lock:
            if self._pages is None:
                t0 = time.perf_counter()
                pages = dict(self._meta)
                for name, parts in self._parts.items():
                    pages[name] = np.stack(
                        [np.asarray(p) for p in parts])
                if self._seal:
                    _integrity.seal_pages(pages)
                nbytes = sum(a.nbytes for a in pages.values()
                             if isinstance(a, np.ndarray))
                self._pages = pages
                self._parts = None  # release device refs
                if self.on_materialized is not None:
                    self.on_materialized(
                        nbytes, (time.perf_counter() - t0) * 1e3)
            return self._pages

    def view(self, i):
        """Single-block view into this capture (batched prefix spill:
        one snapshot serves a whole reclaim wave; each chain hash keys a
        view of its own block)."""
        return _SnapshotView(self, i)


class _SnapshotView:
    """One block of a batched :class:`PageSnapshot` — same ``nblocks``/
    ``materialize`` surface the tier stores, backed by the shared parent
    capture (the wave pays one gather and one D2H, not one per block)."""

    def __init__(self, snap, i):
        self._snap = snap
        self._i = int(i)
        self.nblocks = 1
        self.covered = snap._meta["block_size"]

    def materialize(self):
        pages = self._snap.materialize()
        i = self._i
        # the CRC sidecar is per-block 1-D: slice it by block index, not
        # by the [layer, block, ...] payload axes
        out = {k: (v[i:i + 1] if k == "crc"
                   else v[:, i:i + 1] if isinstance(v, np.ndarray) else v)
               for k, v in pages.items()}
        out["covered"] = self.covered
        return out


class HostKVTier:
    """Bounded host-RAM tier over a :class:`PagedKVCache` (ISSUE 16).

    Two kinds of entries share one LRU under one block budget:

    * ``("req", rid)`` — a preempted request's full page set, spilled by
      the scheduler at eviction and revived (``import_request_pages``)
      on re-admission instead of re-prefilling;
    * ``("prefix", chain_hash)`` — a single refcount-0 registered block
      demoted when the allocator reclaimed it, keyed by the SAME chain
      hash it had on device so :meth:`PrefixCache.match_with_tier` can
      extend a chain walk into host RAM. Prefix-store boot entries land
      here too.

    ``max_host_blocks`` bounds total resident blocks; ``put`` evicts
    oldest entries to fit (spilled content is recomputable — dropping an
    entry costs a re-prefill, never correctness). D2H materialization
    runs on a transfer thread (``DevicePrefetcher`` idiom: dies once,
    warns once, degrades to synchronous conversion on access); every
    access path calls ``materialize()`` itself, so correctness never
    depends on the thread having run.
    """

    def __init__(self, cache, max_host_blocks, instance=None,
                 async_transfer=True):
        if max_host_blocks < 1:
            raise ValueError(
                f"max_host_blocks must be >= 1, got {max_host_blocks}")
        self.cache = cache
        self.max_host_blocks = int(max_host_blocks)
        self.instance = instance
        self._entries = OrderedDict()   # key -> PageSnapshot | dict
        self._blocks_used = 0
        self._tenant_of = {}            # key -> tenant name (tagged only)
        self._tenant_blocks = {}        # tenant -> resident block count
        self._tenant_share = {}         # tenant -> max resident blocks
        self._lock = threading.RLock()
        self._q: queue.Queue = queue.Queue()
        self._thread = None
        if async_transfer:
            self._thread = threading.Thread(
                target=self._worker, daemon=True,
                name=f"{instance or 'kv-tier'}-spill")
            self._thread.start()
        _G_HOST_BLOCKS.set(0, instance=self.instance)

    # -- transfer thread ------------------------------------------------
    def _worker(self):
        while True:
            snap = self._q.get()
            if snap is None:
                return
            try:
                snap.materialize()
            except BaseException as e:  # degrade: consumers materialize
                warnings.warn(
                    f"HostKVTier transfer thread died ({e!r}); degrading "
                    "to synchronous spill materialization", RuntimeWarning)
                return

    def close(self):
        if self._thread is not None:
            self._q.put(None)
            self._thread.join(timeout=2.0)
            self._thread = None
        with self._lock:
            self._entries.clear()
            self._blocks_used = 0
            self._tenant_of.clear()
            self._tenant_blocks.clear()
        _G_HOST_BLOCKS.set(0, instance=self.instance)

    # -- internals ------------------------------------------------------
    def _entry_blocks(self, entry):
        return (int(entry["k"].shape[1]) if isinstance(entry, dict)
                else entry.nblocks)

    def _gauge(self):
        _G_HOST_BLOCKS.set(self._blocks_used, instance=self.instance)

    def set_tenant_share(self, name, max_blocks):
        """Cap one tenant's RESIDENT host blocks (ISSUE 17). Over-share
        inserts evict that tenant's own oldest entries first, so a flood
        of spills from one tenant cannot push other tenants' warm pages
        out of the shared LRU. ``None`` removes the cap."""
        name = str(name)
        with self._lock:
            if max_blocks is None:
                self._tenant_share.pop(name, None)
                return
            if max_blocks < 1:
                raise ValueError(
                    f"tenant share must be >= 1 block, got {max_blocks}")
            self._tenant_share[name] = int(max_blocks)

    def _account(self, key, nblocks, tenant):
        self._blocks_used += nblocks
        if tenant is not None:
            self._tenant_of[key] = tenant
            self._tenant_blocks[tenant] = (
                self._tenant_blocks.get(tenant, 0) + nblocks)

    def _unaccount(self, key, entry):
        n = self._entry_blocks(entry)
        self._blocks_used -= n
        t = self._tenant_of.pop(key, None)
        if t is not None:
            left = self._tenant_blocks.get(t, 0) - n
            if left > 0:
                self._tenant_blocks[t] = left
            else:
                self._tenant_blocks.pop(t, None)

    def _put(self, key, entry, nblocks, tenant=None):
        """Insert under the budget, LRU-evicting other entries to fit.
        A tagged tenant over its share evicts ITS OWN oldest entries
        first before touching the shared LRU. Returns False (no state
        change) when the entry alone exceeds the whole budget or the
        tenant's share."""
        if nblocks > self.max_host_blocks:
            return False
        tenant = str(tenant) if tenant is not None else None
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._unaccount(key, old)
            share = (self._tenant_share.get(tenant)
                     if tenant is not None else None)
            if share is not None:
                if nblocks > share:
                    return False
                while (self._tenant_blocks.get(tenant, 0) + nblocks
                       > share):
                    victim_key = next(
                        (k for k in self._entries
                         if self._tenant_of.get(k) == tenant), None)
                    if victim_key is None:
                        break
                    victim = self._entries.pop(victim_key)
                    self._unaccount(victim_key, victim)
                    _M_HOST_EVICT.inc(instance=self.instance)
            while (self._blocks_used + nblocks > self.max_host_blocks
                   and self._entries):
                victim_key, victim = self._entries.popitem(last=False)
                self._unaccount(victim_key, victim)
                _M_HOST_EVICT.inc(instance=self.instance)
            self._entries[key] = entry
            self._account(key, nblocks, tenant)
            self._gauge()
        return True

    def _get(self, key, pop):
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            if pop:
                self._entries.pop(key)
                self._unaccount(key, entry)
            else:
                self._entries.move_to_end(key)
            self._gauge()
        pages = entry if isinstance(entry, dict) else entry.materialize()
        # ISSUE 20 read-back boundary: a sealed payload (page checksums
        # armed when it was written, or loaded from the prefix store)
        # verifies before it can revive. Mismatch degrades EXACTLY like
        # an LRU drop — the entry is freed and the caller re-prefills;
        # a corrupt page is never served.
        try:
            _integrity.verify_pages(pages, instance=self.instance,
                                    key=key)
        except KVIntegrityError as e:
            warnings.warn(f"HostKVTier dropping corrupt entry: {e}",
                          RuntimeWarning)
            with self._lock:
                stale = self._entries.pop(key, None)
                if stale is not None:
                    self._unaccount(key, stale)
                    self._gauge()
            return None
        return pages

    def _spill(self, key, blocks, covered, tenant=None):
        """Shared spill path: fire the fault site (failure degrades to
        recompute-eviction — the caller just proceeds as if no tier were
        attached), snapshot, insert, queue the async D2H."""
        try:
            _fi.fire("serve.kv_spill")
        except Exception:
            return False
        snap = self.cache.snapshot_request_pages(blocks, covered)
        snap.on_materialized = lambda nbytes, ms: (
            _M_SPILL_BYTES.inc(nbytes, instance=self.instance),
            _H_SPILL_MS.observe(ms, instance=self.instance))
        if not self._put(key, snap, snap.nblocks, tenant=tenant):
            return False
        _M_SPILLS.inc(instance=self.instance)
        if self._thread is not None:
            self._q.put(snap)
        return True

    # -- preempted-request entries (scheduler-facing) -------------------
    def spill_request(self, rid, blocks, covered, tenant=None):
        """Spill one preempted request's pages under ``("req", rid)``;
        the caller frees the device blocks right after (the snapshot's
        gathers already dispatched)."""
        n = -(-int(covered) // self.cache.block_size)
        return self._spill(("req", int(rid)), list(blocks)[:n], covered,
                           tenant=tenant)

    def peek_request(self, rid):
        """Materialized payload for a spilled request (MRU-touched, NOT
        removed — removal happens at :meth:`drop_request` once admission
        actually succeeds), or None if the tier LRU dropped it."""
        return self._get(("req", int(rid)), pop=False)

    def drop_request(self, rid):
        with self._lock:
            key = ("req", int(rid))
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._unaccount(key, entry)
                self._gauge()

    # -- prefix-block entries -------------------------------------------
    def spill_blocks(self, pairs, tenants=None):
        """Demote a reclaim WAVE of registered blocks — ``(block_id,
        chain_hash)`` pairs — in one batch: one fault-site fire, one
        device gather, one queued D2H for the whole wave; each chain
        hash keys a single-block view of the shared capture. ``tenants``
        (parallel to ``pairs``, entries may be None) tags each demoted
        block for per-tenant share accounting. Wired as
        ``PrefixCache.on_spill``."""
        if not pairs:
            return
        try:
            _fi.fire("serve.kv_spill")
        except Exception:
            return
        blocks = [b for b, _ in pairs]
        snap = self.cache.snapshot_request_pages(
            blocks, len(blocks) * self.cache.block_size)
        snap.on_materialized = lambda nbytes, ms: (
            _M_SPILL_BYTES.inc(nbytes, instance=self.instance),
            _H_SPILL_MS.observe(ms, instance=self.instance))
        put_any = False
        for i, (_, h) in enumerate(pairs):
            tenant = tenants[i] if tenants is not None else None
            if self._put(("prefix", bytes(h)), snap.view(i), 1,
                         tenant=tenant):
                put_any = True
                _M_SPILLS.inc(instance=self.instance)
        if put_any and self._thread is not None:
            self._q.put(snap)

    def spill_block(self, block_id, chain_hash, tenant=None):
        """Demote one reclaimed registered block (its chain hash is the
        tier key); single-pair form of :meth:`spill_blocks`."""
        self.spill_blocks([(block_id, chain_hash)], [tenant])

    def has_prefix(self, chain_hash):
        with self._lock:
            key = ("prefix", bytes(chain_hash))
            if key not in self._entries:
                return False
            self._entries.move_to_end(key)
            return True

    def pop_prefix(self, chain_hash):
        """Materialized single-block payload for a host-resident chain
        link (removed: the block is being revived into the device pool,
        where it is re-registered under the same hash)."""
        return self._get(("prefix", bytes(chain_hash)), pop=True)

    def put_prefix_payload(self, chain_hash, pages, tenant=None):
        """Insert an already-materialized single-block payload (prefix
        store boot path)."""
        return self._put(("prefix", bytes(chain_hash)), pages,
                         int(pages["k"].shape[1]), tenant=tenant)

    def prefix_items(self):
        """Materialized ``(chain_hash, payload)`` pairs currently
        resident (for the prefix store's save pass; entries stay put)."""
        with self._lock:
            keys = [k for k in self._entries if k[0] == "prefix"]
        out = []
        for key in keys:
            pages = self._get(key, pop=False)
            if pages is not None:
                out.append((key[1], pages))
        return out

    def drop_prefixes(self):
        """Drop every prefix entry (weight fingerprint changed: host
        content no longer matches any chain under the new weights)."""
        with self._lock:
            for key in [k for k in self._entries if k[0] == "prefix"]:
                entry = self._entries.pop(key)
                self._unaccount(key, entry)
            self._gauge()

    @property
    def host_blocks_in_use(self):
        with self._lock:
            return self._blocks_used

    def tenant_blocks_in_use(self, name):
        """Resident host blocks currently accounted to one tenant."""
        with self._lock:
            return self._tenant_blocks.get(str(name), 0)

    def __len__(self):
        with self._lock:
            return len(self._entries)


def pack_kv_pages(pages):
    """Serialize an ``export_request_pages`` payload to bytes (npz,
    pickle-free) for the fleet's CRC-framed transfer channel."""
    buf = io.BytesIO()
    arrays = {k: v for k, v in pages.items()
              if isinstance(v, np.ndarray)}
    arrays["covered"] = np.int64(pages["covered"])
    arrays["block_size"] = np.int64(pages["block_size"])
    arrays["kv_dtype"] = np.frombuffer(
        (pages["kv_dtype"] or "").encode(), np.uint8)
    np.savez(buf, **arrays)
    return buf.getvalue()


def unpack_kv_pages(data):
    """Inverse of :func:`pack_kv_pages`. Raises ``ValueError`` on a
    payload that does not parse as the page format — the caller treats
    that as a corrupt transfer (the CRC framing should have caught it
    first)."""
    try:
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            out = {k: z[k] for k in z.files}
    except Exception as e:
        raise ValueError(f"undecodable KV page payload: {e}") from e
    for key in ("covered", "block_size", "kv_dtype", "k", "v"):
        if key not in out:
            raise ValueError(f"KV page payload missing field {key!r}")
    out["covered"] = int(out["covered"])
    out["block_size"] = int(out["block_size"])
    dt = bytes(out["kv_dtype"]).decode() or None
    out["kv_dtype"] = dt
    if dt == "int8":
        for key in ("k_scale", "v_scale"):
            if key not in out:
                raise ValueError(
                    f"int8 KV page payload missing field {key!r} — "
                    "codes without scales are not a page")
    return out
