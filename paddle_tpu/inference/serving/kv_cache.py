"""Block-allocated paged KV cache (ISSUE 7 tentpole, part a).

The flagship decode loop used to grow its cache by ``concat`` — a fresh
XLA compile and a full cache copy per generated token, and worse, memory
sized for every request's MAXIMUM length up front. The serving fix
(vLLM-style, per PAPERS.md "Ragged Paged Attention … for TPU") is a
static block pool:

* one ``[num_blocks, block_size, num_kv_heads, head_dim]`` K and V array
  per layer, allocated ONCE — shapes never change, so one compiled decode
  graph serves any mix of request lengths;
* a host-side free-list ``BlockAllocator`` hands blocks to requests as
  they grow, token by token — memory is proportional to tokens actually
  alive, not to worst-case lengths;
* per-request **block tables** (host lists, shipped to the device as a
  small int32 array each step) map logical token positions to pool
  blocks; all pool writes happen in-graph via ``lax.dynamic_update_slice``
  so the decode executable is reused forever.

Block 0 is reserved as the **null block**: padded table entries point at
it, so in-graph writes for padding land somewhere harmless instead of
clobbering a live request's block. It is never handed out.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["BlockAllocator", "PagedKVCache"]


class BlockAllocator:
    """LIFO free-list over ``num_blocks`` pool blocks.

    Block 0 is the reserved null block (see module docstring) and is never
    allocated. ``allocate`` is all-or-nothing: asking for more blocks than
    are free returns ``None`` and takes nothing — the scheduler's signal
    to queue (or evict), never a partial grab to unwind.
    """

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (one is the reserved null "
                             f"block), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        # LIFO: recently-freed (cache-warm) blocks are reused first
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._allocated = set()
        self.high_water = 0

    @property
    def num_free(self):
        return len(self._free)

    def allocate(self, n=1):
        """``n`` block ids, or ``None`` (and no state change) if fewer
        than ``n`` are free."""
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        self._allocated.update(ids)
        self.high_water = max(self.high_water, len(self._allocated))
        return ids

    def free(self, ids):
        for b in ids:
            if b not in self._allocated:
                raise ValueError(f"double-free or foreign block {b}")
            self._allocated.discard(b)
            self._free.append(b)


class PagedKVCache:
    """Static per-layer K/V block pools + the allocator that carves them.

    ``k``/``v`` are lists (one per layer) of
    ``[num_blocks, block_size, num_kv_heads, head_dim]`` arrays. They are
    plain jax arrays deliberately: the engine threads them through its
    compiled step functions (donated on TPU) and rebinds the returned
    buffers, exactly like ``FusedTrainStep`` handles optimizer state.
    """

    def __init__(self, config, num_blocks, block_size, dtype=None):
        if dtype is None:
            dtype = jnp.float32
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        shape = (self.num_blocks, self.block_size,
                 config.num_key_value_heads, config.head_dim)
        L = config.num_hidden_layers
        self.k = [jnp.zeros(shape, dtype) for _ in range(L)]
        self.v = [jnp.zeros(shape, dtype) for _ in range(L)]
        self.allocator = BlockAllocator(num_blocks)

    def blocks_for_tokens(self, n_tokens):
        """Blocks needed to hold ``n_tokens``."""
        return -(-int(n_tokens) // self.block_size)

    def table_array(self, block_lists, max_blocks):
        """Host block tables -> device int32 [len(block_lists), max_blocks],
        padded with the null block."""
        import numpy as np

        out = np.zeros((len(block_lists), max_blocks), np.int32)
        for i, blocks in enumerate(block_lists):
            out[i, :len(blocks)] = blocks
        return jax.device_put(out)
