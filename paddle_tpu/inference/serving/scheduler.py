"""Continuous batching scheduler (ISSUE 7 tentpole, part c).

Token-granularity admission into a fixed set of decode slots:

* a fixed ``max_batch_size`` of decode slots so the decode graph compiles
  ONCE — a finished request's slot is refilled by the next waiting request
  at the very next step (continuous batching), never by re-batching into a
  new shape;
* **prefill/decode split**: prompts run through their own compiled
  prefill graphs (one per registered length bucket — the PR-1 shape-bucket
  discipline), decode runs the shared fixed-shape step; a step admits at
  most ``max_prefills_per_step`` prompts so decode latency for running
  requests stays bounded;
* **graceful degradation**: a request that cannot get blocks stays queued
  (FIFO) — the engine never crashes on pool exhaustion. If a RUNNING
  request cannot grow by one block, the scheduler evicts the
  most-recently-admitted running request (its blocks free immediately, it
  re-queues at the FRONT and will re-prefill from its full
  prompt+generated prefix later — greedy decode makes the re-derived
  tokens identical), mirroring vLLM's recompute preemption;
* blocks free the moment a request finishes (EOS or max_new_tokens).
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque

import numpy as np

from ...observability import metrics as _obs_metrics

__all__ = ["SamplingParams", "Request", "Scheduler"]

# engine-owned admission/eviction counters (ISSUE 10 satellite): the
# registry — labeled by the owning engine/scheduler instance — is the
# authoritative store; ``Scheduler.stats`` is a thin backward-compatible
# dict view over it, and bench_serving reads the registry instead of
# recomputing from private fields.
_M_ADMITTED = _obs_metrics.counter(
    "serving_requests_admitted_total", "requests admitted to decode slots")
_M_EVICTIONS = _obs_metrics.counter(
    "serving_evictions_total",
    "recompute-preemption evictions under pool pressure")
_M_FINISHED = _obs_metrics.counter(
    "serving_requests_finished_total", "requests finished (EOS or length)")
_M_QUEUED_EXH = _obs_metrics.counter(
    "serving_queued_on_exhaustion_total",
    "admissions deferred because the block pool was exhausted")

WAITING, RUNNING, FINISHED = "waiting", "running", "finished"


@dataclasses.dataclass
class SamplingParams:
    max_new_tokens: int = 32
    eos_token_id: int | None = None
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int | None = None
    top_p: float | None = None
    seed: int | None = None


class Request:
    """One in-flight generation request."""

    _ids = itertools.count(1)

    def __init__(self, prompt_ids, sampling: SamplingParams | None = None,
                 rid=None, arrival_t=None):
        self.rid = rid if rid is not None else next(Request._ids)
        self.prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.sampling = sampling or SamplingParams()
        self.arrival_t = arrival_t
        self.state = WAITING
        # observability timestamps (perf_counter_ns; host clocks only):
        # queue-entry time for the queued->running span, first/last token
        # times for TTFT / inter-token latency, decode-phase start
        self.t_queue_start = time.perf_counter_ns()
        self.t_submit = None
        self.t_first_token = None
        self.t_last_token = None
        self.t_decode_start = None
        self.output_tokens: list[int] = []
        self.blocks: list[int] = []       # pool block ids, in order
        self.num_cached = 0               # tokens materialized in the pool
        self.admit_seq = -1               # admission order (eviction policy)
        self.evictions = 0
        self._rng = (np.random.RandomState(self.sampling.seed)
                     if self.sampling.do_sample else None)

    @property
    def tokens(self):
        """Prompt + generated so far (the re-prefill prefix on eviction)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.output_tokens, np.int32)])

    @property
    def num_tokens(self):
        """O(1) token count — ``.tokens`` concatenates, so hot scheduler
        loops must not call it just to measure."""
        return len(self.prompt) + len(self.output_tokens)

    @property
    def last_token(self):
        return (self.output_tokens[-1] if self.output_tokens
                else int(self.prompt[-1]))

    @property
    def finished(self):
        return self.state == FINISHED

    def finish_reason(self):
        if self.state != FINISHED:
            return None
        s = self.sampling
        if (s.eos_token_id is not None and self.output_tokens
                and self.output_tokens[-1] == s.eos_token_id):
            return "eos"
        return "length"

    def should_finish(self):
        s = self.sampling
        if len(self.output_tokens) >= s.max_new_tokens:
            return True
        return (s.eos_token_id is not None and self.output_tokens
                and self.output_tokens[-1] == s.eos_token_id)


class Scheduler:
    """Slots + FIFO wait queue over a :class:`BlockAllocator`.

    ``instance`` names this scheduler's registry label (the owning
    ``LLMEngine`` passes its own name, so every serving counter of one
    engine shares one label); standalone schedulers get an auto name.
    """

    _ids = itertools.count(1)

    def __init__(self, allocator, block_size, max_batch_size,
                 max_prefills_per_step=1, instance=None):
        self.allocator = allocator
        self.block_size = int(block_size)
        self.slots: list[Request | None] = [None] * int(max_batch_size)
        self.waiting: deque[Request] = deque()
        self.max_prefills_per_step = int(max_prefills_per_step)
        self._admit_seq = itertools.count()
        self.instance = instance or f"scheduler#{next(Scheduler._ids)}"
        # pre-touch the series so stats reads zeros before any event
        for m in (_M_ADMITTED, _M_EVICTIONS, _M_FINISHED, _M_QUEUED_EXH):
            m.inc(0, instance=self.instance)

    @property
    def stats(self):
        """Backward-compatible dict view over the registry counters."""
        inst = self.instance
        return {
            "admitted": int(_M_ADMITTED.value(instance=inst)),
            "evictions": int(_M_EVICTIONS.value(instance=inst)),
            "finished": int(_M_FINISHED.value(instance=inst)),
            "queued_on_exhaustion": int(_M_QUEUED_EXH.value(instance=inst)),
        }

    # -- queries ---------------------------------------------------------
    @property
    def running(self):
        return [r for r in self.slots if r is not None]

    def has_work(self):
        return bool(self.waiting) or any(self.slots)

    def _free_slot(self):
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    # -- admission (prefill picks) --------------------------------------
    def pick_prefills(self):
        """Waiting requests to prefill THIS step: pops up to
        ``max_prefills_per_step`` requests that fit (a free slot + blocks
        for prompt-and-first-token). A head-of-queue request that does not
        fit stays queued — FIFO, no overtaking — and the engine simply
        decodes with what is running."""
        picked = []
        while (len(picked) < self.max_prefills_per_step and self.waiting
               and self._free_slot() is not None):
            req = self.waiting[0]
            need = -(-(req.num_tokens + 1) // self.block_size)
            blocks = self.allocator.allocate(need)
            if blocks is None:
                _M_QUEUED_EXH.inc(instance=self.instance)
                break
            self.waiting.popleft()
            slot = self._free_slot()
            req.blocks = blocks
            req.state = RUNNING
            req.admit_seq = next(self._admit_seq)
            self.slots[slot] = req
            _M_ADMITTED.inc(instance=self.instance)
            picked.append((slot, req))
        return picked

    # -- decode-time growth / eviction ----------------------------------
    def ensure_decode_room(self):
        """Grow every running request that is about to write past its last
        block. On exhaustion, evict the most-recently-admitted running
        request (free its blocks, re-queue at the FRONT) and retry —
        token-granularity eviction. Returns the evicted requests."""
        evicted = []
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            # the decode step writes ONE token at position len(tokens)-1,
            # so capacity len(tokens) is exactly enough — demanding a
            # lookahead block here would evict needlessly when the pool is
            # full at a block boundary
            while req.num_tokens > len(req.blocks) * self.block_size:
                got = self.allocator.allocate(1)
                if got is not None:
                    req.blocks.extend(got)
                    continue
                victim = max((r for r in self.running if r is not req),
                             key=lambda r: r.admit_seq, default=None)
                if victim is None:
                    victim = req  # alone and out of memory: preempt self
                self._evict(victim)
                evicted.append(victim)
                if victim is req:
                    break
        return evicted

    def _evict(self, req):
        slot = self.slots.index(req)
        self.allocator.free(req.blocks)
        req.blocks = []
        req.num_cached = 0
        req.state = WAITING
        req.evictions += 1
        req.t_queue_start = time.perf_counter_ns()  # re-queued span start
        self.slots[slot] = None
        self.waiting.appendleft(req)
        _M_EVICTIONS.inc(instance=self.instance)

    # -- completion ------------------------------------------------------
    def finish(self, req):
        slot = self.slots.index(req)
        self.allocator.free(req.blocks)
        req.blocks = []
        req.state = FINISHED
        self.slots[slot] = None
        _M_FINISHED.inc(instance=self.instance)
