"""Continuous batching scheduler (ISSUE 7 tentpole, part c; prefix-aware
admission + chunked prefill added by ISSUE 11).

Token-granularity admission into a fixed set of decode slots:

* a fixed ``max_batch_size`` of decode slots so the decode graph compiles
  ONCE — a finished request's slot is refilled by the next waiting request
  at the very next step (continuous batching), never by re-batching into a
  new shape;
* **prefill/decode split with chunking**: prompts run through compiled
  chunk-prefill graphs (block-aligned chunks, one graph per chunk-length
  bucket — the PR-1 shape-bucket discipline); ``prefill_work`` hands out
  at most ``max_prefill_tokens_per_step`` NEW prompt tokens per engine
  step, so a 2k-token prompt is interleaved with decode steps instead of
  monopolizing them — decode inter-token latency is bounded by the chunk
  budget, not the longest queued prompt;
* **prefix-aware admission**: when a :class:`~.kv_cache.PrefixCache` is
  attached, admission matches the request's tokens against the hash-chain
  index and charges the allocator only for the UNSHARED tail — matched
  blocks are ``acquire``\\ d (ref-counted), ``num_cached`` starts past
  them, and the engine prefills only the remainder;
* **copy-on-write guard**: before decode/verify writes, any block in the
  write window that another request can see (refcount > 1) is replaced by
  a private copy (the device-side page copy is queued on ``pending_cow``
  for the engine to execute); a refcount-1 block that is still registered
  in the prefix index merely retracts its published identity. By
  construction only FULL blocks are shared, so the common path never
  copies — the guard enforces the invariant rather than paying for it;
* **graceful degradation**: a request that cannot get blocks stays queued
  (FIFO) — the engine never crashes on pool exhaustion. If a RUNNING
  request cannot grow by one block, the scheduler evicts the
  most-recently-admitted running request (its blocks free immediately, it
  re-queues at the FRONT and will re-prefill from its full
  prompt+generated prefix later — greedy decode makes the re-derived
  tokens identical), mirroring vLLM's recompute preemption;
* blocks free the moment a request finishes (EOS or max_new_tokens) —
  under prefix sharing "free" means decref: a shared block is reclaimed
  only when its LAST holder releases it.

``version`` counts every block-table mutation (admission, growth,
eviction, finish, COW, trim): the engine caches the device block-table
array against it, so steady-state decode re-uploads nothing (ISSUE 11
satellite).

**Multi-tenant QoS (ISSUE 17).** Requests carry a ``tenant=`` identity
and a ``tier`` (``latency`` | ``batch``). Once any tenant is configured
(:meth:`Scheduler.configure_tenant`) or non-default traffic is queued,
admission switches from strict FIFO to weighted-fair queuing: the
latency tier strictly outranks the batch tier, and within a tier the
backlogged tenant with the lowest *virtual time* (served tokens /
weight) admits next — its own requests still in FIFO order, so each
request's token outputs stay bit-identical to an undisturbed run (QoS
moves *when* work runs, never *which* tokens). Per-tenant token-rate
quotas (:class:`TenantQuota`, the launcher's ``RestartBudget`` leaky
bucket over served tokens) DEFER an over-quota tenant's admissions
instead of shedding them. Batch-tier requests *yield* decode slots
under latency pressure: they are preempted through the normal eviction
path — which spills decode-ready pages to the ISSUE-16 host tier, so
revival is a page import, not a re-prefill — and re-admit when the
pressure drops. Pure-default traffic never touches any of this: the
FIFO admission order of PR 7 is preserved exactly.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque

import numpy as np

from ...observability import metrics as _obs_metrics

__all__ = ["SamplingParams", "Request", "Scheduler", "TenantQuota",
           "TIER_LATENCY", "TIER_BATCH"]

# engine-owned admission/eviction counters (ISSUE 10 satellite): the
# registry — labeled by the owning engine/scheduler instance — is the
# authoritative store; ``Scheduler.stats`` is a thin backward-compatible
# dict view over it, and bench_serving reads the registry instead of
# recomputing from private fields.
_M_ADMITTED = _obs_metrics.counter(
    "serving_requests_admitted_total", "requests admitted to decode slots")
_M_EVICTIONS = _obs_metrics.counter(
    "serving_evictions_total",
    "recompute-preemption evictions under pool pressure")
_M_FINISHED = _obs_metrics.counter(
    "serving_requests_finished_total", "requests finished (EOS or length)")
_M_QUEUED_EXH = _obs_metrics.counter(
    "serving_queued_on_exhaustion_total",
    "admissions deferred because the block pool was exhausted")
_M_PREFIX_REUSED = _obs_metrics.counter(
    "serving_prefix_blocks_reused_total",
    "pool blocks admitted from the prefix cache instead of fresh prefill")
_M_COW = _obs_metrics.counter(
    "serving_cow_copies_total",
    "copy-on-write block copies (divergent write to a shared block)")
# multi-tenant QoS (ISSUE 17)
_M_THROTTLED = _obs_metrics.counter(
    "serving_quota_throttled_total",
    "admission passes that deferred every waiting tenant on its token-"
    "rate quota (deferred, never shed)")
_M_BATCH_YIELD = _obs_metrics.counter(
    "serving_batch_yields_total",
    "batch-tier requests preempted (spilled to the host tier when "
    "decode-ready) so latency-tier work could take their slot")
_M_TENANT_TOKENS = _obs_metrics.counter(
    "serving_tenant_tokens_total",
    "tokens served per tenant (prefill chunks + decode emissions); the "
    "tenant label is bounded to configured tenant names plus 'default'")

WAITING, RUNNING, FINISHED = "waiting", "running", "finished"
TIER_LATENCY, TIER_BATCH = "latency", "batch"


class TenantQuota:
    """Per-tenant token-rate quota: a rolling-window leaky bucket over
    SERVED tokens, mirroring the launcher's ``RestartBudget`` (events
    pruned past the window, injectable clock so tests never sleep).

    ``rate_tokens_per_s * window_s`` tokens may be served per rolling
    ``window_s`` window. The scheduler charges tokens as they are served
    (prefill chunks and decode emissions) and gates *admission* on the
    bucket: an over-quota tenant's waiting requests are deferred — never
    shed — until enough history ages out. One in-flight request may
    overshoot the limit; throttling mid-decode would hold a decode slot
    idle, the one thing a fixed-slot engine can never afford.
    :meth:`retry_after` estimates the wait, the machine-readable backoff
    hint the router's typed quota rejection carries (ISSUE 17 satellite).
    """

    def __init__(self, rate_tokens_per_s, window_s=1.0,
                 clock=time.monotonic):
        self.rate = float(rate_tokens_per_s)
        if self.rate <= 0:
            raise ValueError(
                f"rate_tokens_per_s must be > 0, got {rate_tokens_per_s}")
        self.window_s = float(window_s)
        self.limit = self.rate * self.window_s
        self._clock = clock
        self._events: deque[tuple[float, float]] = deque()
        self._used = 0.0

    def _prune(self, now):
        ev = self._events
        while ev and now - ev[0][0] > self.window_s:
            self._used -= ev.popleft()[1]

    @property
    def used(self):
        """Tokens served inside the current rolling window."""
        self._prune(self._clock())
        return self._used

    def admissible(self):
        return self.used < self.limit

    def note(self, n):
        """Charge ``n`` served tokens to the window."""
        now = self._clock()
        self._prune(now)
        self._events.append((now, float(n)))
        self._used += float(n)

    def retry_after(self):
        """Seconds until the bucket re-admits (0.0 while admissible)."""
        now = self._clock()
        self._prune(now)
        if self._used < self.limit:
            return 0.0
        over = self._used - self.limit
        expired = 0.0
        for t, n in self._events:
            expired += n
            if expired > over:
                return max(0.0, t + self.window_s - now)
        return self.window_s


class _TenantState:
    """Scheduler-side per-tenant accounting: the WFQ virtual time plus
    the optional rate quota. ``configured`` marks tenants registered via
    ``configure_tenant`` — only their names appear as metric label
    values (the cardinality bound); ad-hoc tenant names are served under
    default weight and labeled ``default``."""

    __slots__ = ("name", "weight", "quota", "served_tokens", "vtime",
                 "configured")

    def __init__(self, name, weight=1.0, quota=None, vtime=0.0):
        self.name = str(name)
        self.weight = float(weight)
        self.quota = quota
        self.served_tokens = 0
        self.vtime = float(vtime)
        self.configured = False


@dataclasses.dataclass
class SamplingParams:
    max_new_tokens: int = 32
    eos_token_id: int | None = None
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int | None = None
    top_p: float | None = None
    seed: int | None = None


class Request:
    """One in-flight generation request."""

    _ids = itertools.count(1)

    def __init__(self, prompt_ids, sampling: SamplingParams | None = None,
                 rid=None, arrival_t=None, deadline=None, tenant=None,
                 tier=None):
        self.rid = rid if rid is not None else next(Request._ids)
        self.prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.sampling = sampling or SamplingParams()
        self.arrival_t = arrival_t
        # multi-tenant QoS (ISSUE 17): who this request bills to and how
        # urgent it is. ``latency`` requests hold their decode slots;
        # ``batch`` requests admit behind latency work and yield their
        # slots under pressure (spill to the host tier, revive later).
        self.tenant = str(tenant) if tenant else "default"
        tier = tier or TIER_LATENCY
        if tier not in (TIER_LATENCY, TIER_BATCH):
            raise ValueError(f"unknown tier {tier!r}; expected "
                             f"{TIER_LATENCY!r} or {TIER_BATCH!r}")
        self.tier = tier
        # absolute wall-clock deadline (time.time() seconds, ISSUE 12):
        # the engine checks it at admission and at every step; expiry
        # aborts the request with a typed RequestTimeoutError finish
        self.deadline = float(deadline) if deadline is not None else None
        # set by Scheduler.abort: "timeout" / "cancelled" — overrides the
        # eos/length finish reasons
        self.abort_reason = None
        self.state = WAITING
        # observability timestamps (perf_counter_ns; host clocks only):
        # queue-entry time for the queued->running span, first/last token
        # times for TTFT / inter-token latency, decode-phase start
        self.t_queue_start = time.perf_counter_ns()
        self.t_submit = None
        self.t_first_token = None
        self.t_last_token = None
        self.t_decode_start = None
        self.output_tokens: list[int] = []
        self.blocks: list[int] = []       # pool block ids, in order
        self.num_cached = 0               # tokens materialized in the pool
        # chunked prefill: tokens the CURRENT admission must materialize
        # before the request is decode-ready; ``prefilling`` is True from
        # admission until the final chunk's logits were sampled
        self.prefill_upto = 0
        self.prefilling = False
        # speculative decoding: tokens materialized in the DRAFT pool
        self.draft_cached = 0
        # disaggregated handoff (ISSUE 15): pages computed by a prefill
        # worker, imported at admission instead of prefilling. Cleared
        # after the one-time import — an eviction re-prefills normally.
        self.preloaded = None
        # KV tiering (ISSUE 16): non-None while this request's pages sit
        # in the host tier (set at spill-eviction, keyed by rid);
        # ``revived_from_tier`` marks an admission whose ``preloaded``
        # payload came back FROM the tier, so the engine can count the
        # revive (and its bytes/latency) separately from fleet handoffs.
        self.spill_key = None
        self.revived_from_tier = False
        self.admit_seq = -1               # admission order (eviction policy)
        self.evictions = 0
        # last-position logits row, stashed by the engine only when it was
        # built with ``capture_logits=True`` (ISSUE 18: the copy is a [V]
        # f32 D2H per emission, so it is opt-in); None otherwise
        self.last_logits = None
        self._rng = (np.random.RandomState(self.sampling.seed)
                     if self.sampling.do_sample else None)

    @property
    def tokens(self):
        """Prompt + generated so far (the re-prefill prefix on eviction)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.output_tokens, np.int32)])

    @property
    def num_tokens(self):
        """O(1) token count — ``.tokens`` concatenates, so hot scheduler
        loops must not call it just to measure."""
        return len(self.prompt) + len(self.output_tokens)

    @property
    def last_token(self):
        return (self.output_tokens[-1] if self.output_tokens
                else int(self.prompt[-1]))

    @property
    def finished(self):
        return self.state == FINISHED

    def finish_reason(self):
        if self.state != FINISHED:
            return None
        if self.abort_reason is not None:
            return self.abort_reason
        s = self.sampling
        if (s.eos_token_id is not None and self.output_tokens
                and self.output_tokens[-1] == s.eos_token_id):
            return "eos"
        return "length"

    def should_finish(self):
        s = self.sampling
        if len(self.output_tokens) >= s.max_new_tokens:
            return True
        return (s.eos_token_id is not None and self.output_tokens
                and self.output_tokens[-1] == s.eos_token_id)


class Scheduler:
    """Slots + FIFO wait queue over a :class:`BlockAllocator`.

    ``instance`` names this scheduler's registry label (the owning
    ``LLMEngine`` passes its own name, so every serving counter of one
    engine shares one label); standalone schedulers get an auto name.
    ``prefix_cache`` (a :class:`~.kv_cache.PrefixCache`) arms prefix-aware
    admission; ``None`` keeps the PR-7 charge-everything behavior.
    """

    _ids = itertools.count(1)

    def __init__(self, allocator, block_size, max_batch_size,
                 max_prefills_per_step=1, instance=None, prefix_cache=None,
                 kv_tier=None):
        self.allocator = allocator
        self.block_size = int(block_size)
        self.slots: list[Request | None] = [None] * int(max_batch_size)
        self.waiting: deque[Request] = deque()
        self.max_prefills_per_step = int(max_prefills_per_step)
        self._admit_seq = itertools.count()
        self.instance = instance or f"scheduler#{next(Scheduler._ids)}"
        self.prefix_cache = prefix_cache
        # host-RAM tier (ISSUE 16, a kv_cache.HostKVTier): eviction spills
        # decode-ready requests' pages instead of dropping them, admission
        # revives spilled requests / host-resident prefix chains by page
        # import instead of re-prefill. None keeps recompute preemption.
        self.kv_tier = kv_tier
        # (req, block_id, chain_hash) host-prefix revivals the engine must
        # import+adopt before this step's prefill work (drained like
        # pending_cow)
        self.pending_revive: list[tuple] = []
        # spill revivals that missed: entry LRU-dropped or freed by the
        # tier's read-back integrity check (ISSUE 20) — each one is a
        # silent degrade to re-prefill, worth seeing when it spikes
        self.revive_misses = 0
        # block-table mutation counter: the engine invalidates its cached
        # device table array on change, so steady-state decode does ZERO
        # table H2D (ISSUE 11 satellite)
        self.version = 0
        # (src, dst) device page copies the engine must run before the
        # next pool write — queued by the COW guard, drained by step()
        self.pending_cow: list[tuple[int, int]] = []
        # multi-tenant QoS (ISSUE 17): per-tenant WFQ/quota state, lazily
        # created per tenant name. The weighted-fair admission path arms
        # itself only once a tenant is configured or non-default traffic
        # is queued — pure-default traffic keeps the exact FIFO order.
        self.tenants: dict[str, _TenantState] = {}
        self._qos_configured = False
        # pre-touch the series so stats reads zeros before any event
        for m in (_M_ADMITTED, _M_EVICTIONS, _M_FINISHED, _M_QUEUED_EXH,
                  _M_PREFIX_REUSED, _M_COW, _M_THROTTLED, _M_BATCH_YIELD):
            m.inc(0, instance=self.instance)
        _M_TENANT_TOKENS.inc(0, instance=self.instance, tenant="default")

    @property
    def stats(self):
        """Backward-compatible dict view over the registry counters."""
        inst = self.instance
        return {
            "admitted": int(_M_ADMITTED.value(instance=inst)),
            "evictions": int(_M_EVICTIONS.value(instance=inst)),
            "finished": int(_M_FINISHED.value(instance=inst)),
            "queued_on_exhaustion": int(_M_QUEUED_EXH.value(instance=inst)),
            "prefix_blocks_reused": int(
                _M_PREFIX_REUSED.value(instance=inst)),
            "cow_copies": int(_M_COW.value(instance=inst)),
            "quota_throttled": int(_M_THROTTLED.value(instance=inst)),
            "batch_yields": int(_M_BATCH_YIELD.value(instance=inst)),
            "revive_misses": self.revive_misses,
        }

    # -- multi-tenant QoS (ISSUE 17) -------------------------------------
    def configure_tenant(self, name, *, weight=1.0, rate_tokens_per_s=None,
                         window_s=1.0, clock=time.monotonic):
        """Register (or refresh) tenant ``name``: its weighted-fair
        ``weight`` — the share of admission it gets while backlogged
        against other tenants — and an optional :class:`TenantQuota`
        token-rate quota. The first configured tenant arms the QoS
        admission path; until then admission is plain FIFO."""
        if float(weight) <= 0:
            raise ValueError(f"tenant weight must be > 0, got {weight}")
        st = self._tenant(name)
        st.weight = float(weight)
        st.quota = (TenantQuota(rate_tokens_per_s, window_s, clock=clock)
                    if rate_tokens_per_s else None)
        st.configured = True
        self._qos_configured = True
        return st

    def _tenant(self, name):
        st = self.tenants.get(name)
        if st is None:
            # a tenant joining late starts at the LOWEST live virtual
            # time, not 0 — otherwise it would monopolize admission
            # until it "caught up" with tenants that served all along
            vt = min((s.vtime for s in self.tenants.values()), default=0.0)
            st = self.tenants[name] = _TenantState(name, vtime=vt)
        return st

    def _qos_active(self):
        return self._qos_configured or any(
            r.tier != TIER_LATENCY or r.tenant != "default"
            for r in self.waiting)

    def note_served(self, req, n):
        """Charge ``n`` served tokens (a prefill chunk or a decode
        emission) to the request's tenant: advances its WFQ virtual time
        by ``n / weight``, feeds its rate quota, and the per-tenant
        token counter. Called by the engine on the serving hot path —
        host-side arithmetic only."""
        if n <= 0:
            return
        st = self._tenant(req.tenant)
        st.served_tokens += int(n)
        st.vtime += n / st.weight
        if st.quota is not None:
            st.quota.note(n)
        _M_TENANT_TOKENS.inc(
            n, instance=self.instance,
            tenant=st.name if st.configured else "default")

    def _admissible(self, st):
        return st.quota is None or st.quota.admissible()

    def _next_admission(self):
        """QoS admission choice: the ``waiting`` position to admit next,
        or ``None`` when every waiting request's tenant is quota-
        deferred. The latency tier strictly outranks batch; within a
        tier, the tenant with the lowest virtual time wins and its
        EARLIEST queued request goes — per-tenant order stays FIFO, so
        a tenant's own requests admit in submission order regardless of
        what the other tenants do. Over-quota tenants are skipped
        (deferred, never shed)."""
        throttled = False
        for tier in (TIER_LATENCY, TIER_BATCH):
            best = None
            seen = set()
            for pos, req in enumerate(self.waiting):
                if req.tier != tier or req.tenant in seen:
                    continue
                seen.add(req.tenant)
                st = self._tenant(req.tenant)
                if not self._admissible(st):
                    throttled = True
                    continue
                if best is None or (st.vtime, pos) < best:
                    best = (st.vtime, pos)
            if best is not None:
                return best[1]
        if throttled:
            _M_THROTTLED.inc(instance=self.instance)
        return None

    def _yield_batch_slot(self):
        """Batch-tier yield (ISSUE 17): the slots are full and a
        latency-tier request is waiting admissibly — preempt the most
        recently admitted batch-tier running request through the normal
        eviction path (which spills decode-ready pages to the host
        tier, so revival is a page import, not a re-prefill). Returns
        True when a slot was freed."""
        wants_latency = any(
            r.tier == TIER_LATENCY and self._admissible(self._tenant(
                r.tenant))
            for r in self.waiting)
        if not wants_latency:
            return False
        batch = [r for r in self.running if r.tier == TIER_BATCH]
        if not batch:
            return False
        # prefer decode-ready victims: their pages spill (mid-prefill
        # pages are incomplete and degrade to recompute preemption)
        ready = [r for r in batch if not r.prefilling]
        victim = max(ready or batch, key=lambda r: r.admit_seq)
        self._evict(victim)
        _M_BATCH_YIELD.inc(instance=self.instance)
        return True

    # -- queries ---------------------------------------------------------
    @property
    def running(self):
        return [r for r in self.slots if r is not None]

    def has_work(self):
        return bool(self.waiting) or any(self.slots)

    def _free_slot(self):
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    # -- admission (prefill picks) --------------------------------------
    def pick_prefills(self):
        """Waiting requests to admit THIS step: pops up to
        ``max_prefills_per_step`` requests that fit (a free slot + blocks
        for prompt-and-first-token, charging only blocks the prefix cache
        cannot supply). A chosen request that does not fit stays queued —
        no overtaking within the step — and the engine simply decodes
        with what is running. Default traffic picks the FIFO head; with
        QoS active the weighted-fair ``_next_admission`` chooses, and a
        full slot set may first make room by preempting batch-tier work
        (``_yield_batch_slot``)."""
        picked = []
        while len(picked) < self.max_prefills_per_step and self.waiting:
            qos = self._qos_active()
            if self._free_slot() is None:
                # batch-tier yield (ISSUE 17): under latency pressure a
                # full slot set preempts batch work to the host tier
                # instead of queueing latency requests behind it
                if not (qos and self._yield_batch_slot()):
                    break
            pos = self._next_admission() if qos else 0
            if pos is None:
                break  # every waiting tenant is quota-deferred
            req = self.waiting[pos]
            # a spill-evicted request revives from the host tier: its
            # payload becomes a ``preloaded`` import, exactly the
            # disaggregated-handoff shape. A tier that LRU-dropped the
            # entry under budget pressure degrades to plain re-prefill.
            if req.spill_key is not None and self.kv_tier is not None:
                payload = self.kv_tier.peek_request(req.spill_key)
                if payload is not None:
                    req.preloaded = payload
                    req.revived_from_tier = True
                else:
                    # LRU-dropped under budget pressure, or freed by the
                    # tier's read-back CRC verification (ISSUE 20) — both
                    # degrade identically to plain re-prefill, and the
                    # miss is counted so an elevated rate is visible
                    self.revive_misses += 1
                    req.spill_key = None
            # preloaded (disaggregated-handoff) requests charge full
            # blocks and skip prefix matching: their pages arrive by
            # import, not by sharing — the engine registers the imported
            # full blocks afterwards so LATER admissions can share them
            host_hits = []
            if self.prefix_cache is not None and req.preloaded is None:
                if self.kv_tier is not None:
                    matched, mtok, host_hits = (
                        self.prefix_cache.match_with_tier(
                            req.tokens, self.kv_tier))
                else:
                    matched, mtok = self.prefix_cache.match(req.tokens)
            else:
                matched, mtok = [], 0
            need = -(-(req.num_tokens + 1) // self.block_size) - len(matched)
            if matched:
                # pin the matched blocks FIRST: the fresh allocation below
                # may reclaim reusable (refcount-0) blocks, and the match
                # must not be reclaimed out from under its own admission
                self.allocator.acquire(matched)
            blocks = self.allocator.allocate(need) if need > 0 else []
            if blocks is None:
                if matched:
                    self.allocator.free(matched)
                _M_QUEUED_EXH.inc(instance=self.instance)
                break
            del self.waiting[pos]
            slot = self._free_slot()
            req.blocks = list(matched) + blocks
            if req.preloaded is not None:
                # decode-ready immediately: pages cover every token but
                # the last one (whose KV the first decode step writes);
                # the engine imports the payload into req.blocks before
                # this step's decode runs. The draft pool (speculative
                # decoding) was NOT transferred — its catch-up loop
                # re-derives the prompt positions deterministically.
                req.num_cached = int(req.preloaded["covered"])
                req.draft_cached = 0
                req.prefilling = False
                if req.revived_from_tier:
                    self.kv_tier.drop_request(req.spill_key)
                    req.spill_key = None
            else:
                # host-resident chain links continue the device match:
                # queue their page imports (drained by the engine before
                # prefill work) and start num_cached past them — the
                # blocks that would otherwise be re-prefilled arrive by
                # host->device copy instead. The draft pool (speculative
                # decoding) only mirrors the DEVICE match; the catch-up
                # loop re-derives the revived span deterministically.
                for j, h in enumerate(host_hits):
                    self.pending_revive.append((req, blocks[j], h))
                req.num_cached = mtok + len(host_hits) * self.block_size
                req.draft_cached = mtok
                req.prefilling = True
            req.prefill_upto = req.num_tokens
            req.state = RUNNING
            req.admit_seq = next(self._admit_seq)
            self.slots[slot] = req
            self.version += 1
            _M_ADMITTED.inc(instance=self.instance)
            if matched:
                _M_PREFIX_REUSED.inc(len(matched), instance=self.instance)
            picked.append((slot, req))
        return picked

    # -- chunked prefill work -------------------------------------------
    def prefill_work(self, budget=None):
        """Chunk assignments ``[(req, start, n_new_tokens)]`` for this
        engine step: oldest-admitted prefilling requests first, total NEW
        tokens bounded by ``budget`` (``None`` = unlimited — whole prompts
        in one chunk, the PR-7 behavior). Non-final chunks are
        block-aligned (chunk starts must sit on page boundaries for
        whole-page pool writes); the head assignment always gets at least
        one block so prefill can never stall under a tiny budget."""
        out = []
        remaining = float("inf") if budget is None else int(budget)
        for req in sorted((r for r in self.slots
                           if r is not None and r.prefilling),
                          key=lambda r: r.admit_seq):
            todo = req.prefill_upto - req.num_cached
            if todo <= 0:
                continue
            if remaining <= 0:
                break
            allowed = remaining
            if allowed < todo:
                allowed = int(allowed) // self.block_size * self.block_size
                if allowed == 0:
                    if out:
                        break
                    allowed = self.block_size  # guaranteed progress
            take = int(min(todo, allowed))
            out.append((req, req.num_cached, take))
            remaining -= take
        return out

    # -- decode-time growth / eviction / COW ----------------------------
    def _grow_one(self, req, evicted):
        """One block for ``req``, evicting peers (then self) on
        exhaustion. Returns the block id or None if ``req`` itself was
        evicted."""
        while True:
            got = self.allocator.allocate(1)
            if got is not None:
                return got[0]
            peers = [r for r in self.running if r is not req]
            # batch-tier peers yield first (ISSUE 17): growing latency
            # work never preempts a latency peer while batch work still
            # occupies slots
            batch = [r for r in peers if r.tier == TIER_BATCH]
            victim = max(batch or peers, key=lambda r: r.admit_seq,
                         default=None)
            if victim is None:
                victim = req  # alone and out of memory: preempt self
            if victim.tier == TIER_BATCH and req.tier == TIER_LATENCY:
                _M_BATCH_YIELD.inc(instance=self.instance)
            self._evict(victim)
            evicted.append(victim)
            if victim is req:
                return None

    def ensure_decode_room(self, extra=0, extra_for=None):
        """Grow every running request that is about to write past its last
        block; ``extra`` reserves additional lookahead positions (the
        speculative verify window writes ``k+1`` tokens at once).
        ``extra_for`` — a ``Request -> int`` callable — overrides ``extra``
        per request: fused decode windows (ISSUE 18) reserve
        ``min(k, tokens_remaining) - 1`` positions so a request one token
        from its budget cap never grows a block it will not write. On
        exhaustion, evict the most-recently-admitted running request (free
        its blocks, re-queue at the FRONT) and retry — token-granularity
        eviction. Divergent-write targets that are shared get a private
        copy queued on ``pending_cow`` (COW). Returns the evicted
        requests."""
        evicted = []
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            # mid-prefill requests already own blocks for prompt+1 tokens
            # (charged at admission) and take no speculative lookahead
            lookahead = 0 if req.prefilling else int(
                extra_for(req) if extra_for is not None else extra)
            # the decode step writes ONE token at position len(tokens)-1
            # (plus ``lookahead`` speculative positions), so capacity
            # len(tokens)+lookahead is exactly enough — demanding more
            # would evict needlessly when the pool is full at a boundary
            while (req.state == RUNNING and req.num_tokens + lookahead
                    > len(req.blocks) * self.block_size):
                got = self._grow_one(req, evicted)
                if got is None:
                    break
                req.blocks.append(got)
                self.version += 1
            if req.state != RUNNING or req.prefilling:
                continue
            # COW guard over the write window [num_cached, num_cached+
            # lookahead]: a shared block must never be mutated in place
            first = req.num_cached // self.block_size
            last = min((req.num_cached + lookahead) // self.block_size,
                       len(req.blocks) - 1)
            for bi in range(first, last + 1):
                b = req.blocks[bi]
                if self.allocator.is_shared(b):
                    got = self._grow_one(req, evicted)
                    if got is None:
                        break
                    self.pending_cow.append((b, got))
                    self.allocator.free([b])
                    req.blocks[bi] = got
                    self.version += 1
                    _M_COW.inc(instance=self.instance)
                elif (self.prefix_cache is not None
                        and self.prefix_cache.registered(b)):
                    # sole holder, but the content is published: the write
                    # diverges it from its hash — retract the identity
                    self.prefix_cache.forget(b)
        return evicted

    def trim_to_capacity(self, req, extra=0):
        """Free tail blocks beyond what ``req.num_tokens + extra`` needs
        (the speculative-rollback path: a rejected window leaves
        over-allocated lookahead blocks behind). ``extra`` keeps the NEXT
        verify window's lookahead room — trimming to the bare token count
        would free a block that ``ensure_decode_room`` re-allocates one
        step later, ping-ponging the allocator and invalidating the
        engine's device table cache every step near a block boundary.
        Tail blocks are private by construction; ``free`` decrefs anyway,
        so a forged shared tail is still safe."""
        keep = max(-(-(req.num_tokens + int(extra)) // self.block_size), 1)
        if len(req.blocks) > keep:
            extras = req.blocks[keep:]
            del req.blocks[keep:]
            self.allocator.free(extras)
            self.version += 1

    def _evict(self, req):
        slot = self.slots.index(req)
        # KV tiering (ISSUE 16): spill a decode-ready victim's pages to
        # the host tier BEFORE the blocks free — the snapshot's gathers
        # dispatch against the still-bound pool arrays, so freeing (and
        # even re-writing) the blocks afterwards cannot corrupt the
        # spilled copy. Mid-prefill victims are not spilled (their pages
        # are incomplete); a failed/over-budget spill degrades to the
        # plain recompute preemption below.
        if (self.kv_tier is not None and not req.prefilling
                and req.num_cached > 0
                and req.num_cached == req.num_tokens - 1):
            if self.kv_tier.spill_request(req.rid, req.blocks,
                                          req.num_cached,
                                          tenant=req.tenant):
                req.spill_key = req.rid
        self.allocator.free(req.blocks)
        req.blocks = []
        req.num_cached = 0
        req.draft_cached = 0
        req.prefilling = False
        req.state = WAITING
        req.evictions += 1
        req.t_queue_start = time.perf_counter_ns()  # re-queued span start
        self.slots[slot] = None
        self.waiting.appendleft(req)
        self.version += 1
        _M_EVICTIONS.inc(instance=self.instance)

    # -- early termination (deadline expiry / cancel / engine close) -----
    def abort(self, req, reason="cancelled"):
        """Finish ``req`` early, releasing everything it holds: a RUNNING
        request frees its blocks (decref under sharing) and recycles its
        slot for the very next admission; a WAITING request just leaves
        the queue. Idempotent on already-finished requests. The typed
        reason lands in ``finish_reason()`` — deliberately NOT counted as
        ``serving_requests_finished_total`` (an aborted request did not
        finish; the fleet's completed+typed-error accounting depends on
        the distinction)."""
        if req.state == FINISHED:
            return
        # early termination must also unwind queued device-page work that
        # references the dying request (ISSUE 17 satellite): a pending
        # host-tier revive would index into the emptied block list, and a
        # pending COW copy would write into a freed (possibly re-
        # allocated) destination block.
        if self.pending_revive:
            mine = [t for t in self.pending_revive if t[0] is req]
            if mine:
                self.pending_revive = [t for t in self.pending_revive
                                       if t[0] is not req]
                for _, _, h in mine:
                    # the chain's host pages were pinned for this
                    # admission; drop them the way the engine's dead-
                    # request drain path does — a payload nobody will
                    # import must not sit in the host tier forever
                    if self.kv_tier is not None:
                        self.kv_tier.pop_prefix(h)
        if req.state == RUNNING:
            slot = self.slots.index(req)
            if self.pending_cow and req.blocks:
                dying = set(req.blocks)
                self.pending_cow = [(s, d) for s, d in self.pending_cow
                                    if d not in dying]
            if req.blocks:
                self.allocator.free(req.blocks)
            req.blocks = []
            self.slots[slot] = None
            self.version += 1
        else:  # WAITING
            try:
                self.waiting.remove(req)
            except ValueError:
                pass
        req.prefilling = False
        req.preloaded = None  # never-imported handoff pages die here
        if req.spill_key is not None and self.kv_tier is not None:
            self.kv_tier.drop_request(req.spill_key)  # host pages too
            req.spill_key = None
        req.abort_reason = reason
        req.state = FINISHED

    # -- completion ------------------------------------------------------
    def finish(self, req):
        slot = self.slots.index(req)
        self.allocator.free(req.blocks)
        req.blocks = []
        req.state = FINISHED
        self.slots[slot] = None
        self.version += 1
        _M_FINISHED.inc(instance=self.instance)
