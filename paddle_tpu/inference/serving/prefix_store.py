"""Crash-safe on-disk prefix store (ISSUE 16 tentpole, part b).

A PR-12 rolling update drains an engine, reloads weights, and until now
restarted the prefix cache stone-cold: every cross-request prompt prefix
had to be re-prefilled from scratch. This module persists the
:class:`~.kv_cache.PrefixCache` hash-chain — chain hash → one block's
page payload — as a CRC-framed ``*.pdstream`` shard (the PR-13 container
format, written with the PR-13 atomic-write discipline: tmp → fsync →
rename, so a killed writer can never publish a torn store) and re-imports
it at engine boot / ``reload_weights``, landing the entries in the
host-RAM tier where the first matching request revives them via
``import_request_pages`` — a warm restart instead of a cold one.

Wrong pages are worse than no pages, so the load path is gated three
ways, each degrading to a CLEAN COLD START (typed
:class:`PrefixStoreMismatch`, counted in
``serving_prefix_store_rejected_total``), never a partial import:

* **CRC / framing** — any torn frame, bad magic, or checksum mismatch
  surfaces as the stream layer's ``StreamCorruptionError``;
* **weight fingerprint** — :func:`weights_fingerprint` digests every
  parameter's name/shape/dtype/bytes; KV pages are a pure function of
  the weights and the tokens, so pages written under different weights
  would decode fluent garbage. The fingerprint in the store header must
  match the serving model exactly;
* **pool geometry** — block size, KV dtype, layer count and head
  geometry must match: a page of the wrong shape cannot land in the
  pool (``validate_request_pages`` would throw block-by-block; the
  header check rejects the whole store up front instead).

The save path sits behind the ``serve.store_write`` fault site, armed by
``chaos_serve.py --drill warmstore`` in the killed-mid-save window.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

from ...observability import metrics as _obs_metrics
from ...utils.retry import atomic_write
from ...io.streaming import (MAGIC, StreamCorruptionError, _FRAME,
                             read_stream_shard)
from .kv_cache import pack_kv_pages, unpack_kv_pages

import zlib

__all__ = ["PrefixStoreMismatch", "weights_fingerprint", "pool_geometry",
           "save_prefix_store", "load_prefix_store", "STORE_VERSION"]

STORE_VERSION = 1

_M_STORE_SAVED = _obs_metrics.counter(
    "serving_prefix_store_saved_total",
    "prefix-chain entries serialized to the on-disk prefix store")
_M_STORE_LOADED = _obs_metrics.counter(
    "serving_prefix_store_loaded_total",
    "prefix-chain entries re-imported from the on-disk prefix store "
    "into the host tier at engine boot / reload_weights")
_M_STORE_REJECTED = _obs_metrics.counter(
    "serving_prefix_store_rejected_total",
    "prefix-store files rejected whole — the engine cold-starts cleanly "
    "instead of importing wrong pages. Labeled by reason (ISSUE 20): "
    "'corrupt' (CRC/framing/truncation), 'version', 'fingerprint' "
    "(different weights), 'geometry' (different pool shape) — a bounded "
    "set, so operators can tell a corrupt store from a stale one")

# the bounded ``reason`` label set of _M_STORE_REJECTED (and of
# PrefixStoreMismatch.reason)
REJECT_REASONS = ("corrupt", "version", "fingerprint", "geometry")


class PrefixStoreMismatch(RuntimeError):
    """The store on disk cannot be trusted for THIS engine: corrupt
    framing, a different weight fingerprint, or a different pool
    geometry. The caller degrades to a cold start — never a partial or
    wrong import. ``reason`` is one of :data:`REJECT_REASONS` (typed,
    bounded — it labels ``serving_prefix_store_rejected_total``)."""

    def __init__(self, msg, reason="corrupt"):
        super().__init__(msg)
        assert reason in REJECT_REASONS, reason
        self.reason = reason


def weights_fingerprint(model):
    """Order-independent digest of every parameter (name, shape, dtype,
    bytes). KV pages are a deterministic function of weights + tokens,
    so two models with the same fingerprint produce byte-identical
    pages for the same chain — the gate that makes re-importing stored
    pages sound."""
    h = hashlib.sha1()
    for name, val in sorted(model.state_dict().items()):
        arr = np.ascontiguousarray(
            np.asarray(val.numpy() if hasattr(val, "numpy") else val))
        h.update(name.encode())
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def pool_geometry(cache, config):
    """The geometry tuple a stored page must match to land in ``cache``
    (mirrors what ``validate_request_pages`` would check page-by-page)."""
    return {
        "block_size": cache.block_size,
        "kv_dtype": cache.kv_dtype,
        "layers": len(cache.k),
        "kv_heads": int(config.num_key_value_heads),
        "head_dim": int(config.head_dim),
    }


def save_prefix_store(path, entries, *, fingerprint, geometry,
                      instance=None):
    """Atomically publish ``entries`` — ``(chain_hash bytes, pages
    dict)`` pairs — as one CRC-framed shard at ``path``. Record 0 is the
    JSON header (version, fingerprint, geometry, entry count); each
    following record is ``chain_hash ‖ pack_kv_pages(pages)``. The
    ``serve.store_write`` fault site sits between the payload hitting
    the tmp file and the atomic rename: a failure (or a SIGKILL) there
    leaves the PREVIOUS store intact and never publishes a torn one.
    Returns the number of entries written."""
    entries = list(entries)
    header = json.dumps({
        "version": STORE_VERSION,
        "fingerprint": fingerprint,
        "geometry": geometry,
        "entries": len(entries),
    }, sort_keys=True).encode()

    def body(f):
        f.write(MAGIC)
        for rec in [header] + [h + pack_kv_pages(p) for h, p in entries]:
            f.write(_FRAME.pack(len(rec), zlib.crc32(rec)))
            f.write(rec)

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    atomic_write(path, body, fire_site="serve.store_write")
    _M_STORE_SAVED.inc(len(entries), instance=instance)
    return len(entries)


def load_prefix_store(path, *, fingerprint, geometry, instance=None):
    """Entries of the store at ``path`` as ``(chain_hash, pages)``
    pairs, or ``None`` when no store exists (a first boot, not an
    error). Raises :class:`PrefixStoreMismatch` — counting the file in
    ``serving_prefix_store_rejected_total`` — on CRC/framing corruption,
    version/fingerprint/geometry mismatch, or an entry count that does
    not match the header (a self-consistency belt on top of per-frame
    CRCs)."""
    if not os.path.exists(path):
        return None
    try:
        try:
            recs = read_stream_shard(path, decode_fn=bytes)
        except StreamCorruptionError as e:
            raise PrefixStoreMismatch(f"corrupt prefix store: {e}") from e
        if not recs:
            raise PrefixStoreMismatch(f"{path}: empty store (no header)")
        try:
            header = json.loads(recs[0])
        except ValueError as e:
            raise PrefixStoreMismatch(
                f"{path}: undecodable store header: {e}") from e
        if header.get("version") != STORE_VERSION:
            raise PrefixStoreMismatch(
                f"{path}: store version {header.get('version')!r}, "
                f"this engine speaks {STORE_VERSION}", reason="version")
        if header.get("fingerprint") != fingerprint:
            raise PrefixStoreMismatch(
                f"{path}: weight fingerprint mismatch (store "
                f"{str(header.get('fingerprint'))[:12]}…, model "
                f"{fingerprint[:12]}…) — pages from other weights "
                "would decode garbage", reason="fingerprint")
        if header.get("geometry") != geometry:
            raise PrefixStoreMismatch(
                f"{path}: pool geometry mismatch (store "
                f"{header.get('geometry')}, engine {geometry})",
                reason="geometry")
        if header.get("entries") != len(recs) - 1:
            raise PrefixStoreMismatch(
                f"{path}: header promises {header.get('entries')} "
                f"entries, shard holds {len(recs) - 1}")
        out = []
        for rec in recs[1:]:
            if len(rec) <= 20:
                raise PrefixStoreMismatch(
                    f"{path}: truncated store entry")
            try:
                out.append((rec[:20], unpack_kv_pages(rec[20:])))
            except ValueError as e:
                raise PrefixStoreMismatch(
                    f"{path}: undecodable page payload: {e}") from e
    except PrefixStoreMismatch as e:
        _M_STORE_REJECTED.inc(instance=instance, reason=e.reason)
        raise
    _M_STORE_LOADED.inc(len(out), instance=instance)
    return out
