"""Serving integrity sentinel (ISSUE 20): end-to-end silent-data-
corruption defense for the fleet.

Every fault the fleet survives elsewhere is LOUD — crashes, hangs, torn
writes, corrupt CRC frames. Silent data corruption (a flipped bit in a
host spill buffer, a transfer payload, or a live weight shard) produces
wrong tokens with no error anywhere. The defense has four layers, all
built on the stack's hard-won invariant that greedy decode is bit-exact
across replicas, redispatch, handoff, spill/revive, quantization and tp
groups — so any two honest replicas must agree token-for-token, and
disagreement IS corruption:

* **page checksums** (this module + kv_cache) — per-block CRC32 sealed
  into every page payload the moment it is materialized to host memory
  (:meth:`~.kv_cache.PageSnapshot.materialize`, which is the single
  choke point behind ``export_request_pages``, ``HostKVTier`` spills and
  the prefix-store save pass), and verified at every read-back boundary
  (host-tier revive / prefix pop, ``add_request_with_pages`` import,
  prefix-store boot entries on their first revive). Chunk-prefill and
  COW content is covered transitively: those writes live in the device
  pool, and the seal is computed from the pool's bytes the instant they
  cross the host boundary — a flip INSIDE the device pool is the output
  audit's job, a flip at rest in host RAM or a transfer buffer is
  caught here, before a single wrong token decodes. Off by default;
  ``LLMEngine(kv_page_checksums=True)`` arms sealing. Degrade rule:
  verification failure frees the entry and falls back to re-prefill —
  a corrupt page is NEVER served (:class:`~.errors.KVIntegrityError`).
  The CRC chains the int8 scale sidecars after the code planes: codes
  with a flipped scale row are exactly as wrong as flipped codes.

* **sampled output audit** (router) — ``Router(audit_fraction=p)``
  replays a deterministic hash-sample of completed requests on a
  DIFFERENT replica as batch-tier background work and compares the
  token streams bit-for-bit; a mismatch triggers a third-replica
  referee replay to majority-vote which replica is corrupt.

* **replica quarantine** (router + supervisor) — a per-replica
  :class:`SuspicionScore` leaky bucket; crossing the threshold drives
  remove-from-placement → group-atomic restart through ONE
  ``RestartBudget`` slot, with the quarantined replica's in-flight
  requests redispatched bit-exact.

* **weight integrity re-audit** (engine + replica) — periodic
  re-verification of the live :func:`~.prefix_store.weights_fingerprint`
  against the value captured at load; a mismatch means the weights
  changed IN PLACE (SDC, not a reload) → ``reload_weights`` + a
  suspicion charge.

The whole chain is provable end-to-end via the ``serve.bit_flip`` fault
site (:func:`flip_bit` is its payload: it can hit a KV pool page, a
host-tier entry, or a weight buffer) — ``chaos_serve.py --drill sdc``.
"""

from __future__ import annotations

import time
import zlib
from collections import deque

import numpy as np

from ...observability import metrics as _obs_metrics
from .errors import KVIntegrityError

__all__ = ["compute_page_crcs", "seal_pages", "verify_pages",
           "SuspicionScore", "flip_bit", "audit_sampled"]

# CRC planes in a fixed order so fp32 and int8 payloads hash
# deterministically; the scale sidecars chain AFTER the codes — a page
# with corrupt scales fails exactly like one with corrupt codes.
_CRC_PARTS = ("k", "v", "k_scale", "v_scale")

_M_PAGES_VERIFIED = _obs_metrics.counter(
    "serving_kv_pages_verified_total",
    "KV page blocks whose CRC32 seal verified clean at a read-back "
    "boundary (host-tier revive, page import, prefix revive)")
_M_PAGES_REJECTED = _obs_metrics.counter(
    "serving_kv_pages_rejected_total",
    "KV page payloads REJECTED at a read-back boundary (CRC mismatch or "
    "malformed seal) — the entry is freed and the request re-prefills; "
    "a corrupt page is never served")
_M_WEIGHT_AUDIT_FAIL = _obs_metrics.counter(
    "serving_weight_audit_failures_total",
    "weight integrity re-audits that found the live fingerprint "
    "diverged from the loaded artifact's — in-place weight corruption, "
    "answered by reload_weights + a suspicion charge")


def compute_page_crcs(pages):
    """Per-block CRC32 of a page payload (``export_request_pages``
    format): for block ``i``, the CRC chains the contiguous bytes of
    every present plane's block-``i`` slice in :data:`_CRC_PARTS` order.
    Returns ``uint32 [nblocks]``."""
    parts = [np.asarray(pages[nm]) for nm in _CRC_PARTS if nm in pages
             and pages[nm] is not None]
    n = int(parts[0].shape[1])
    out = np.empty(n, np.uint32)
    for i in range(n):
        c = 0
        for a in parts:
            c = zlib.crc32(np.ascontiguousarray(a[:, i]).tobytes(), c)
        out[i] = c
    return out


def seal_pages(pages):
    """Attach the per-block CRC sidecar (``pages["crc"]``, uint32
    ``[nblocks]``) to a freshly-materialized payload. The sidecar is a
    plain ndarray value, so it rides ``pack_kv_pages``/``unpack_kv_pages``
    and the prefix store with zero format changes."""
    pages["crc"] = compute_page_crcs(pages)
    return pages


def verify_pages(pages, *, instance=None, key=None):
    """Verify a payload's CRC seal at a read-back boundary. Unsealed
    payloads (no ``"crc"`` — checksums were off when the page was
    written) pass through untouched, so arming mid-flight never rejects
    pre-existing clean entries. Returns the number of blocks verified
    (0 when unsealed); raises :class:`KVIntegrityError` — after bumping
    ``serving_kv_pages_rejected_total`` — on any mismatch. Callers own
    the degrade rule: free the entry, fall back to re-prefill."""
    crc = pages.get("crc")
    if crc is None:
        return 0
    crc = np.asarray(crc, np.uint32).reshape(-1)
    n = int(np.asarray(pages["k"]).shape[1])
    if crc.shape[0] != n:
        _M_PAGES_REJECTED.inc(instance=instance)
        raise KVIntegrityError(
            f"KV page seal is malformed: {crc.shape[0]} CRCs for {n} "
            f"blocks (key={key!r})", key=key)
    got = compute_page_crcs(pages)
    bad = np.nonzero(got != crc)[0]
    if bad.size:
        _M_PAGES_REJECTED.inc(instance=instance)
        raise KVIntegrityError(
            f"KV page CRC mismatch on block {int(bad[0])} of {n} "
            f"(key={key!r}): page bytes changed at rest — refusing to "
            "serve a corrupt page", key=key, block=int(bad[0]))
    _M_PAGES_VERIFIED.inc(n, instance=instance)
    return n


def audit_sampled(gid, fraction):
    """Deterministic audit sampling: whether completed request ``gid``
    is in the audited fraction. Hash-based (not random) so a replayed /
    redispatched request makes the same decision everywhere, and so the
    drill can force ``fraction=1.0`` without touching RNG state."""
    f = float(fraction)
    if f <= 0.0:
        return False
    if f >= 1.0:
        return True
    return zlib.crc32(f"audit:{gid}".encode()) % 10000 < int(f * 10000)


class SuspicionScore:
    """Per-replica leaky-bucket suspicion (the ``RestartBudget`` idiom
    pointed at corruption instead of crashes): each confirmed-corrupt
    audit verdict or failed weight audit ``charge()``s the bucket;
    charges older than ``window_s`` leak out. Crossing ``threshold``
    live charges returns True ONCE (the bucket resets — the quarantine
    restart wipes the replica's state, so stale suspicion must not
    instantly re-quarantine the clean respawn)."""

    def __init__(self, threshold=2, window_s=300.0, clock=time.monotonic):
        if int(threshold) < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self._clock = clock
        self._events = deque()

    def _leak(self, now):
        while self._events and now - self._events[0] > self.window_s:
            self._events.popleft()

    def charge(self, n=1, now=None):
        """Add ``n`` suspicion charges; True when the threshold is
        crossed (bucket drained — caller quarantines exactly once)."""
        now = self._clock() if now is None else now
        self._leak(now)
        self._events.extend([now] * int(n))
        if len(self._events) >= self.threshold:
            self._events.clear()
            return True
        return False

    def score(self, now=None):
        now = self._clock() if now is None else now
        self._leak(now)
        return len(self._events)


# -- chaos payload (the serve.bit_flip fault site) ----------------------

def flip_bit(eng, target="weights", block=1):
    """Inject silent data corruption into a live engine — the payload
    behind the ``serve.bit_flip`` fault site. Returns a description dict
    (or None when the target had nothing to corrupt, e.g. an empty host
    tier), so drills can assert the flip actually landed.

    * ``"weights"`` — sign-flip the largest-magnitude element of every
      floating-point parameter (via ``Tensor.set_value``, so the next
      compiled step reads the corrupt bytes). One flip per tensor is a
      worst-case SDC burst: it guarantees the weight fingerprint AND
      greedy decode both diverge, which is what a deterministic drill
      needs.
    * ``"host_entry"`` — flip one payload byte inside an oldest-first
      resident host-tier entry (after its seal was computed, so the CRC
      catches it at revive).
    * ``"kv_page"`` — corrupt pool block ``block`` of layer 0's K plane
      in place (device-pool flip: invisible to page CRCs by design; the
      output audit owns this class of flip).
    """
    if target == "weights":
        flips = 0
        for name, val in sorted(eng.model.state_dict().items()):
            arr = np.array(np.asarray(
                val.numpy() if hasattr(val, "numpy") else val))
            if arr.size == 0 or not np.issubdtype(arr.dtype, np.floating):
                continue
            i = int(np.argmax(np.abs(arr)))
            arr.flat[i] = -arr.flat[i] if arr.flat[i] != 0 else 1.0
            val.set_value(arr)
            flips += 1
        return {"target": "weights", "flips": flips} if flips else None
    if target == "host_entry":
        tier = getattr(eng, "kv_tier", None)
        if tier is None:
            return None
        with tier._lock:
            entries = list(tier._entries.items())
        for key, entry in entries:  # oldest first
            # reach the stored bytes directly — going through the
            # tier's _get would run the very verification this flip
            # exists to defeat. materialize() is idempotent and caches,
            # so the mutated dict IS the resident entry (and the flip
            # lands AFTER the seal was computed).
            pages = entry if isinstance(entry, dict) else entry.materialize()
            k = pages.get("k")
            if k is None or getattr(k, "size", 0) == 0:
                continue
            buf = np.asarray(k).view(np.uint8)
            buf.flat[buf.size // 2] ^= 0x80
            return {"target": "host_entry", "key": key}
        return None
    if target == "kv_page":
        cache = eng.cache
        b = int(block)
        kp = cache.k[0]
        # -x - 1 differs from x for every int8 code (bitwise NOT) and
        # every float but -0.5 — a deterministic "flipped" value for
        # either pool dtype
        cache.k[0] = kp.at[b].set(-kp[b] - 1)
        return {"target": "kv_page", "block": b}
    raise ValueError(f"unknown bit-flip target {target!r} "
                     "(weights | host_entry | kv_page)")
