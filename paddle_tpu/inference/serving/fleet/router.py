"""Fleet front-end router (ISSUE 12 tentpole, parts b + c).

``Router`` dispatches requests over the replicas a
:class:`~.supervisor.ReplicaSupervisor` keeps alive:

* **Least-loaded placement** scored from router-tracked in-flight
  counts plus each replica's self-reported ``metrics()`` gauges
  (kv-block utilization + decode occupancy, the PR-10 load signal),
  with **session affinity**: requests carrying the same ``session`` key
  prefer the replica that served the session last, so a prefix-cached
  replica keeps its warm blocks hot.
* **Deadlines**: every request may carry ``deadline_s``; expiry is
  checked at admission (an already-expired request is rejected with a
  typed :class:`~..errors.RequestTimeoutError` before anything is
  queued) and at every router tick for queued AND placed requests
  (placed expiries also cancel on the replica, freeing its blocks).
* **Load shedding**: the admission queue is bounded (``max_queue``);
  a full queue sheds with a typed
  :class:`~..errors.FleetOverloadedError` instead of growing without
  bound — under overload, a fast typed no beats a slow timeout.
* **Redispatch**: when a replica dies (crash or hang — the supervisor
  reports it), its in-flight requests are replayed on a healthy
  replica from their recorded prompt + already-emitted tokens (greedy
  decode is deterministic, so the resumed stream is bit-identical —
  the chaos drill asserts it against an undisturbed baseline). Token
  events carry the dispatch *generation* and source replica; emissions
  from a superseded assignment are dropped, so a slow-but-alive
  replica can never double-emit into a redispatched stream.
* **Graceful drain** (part c): ``drain(i)`` stops admission to a
  replica, lets its in-flight requests finish, then runs the
  ``then=`` action — ``"resume"``, ``"reload"`` (hot weight swap via
  the worker's ``reload_weights``) or ``"retire"`` — giving zero-drop
  rolling weight updates across the fleet.

The router is single-threaded by design: all state mutates inside
:meth:`step` (the pump), mirroring ``LLMEngine.step``. ``submit`` +
``join``/``step`` + ``result`` is the whole client API.
"""

from __future__ import annotations

import itertools
import time
from collections import deque

import numpy as np

from ....observability import metrics as _obs_metrics
from ....utils import fault_injection as _fi
from ..errors import (EngineClosedError, FleetOverloadedError,
                      RequestTimeoutError)
from .supervisor import ReplicaSupervisor

__all__ = ["Router", "FleetRequest"]

_M_REDISPATCH = _obs_metrics.counter(
    "fleet_redispatches_total",
    "in-flight requests replayed on a healthy replica after their "
    "replica died (or a dispatch failed)")
_M_SHED = _obs_metrics.counter(
    "fleet_requests_shed_total",
    "requests rejected with FleetOverloadedError because the bounded "
    "admission queue was full")
_M_TIMEOUTS = _obs_metrics.counter(
    "fleet_deadline_expired_total",
    "requests finished with RequestTimeoutError by the router "
    "(admission-time rejections included)")
_G_QUEUE = _obs_metrics.gauge(
    "fleet_queue_depth", "requests waiting in the router's admission "
    "queue (bounded by max_queue)")
_G_DRAINING = _obs_metrics.gauge(
    "fleet_replicas_draining",
    "replicas currently draining (no new placements)")

QUEUED, PLACED, DONE, FAILED = "queued", "placed", "done", "failed"


class FleetRequest:
    """Router-side record of one request: the original prompt/sampling
    (the redispatch replay source), emitted tokens so far, the absolute
    deadline, and the current assignment (replica + generation)."""

    __slots__ = ("gid", "prompt", "max_new", "eos", "deadline", "session",
                 "state", "replica", "generation", "emitted", "error",
                 "finish_reason", "t_submit", "t_first", "t_done",
                 "redispatches")

    def __init__(self, gid, prompt, max_new, eos, deadline, session):
        self.gid = gid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.eos = eos
        self.deadline = deadline
        self.session = session
        self.state = QUEUED
        self.replica = None
        self.generation = 0
        self.emitted: list[int] = []
        self.error = None
        self.finish_reason = None
        self.t_submit = time.perf_counter()
        self.t_first = None
        self.t_done = None
        self.redispatches = 0

    @property
    def finished(self):
        return self.state in (DONE, FAILED)

    @property
    def remaining(self):
        return self.max_new - len(self.emitted)


class Router:
    """Fault-tolerant request dispatch over a replica fleet."""

    _ids = itertools.count(1)
    # session-affinity map bound (LRU eviction): affinity is a locality
    # hint, so forgetting a cold session costs one prefix re-prefill —
    # never correctness
    MAX_SESSIONS = 4096

    def __init__(self, supervisor=None, *, artifact=None, n_replicas=None,
                 engine_kwargs=None, ckpt_root=None, max_queue=64,
                 max_inflight_per_replica=None, session_affinity=True,
                 hang_timeout_s=0.0, max_restarts=3, log_dir=None,
                 env_extra=None, wait_ready=True):
        self._name = f"fleet#{next(Router._ids)}"
        engine_kwargs = dict(engine_kwargs or {})
        if supervisor is None:
            if artifact is None or n_replicas is None:
                raise ValueError("pass either a supervisor or "
                                 "artifact= + n_replicas=")
            supervisor = ReplicaSupervisor(
                n_replicas,
                {"artifact": artifact, "engine": engine_kwargs,
                 "ckpt_root": ckpt_root},
                hang_timeout_s=hang_timeout_s, max_restarts=max_restarts,
                log_dir=log_dir, env_extra=env_extra, instance=self._name)
            if wait_ready:
                try:
                    supervisor.wait_ready()
                except BaseException:
                    supervisor.shutdown()  # never leak worker processes
                    raise
        self.supervisor = supervisor
        self._ckpt_root = ckpt_root
        self.max_queue = int(max_queue)
        self.max_inflight_per_replica = int(
            max_inflight_per_replica
            or 2 * int(engine_kwargs.get("max_batch_size", 4) or 4))
        self.session_affinity = bool(session_affinity)
        self._reqs: dict[int, FleetRequest] = {}
        self._queue: deque[FleetRequest] = deque()
        self._inflight: dict[int, set] = {
            h.id: set() for h in supervisor.handles}
        self._load: dict[int, dict] = {}
        self._sessions: dict = {}
        self._draining: dict[int, dict] = {}
        self.drains_completed = 0
        self.reloads: list[tuple] = []  # (replica_id, checkpoint step)
        self._gids = itertools.count(1)
        self._closed = False
        for m in (_M_REDISPATCH, _M_SHED, _M_TIMEOUTS):
            m.inc(0, instance=self._name)
        _G_QUEUE.set(0, instance=self._name)
        _G_DRAINING.set(0, instance=self._name)

    # ------------------------------------------------------------------
    # client API
    # ------------------------------------------------------------------
    def submit(self, prompt, *, max_new=32, eos=None, deadline_s=None,
               session=None):
        """Admit a request; returns its fleet-wide id. Raises
        :class:`RequestTimeoutError` when the deadline is already spent
        and :class:`FleetOverloadedError` when the bounded queue is full
        — in both cases NOTHING was queued or placed."""
        if self._closed:
            raise EngineClosedError(f"{self._name} is closed")
        deadline = (time.time() + float(deadline_s)
                    if deadline_s is not None else None)
        if deadline is not None and time.time() >= deadline:
            _M_TIMEOUTS.inc(instance=self._name)
            raise RequestTimeoutError(
                f"deadline_s={deadline_s} already expired at admission",
                deadline=deadline)
        if len(self._queue) >= self.max_queue:
            _M_SHED.inc(instance=self._name)
            raise FleetOverloadedError(
                f"admission queue full ({self.max_queue} requests "
                "waiting); shedding instead of queuing unboundedly",
                queue_depth=len(self._queue))
        req = FleetRequest(next(self._gids), prompt, max_new, eos,
                           deadline, session)
        self._reqs[req.gid] = req
        self._queue.append(req)
        _G_QUEUE.set(len(self._queue), instance=self._name)
        return req.gid

    def request(self, gid):
        return self._reqs[gid]

    def tokens(self, gid):
        """Tokens emitted so far (partial results survive a stored
        error — a deadline-killed stream keeps what it produced)."""
        return list(self._reqs[gid].emitted)

    def result(self, gid):
        """Full prompt+generated array for a DONE request; re-raises the
        stored typed error for a FAILED one."""
        req = self._reqs[gid]
        if req.error is not None:
            raise req.error
        if req.state != DONE:
            raise RuntimeError(f"request {gid} is {req.state}")
        return np.concatenate(
            [req.prompt, np.asarray(req.emitted, np.int32)])

    def release(self, gid):
        req = self._reqs.get(gid)
        if req is not None and not req.finished:
            raise ValueError(f"request {gid} is {req.state}; only "
                             "finished requests can be released")
        self._reqs.pop(gid, None)

    def pending(self):
        return [r.gid for r in self._reqs.values() if not r.finished]

    def inflight(self, replica_id):
        """Request ids currently assigned to ``replica_id`` (the chaos
        drill picks its SIGKILL victim by load)."""
        return sorted(self._inflight.get(replica_id, ()))

    def join(self, timeout=None, poll_s=0.005):
        """Pump :meth:`step` until every submitted request finished."""
        deadline = (time.time() + float(timeout)
                    if timeout is not None else None)
        while self.pending():
            progressed = self.step()
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(
                    f"fleet join timed out with {len(self.pending())} "
                    "requests unfinished")
            if not progressed:
                time.sleep(poll_s)

    # ------------------------------------------------------------------
    # the pump
    # ------------------------------------------------------------------
    def step(self):
        """One router tick: consume replica events, recover deaths
        (redispatch), enforce deadlines, place queued requests, advance
        drains. Returns the number of events processed + placements made
        (0 = nothing to do right now)."""
        if self._closed:
            raise EngineClosedError(f"{self._name} is closed")
        progressed = 0
        # 1. replica events (tokens, loads, ready/reloaded acks)
        for h in list(self.supervisor.handles):
            for ev in h.events():
                progressed += 1
                self._handle_event(h, ev)
        # 2. supervision: deaths drain their final events first, then
        #    their in-flight requests are replayed elsewhere
        for death in self.supervisor.check():
            progressed += 1
            for ev in death["events"]:
                self._handle_event_from(death["replica"], ev)
            self._recover_replica(death["replica"])
        # 3. deadlines (queued + placed)
        self._expire_deadlines()
        # 4. placement
        progressed += self._place()
        # 5. drains
        self._advance_drains()
        _G_QUEUE.set(len(self._queue), instance=self._name)
        _G_DRAINING.set(len(self._draining), instance=self._name)
        return progressed

    # -- events ----------------------------------------------------------
    def _handle_event(self, handle, ev):
        self._handle_event_from(handle.id, ev)

    def _handle_event_from(self, replica_id, ev):
        kind = ev.get("e")
        if kind == "tok":
            req = self._reqs.get(ev.get("gid"))
            if req is None or req.finished:
                return
            # dedup contract: accept only the CURRENT assignment — same
            # replica AND same dispatch generation. A slow-but-alive
            # replica still emitting a superseded copy is ignored.
            if (req.state != PLACED or req.replica != replica_id
                    or ev.get("gen") != req.generation):
                return
            for tok in ev.get("toks", ()):
                if req.t_first is None:
                    req.t_first = time.perf_counter()
                req.emitted.append(int(tok))
            if ev.get("fin"):
                reason = ev.get("reason")
                self._inflight[replica_id].discard(req.gid)
                if reason == "timeout":
                    self._fail(req, RequestTimeoutError(
                        f"request {req.gid} hit its deadline mid-stream "
                        f"on replica {replica_id}", rid=req.gid,
                        deadline=req.deadline), reason)
                else:
                    req.state = DONE
                    req.finish_reason = reason
                    req.t_done = time.perf_counter()
        elif kind == "load":
            self._load[replica_id] = ev
        elif kind == "err":
            req = self._reqs.get(ev.get("gid"))
            if req is not None and not req.finished:
                self._inflight[replica_id].discard(req.gid)
                self._fail(req, RuntimeError(
                    f"replica {replica_id} rejected request {req.gid}: "
                    f"{ev.get('kind')}: {ev.get('msg')}"), "error")
        elif kind == "reloaded":
            self.reloads.append((replica_id, ev.get("step")))
            d = self._draining.get(replica_id)
            if d is not None and d.get("state") == "reloading":
                d["reloaded_step"] = ev.get("step")
                self._finish_drain(replica_id)
        # "ready"/"stats"/"bye" need no router action (ready flips the
        # handle flag inside handle.events())

    def _fail(self, req, error, reason):
        req.state = FAILED
        req.error = error
        req.finish_reason = reason
        req.t_done = time.perf_counter()
        if isinstance(error, RequestTimeoutError):
            _M_TIMEOUTS.inc(instance=self._name)

    # -- death recovery --------------------------------------------------
    def _recover_replica(self, replica_id):
        """Requeue (at the FRONT, preserving age order) every in-flight
        request of a dead replica for replay elsewhere. The replay
        prompt is prompt + emitted-so-far; greedy determinism makes the
        resumed stream bit-identical to an undisturbed one."""
        gids = sorted(self._inflight.get(replica_id, ()))
        self._inflight[replica_id] = set()
        self._load.pop(replica_id, None)
        # a dying replica cancels any drain it was serving
        self._draining.pop(replica_id, None)
        for gid in reversed(gids):
            req = self._reqs.get(gid)
            if req is None or req.finished:
                continue
            if req.remaining <= 0:
                # everything was emitted; only the fin event was lost
                req.state = DONE
                req.finish_reason = "length"
                req.t_done = time.perf_counter()
                continue
            req.state = QUEUED
            req.replica = None
            req.redispatches += 1
            self._queue.appendleft(req)
            _M_REDISPATCH.inc(instance=self._name)

    # -- deadlines -------------------------------------------------------
    def _expire_deadlines(self):
        now = time.time()
        for req in list(self._reqs.values()):
            if req.finished or req.deadline is None or now < req.deadline:
                continue
            if req.state == QUEUED:
                try:
                    self._queue.remove(req)
                except ValueError:
                    pass
            elif req.state == PLACED:
                # free the replica's blocks; its own engine-side deadline
                # check races with this cancel — both are idempotent
                h = self._handle(req.replica)
                if h is not None:
                    h.send({"op": "cancel", "gid": req.gid,
                            "reason": "timeout"})
                self._inflight[req.replica].discard(req.gid)
            self._fail(req, RequestTimeoutError(
                f"request {req.gid} deadline expired "
                f"({'queued' if req.state == QUEUED else 'in flight'})",
                rid=req.gid, deadline=req.deadline), "timeout")

    # -- placement -------------------------------------------------------
    def _handle(self, replica_id):
        for h in self.supervisor.handles:
            if h.id == replica_id:
                return h
        return None

    def _placeable(self, h):
        return (h.ready and h.alive and not h.retired
                and h.id not in self._draining
                and len(self._inflight[h.id])
                < self.max_inflight_per_replica)

    def _pick_replica(self, req):
        if self.session_affinity and req.session is not None:
            rid = self._sessions.get(req.session)
            if rid is not None:
                h = self._handle(rid)
                if h is not None and self._placeable(h):
                    return h
        best, best_score = None, None
        for h in self.supervisor.handles:
            if not self._placeable(h):
                continue
            load = self._load.get(h.id, {})
            score = (len(self._inflight[h.id]),
                     float(load.get("kv", 0.0))
                     + float(load.get("occ", 0.0)), h.id)
            if best_score is None or score < best_score:
                best, best_score = h, score
        return best

    def _place(self):
        placed = 0
        while self._queue:
            req = self._queue[0]
            h = self._pick_replica(req)
            if h is None:
                break
            self._queue.popleft()
            req.generation += 1
            req.replica = h.id
            req.state = PLACED
            payload = {
                "op": "submit", "gid": req.gid, "gen": req.generation,
                # replay source: original prompt + everything already
                # emitted — the greedy continuation is bit-identical
                "prompt": np.concatenate(
                    [req.prompt,
                     np.asarray(req.emitted, np.int32)]).tolist(),
                "max_new": req.remaining, "eos": req.eos,
                "deadline": req.deadline,
            }
            ok = True
            try:
                _fi.fire("serve.dispatch")
            except Exception:
                ok = False
            if ok:
                ok = h.send(payload)
            if not ok:
                # dispatch failed (dead pipe or injected fault): replay
                # elsewhere; the bumped generation invalidates this copy
                # even if it half-arrived
                req.state = QUEUED
                req.replica = None
                req.redispatches += 1
                self._queue.appendleft(req)
                _M_REDISPATCH.inc(instance=self._name)
                # one retry per tick; if the pipe is really dead the
                # supervisor's next check() reports the death and the
                # replica leaves the placeable set
                break
            self._inflight[h.id].add(req.gid)
            if self.session_affinity and req.session is not None:
                # LRU-bounded: one entry per session key forever would
                # grow without bound on a long-lived server (the replica
                # worker bounds its gid bookkeeping the same way)
                self._sessions.pop(req.session, None)
                self._sessions[req.session] = h.id
                while len(self._sessions) > self.MAX_SESSIONS:
                    self._sessions.pop(next(iter(self._sessions)))
            placed += 1
        return placed

    # ------------------------------------------------------------------
    # graceful drain (part c)
    # ------------------------------------------------------------------
    def drain(self, replica_id, then="resume", ckpt_root=None, wait=False,
              timeout=120.0):
        """Stop placing requests on ``replica_id``; once its in-flight
        requests finish, run ``then``:

        * ``"resume"`` — just rejoin the placeable set;
        * ``"reload"`` — hot-swap weights from ``ckpt_root`` (default:
          the fleet's checkpoint root) via the worker's
          ``reload_weights``, then rejoin: the zero-drop rolling-update
          primitive;
        * ``"retire"`` — shut the replica down permanently.

        ``wait=True`` pumps :meth:`step` until the drain completes."""
        if then not in ("resume", "reload", "retire"):
            raise ValueError(f"unknown drain action {then!r}")
        if self._handle(replica_id) is None:
            raise ValueError(f"unknown replica {replica_id}")
        if then == "reload" and not (ckpt_root or self._ckpt_root):
            raise ValueError("drain(then='reload') needs ckpt_root= "
                             "(none configured on the fleet)")
        self._draining[replica_id] = {
            "state": "draining", "then": then,
            "root": ckpt_root or self._ckpt_root}
        _G_DRAINING.set(len(self._draining), instance=self._name)
        if wait:
            deadline = time.time() + float(timeout)
            while replica_id in self._draining:
                if not self.step():
                    time.sleep(0.005)
                if time.time() > deadline:
                    raise TimeoutError(
                        f"drain of replica {replica_id} timed out")

    def _advance_drains(self):
        for rid, d in list(self._draining.items()):
            if d["state"] != "draining" or self._inflight.get(rid):
                continue
            if d["then"] == "retire":
                self.supervisor.retire(rid)
                self._finish_drain(rid)
            elif d["then"] == "reload":
                h = self._handle(rid)
                if h is None or not h.send({"op": "reload",
                                            "root": d["root"]}):
                    self._draining.pop(rid, None)  # died; recovery owns it
                else:
                    d["state"] = "reloading"
            else:  # resume
                self._finish_drain(rid)

    def _finish_drain(self, replica_id):
        self._draining.pop(replica_id, None)
        self.drains_completed += 1
        _G_DRAINING.set(len(self._draining), instance=self._name)

    # ------------------------------------------------------------------
    # introspection + teardown
    # ------------------------------------------------------------------
    def metrics(self):
        """Fleet-owned observability snapshot (the ``LLMEngine.metrics``
        discipline): registry-backed counters/gauges for THIS fleet."""
        inst = self._name
        from .supervisor import _G_LIVE, _M_RESTARTS

        # supervisor-owned series live under the SUPERVISOR's instance
        # label — identical to ours when we built it, but an injected
        # supervisor keeps its own name
        sup_inst = getattr(self.supervisor, "instance", inst)
        return {
            "instance": inst,
            "replicas_live": _G_LIVE.value(instance=sup_inst),
            "replica_restarts": int(_M_RESTARTS.value(instance=sup_inst)),
            "redispatches": int(_M_REDISPATCH.value(instance=inst)),
            "requests_shed": int(_M_SHED.value(instance=inst)),
            "deadline_expired": int(_M_TIMEOUTS.value(instance=inst)),
            "queue_depth": _G_QUEUE.value(instance=inst),
            "replicas_draining": _G_DRAINING.value(instance=inst),
            "drains_completed": self.drains_completed,
        }

    def ttft_seconds(self):
        """Per-request submit→first-token latencies (finished requests
        that produced at least one token) — the drill's p99 source."""
        return [r.t_first - r.t_submit for r in self._reqs.values()
                if r.t_first is not None]

    def replica_stats(self, replica_id, timeout=10.0):
        """Synchronous ``stats`` RPC to one replica (allocator cleanliness
        assertions in drills/tests). Every non-stats event drained while
        waiting is routed through the normal pump — ``events()`` is
        destructive, so returning mid-batch would drop live tokens."""
        h = self._handle(replica_id)
        if h is None or not h.send({"op": "stats"}):
            return None
        deadline = time.time() + timeout
        while time.time() < deadline:
            stats = None
            for ev in h.events():
                if ev.get("e") == "stats" and stats is None:
                    stats = ev
                else:
                    self._handle_event(h, ev)
            if stats is not None:
                return stats
            time.sleep(0.005)
        return None

    def close(self):
        if self._closed:
            return
        self._closed = True
        self.supervisor.shutdown()
        for m in (_M_REDISPATCH, _M_SHED, _M_TIMEOUTS, _G_QUEUE,
                  _G_DRAINING):
            m.remove(instance=self._name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
