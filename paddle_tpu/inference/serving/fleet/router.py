"""Fleet front-end router (ISSUE 12 tentpole, parts b + c).

``Router`` dispatches requests over the replicas a
:class:`~.supervisor.ReplicaSupervisor` keeps alive:

* **Least-loaded placement** scored from router-tracked in-flight
  counts plus each replica's self-reported ``metrics()`` gauges
  (kv-block utilization + decode occupancy, the PR-10 load signal),
  with **session affinity**: requests carrying the same ``session`` key
  prefer the replica that served the session last, so a prefix-cached
  replica keeps its warm blocks hot.
* **Deadlines**: every request may carry ``deadline_s``; expiry is
  checked at admission (an already-expired request is rejected with a
  typed :class:`~..errors.RequestTimeoutError` before anything is
  queued) and at every router tick for queued AND placed requests
  (placed expiries also cancel on the replica, freeing its blocks).
* **Load shedding**: the admission queue is bounded (``max_queue``);
  a full queue sheds with a typed
  :class:`~..errors.FleetOverloadedError` instead of growing without
  bound — under overload, a fast typed no beats a slow timeout.
* **Redispatch**: when a replica dies (crash or hang — the supervisor
  reports it), its in-flight requests are replayed on a healthy
  replica from their recorded prompt + already-emitted tokens (greedy
  decode is deterministic, so the resumed stream is bit-identical —
  the chaos drill asserts it against an undisturbed baseline). Token
  events carry the dispatch *generation* and source replica; emissions
  from a superseded assignment are dropped, so a slow-but-alive
  replica can never double-emit into a redispatched stream.
* **Graceful drain** (part c): ``drain(i)`` stops admission to a
  replica, lets its in-flight requests finish, then runs the
  ``then=`` action — ``"resume"``, ``"reload"`` (hot weight swap via
  the worker's ``reload_weights``) or ``"retire"`` — giving zero-drop
  rolling weight updates across the fleet.

ISSUE 15 adds **disaggregated prefill/decode** (``roles=`` on the
fleet): requests on a split fleet place in two stages — a prefill
worker computes the prompt's KV pages and streams them back as
CRC-framed ``kvpage`` events plus a ``kvdone`` carrying the first
sampled token, then the router ships the verified pages to a decode
worker (session affinity pins there — that is where the prefix cache
lives) which imports them and decodes with zero prefill work. The
robustness contract: every handoff is fenced by a handoff id (a zombie
prefill worker cannot double-deliver), corrupt frames void the WHOLE
transfer and re-drive the prefill under a bounded retry budget (typed
:class:`~..errors.KVTransferError` past it — never decoded-on-garbage),
a prefill worker dying mid-transfer discards its partial pages
atomically and fails over to a healthy prefill peer
(``fleet_handoff_failovers_total``), decode-worker death rides the
PR-12 replay (deadline carried unchanged), a stalled transfer channel
pauses new prefills so the bounded admission queue sheds typed, and a
fleet with NO healthy prefill worker degrades to colocated prefill on
the decode side with a one-shot warning.

ISSUE 17 adds the **multi-tenant QoS front door**: requests may carry a
``tenant=`` identity and a ``tier=`` (latency | batch). A tenant
declared via :meth:`Router.configure_tenant` gets a hard leaky-bucket
admission quota at the router (token demand charged at submit; over it,
a typed :class:`~..errors.TenantQuotaExceededError` with a
machine-readable ``retry_after_s``) and its weight/quota/cache shares
are pushed down to every replica engine (re-pushed to respawns and
autoscaled newcomers), where weighted-fair scheduling paces the served
tokens. ``slo_admission=True`` arms deadline-feasibility at placement:
a request whose deadline budget is already smaller than the estimated
queue wait plus prefill cost is rejected with a typed
:class:`~..errors.DeadlineInfeasibleError` (plus ``retry_after_s``)
instead of being admitted to expire mid-decode. ``enable_autoscale``
turns on the supervisor's autoscale tick inside :meth:`step` — scale-up
spawns a replica, scale-down rides :meth:`drain` (``then="retire"``)
so shrinking the fleet drops zero requests. All of it off by default:
untagged traffic on an unconfigured router behaves exactly as before.

ISSUE 20 adds the **serving integrity sentinel**'s fleet layer:
``audit_fraction=p`` replays a deterministic sample of completed
requests on a DIFFERENT replica as batch-tier background work and
compares the token streams bit-for-bit — greedy decode is
deterministic, so two honest replicas cannot disagree, and a mismatch
IS silent data corruption. A mismatch triggers a third-replica referee
replay that majority-votes the corrupt side; confirmed corruption (and
repeated unrefereed disagreement, and failed weight re-audits reported
by the replicas) charges a per-replica leaky-bucket suspicion score
whose overflow QUARANTINES the replica: killed without grace, removed
from placement, restarted under ONE restart-budget slot, with its
in-flight requests redispatched bit-exact on healthy peers. Off by
default (``audit_fraction=0.0``).

The router is single-threaded by design: all state mutates inside
:meth:`step` (the pump), mirroring ``LLMEngine.step``. ``submit`` +
``join``/``step`` + ``result`` is the whole client API.
"""

from __future__ import annotations

import itertools
import os
import signal
import time
import warnings
from collections import deque

import numpy as np

from ....observability import metrics as _obs_metrics
from ....utils import fault_injection as _fi
from ..errors import (DeadlineInfeasibleError, EngineClosedError,
                      FleetOverloadedError, KVTransferError,
                      RequestTimeoutError, TenantQuotaExceededError)
from ..integrity import SuspicionScore, audit_sampled
from ..scheduler import TIER_BATCH, TIER_LATENCY, TenantQuota
from .framing import decode_frame, join_frames
from .supervisor import ReplicaSupervisor

__all__ = ["Router", "FleetRequest"]

_M_REDISPATCH = _obs_metrics.counter(
    "fleet_redispatches_total",
    "in-flight requests replayed on a healthy replica after their "
    "replica died (or a dispatch failed)")
_M_SHED = _obs_metrics.counter(
    "fleet_requests_shed_total",
    "requests rejected with FleetOverloadedError because the bounded "
    "admission queue was full")
_M_TIMEOUTS = _obs_metrics.counter(
    "fleet_deadline_expired_total",
    "requests finished with RequestTimeoutError by the router "
    "(admission-time rejections included)")
_G_QUEUE = _obs_metrics.gauge(
    "fleet_queue_depth", "requests waiting in the router's admission "
    "queue (bounded by max_queue)")
_G_DRAINING = _obs_metrics.gauge(
    "fleet_replicas_draining",
    "replicas currently draining (no new placements)")
# disaggregated prefill/decode handoff (ISSUE 15)
_M_KV_PAGES = _obs_metrics.counter(
    "fleet_kv_pages_transferred_total",
    "CRC-valid KV-page frames received from prefill workers (corrupt "
    "frames are not counted — they void the whole handoff)")
_M_KV_RETRIES = _obs_metrics.counter(
    "fleet_kv_transfer_retries_total",
    "KV handoffs re-driven after a transient transfer failure (corrupt "
    "frame, failed delivery) — bounded by max_kv_retries, past which the "
    "request fails with a typed KVTransferError")
_M_HANDOFFS = _obs_metrics.counter(
    "fleet_prefill_handoffs_total",
    "completed prefill->decode KV-page handoffs (CRC-verified pages plus "
    "the first sampled token accepted by the router)")
_M_FAILOVERS = _obs_metrics.counter(
    "fleet_handoff_failovers_total",
    "handoffs abandoned because a worker died mid-transfer, with the "
    "prefill re-dispatched elsewhere (partial pages discarded "
    "atomically)")
# multi-tenant QoS front door (ISSUE 17)
_M_QUOTA_REJECTED = _obs_metrics.counter(
    "fleet_quota_rejections_total",
    "requests rejected at submit with TenantQuotaExceededError because "
    "their tenant's leaky-bucket admission quota was exhausted")
_M_INFEASIBLE = _obs_metrics.counter(
    "fleet_deadline_infeasible_total",
    "requests rejected at submit by the SLO feasibility check "
    "(estimated queue wait + prefill cost already exceed the deadline "
    "budget)")
# serving integrity sentinel (ISSUE 20)
_M_AUDITS = _obs_metrics.counter(
    "fleet_audits_total",
    "sampled output audits completed: a finished request replayed "
    "bit-for-bit on a DIFFERENT replica as batch-tier background work "
    "(greedy decode is deterministic, so any disagreement IS silent "
    "data corruption)")
_M_AUDIT_MISMATCH = _obs_metrics.counter(
    "fleet_audit_mismatches_total",
    "output audits whose replayed token stream disagreed with the "
    "served one; a third-replica referee replay majority-votes which "
    "side is corrupt")
_M_QUARANTINED = _obs_metrics.counter(
    "fleet_replicas_quarantined_total",
    "replicas force-restarted by the integrity sentinel after their "
    "leaky-bucket suspicion score crossed the quarantine threshold "
    "(drain of trust -> removal from placement -> one restart-budget "
    "slot)")

QUEUED, PREFILLING, PLACED, DONE, FAILED = (
    "queued", "prefilling", "placed", "done", "failed")


class _IdleBackoff:
    """Exponential idle backoff for the router's wait loops (ISSUE 15
    satellite): replaces the hardcoded 5 ms busy-polls that burned a
    core on every large idle fleet. ``idle()`` sleeps the current delay
    and doubles it toward ``ceiling``; any progress ``reset()``\\ s to
    ``floor``, so a busy fleet stays responsive while an idle one backs
    off to sleeping ~ceiling seconds per probe."""

    __slots__ = ("floor", "ceiling", "_delay")

    def __init__(self, floor=0.0005, ceiling=0.05):
        self.floor = float(floor)
        self.ceiling = max(float(ceiling), float(floor))
        self._delay = self.floor

    def reset(self):
        self._delay = self.floor

    def idle(self):
        time.sleep(self._delay)
        self._delay = min(self.ceiling, self._delay * 2)


class FleetRequest:
    """Router-side record of one request: the original prompt/sampling
    (the redispatch replay source), emitted tokens so far, the absolute
    deadline, and the current assignment (replica + generation). On a
    role-split fleet it also carries the handoff state: ``hid`` (the
    handoff generation — stale frames from a zombie prefill worker are
    dropped by id), the frame buffer of an in-flight transfer, and the
    CRC-verified ``pages`` awaiting decode placement."""

    __slots__ = ("gid", "prompt", "max_new", "eos", "deadline", "session",
                 "state", "replica", "generation", "emitted", "error",
                 "finish_reason", "t_submit", "t_first", "t_done",
                 "redispatches", "hid", "kv_retries", "frames", "pages",
                 "tenant", "tier", "audit")

    def __init__(self, gid, prompt, max_new, eos, deadline, session,
                 tenant=None, tier=None):
        self.gid = gid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.eos = eos
        self.deadline = deadline
        self.session = session
        self.tenant = str(tenant) if tenant else "default"
        tier = tier or TIER_LATENCY
        if tier not in (TIER_LATENCY, TIER_BATCH):
            raise ValueError(f"unknown tier {tier!r}; expected "
                             f"{TIER_LATENCY!r} or {TIER_BATCH!r}")
        self.tier = tier
        self.state = QUEUED
        self.replica = None
        self.generation = 0
        self.emitted: list[int] = []
        self.error = None
        self.finish_reason = None
        self.t_submit = time.perf_counter()
        self.t_first = None
        self.t_done = None
        self.redispatches = 0
        self.hid = 0
        self.kv_retries = 0
        # in-flight transfer buffer: seq -> (raw chunk, encoded data,
        # declared crc) — the raw bytes feed the whole-payload CRC at
        # kvdone; the already-encoded+verified form forwards verbatim
        # to the decode worker (no re-encode, no re-CRC)
        self.frames: dict[int, tuple] = {}
        self.pages = None  # {"frames": [(data_b64, crc)], "crc", "count"}
        # integrity-sentinel replay metadata (ISSUE 20); None for
        # normal traffic. An audit request carries the gid it audits,
        # the served token stream it must reproduce, the replicas it
        # may NOT place on, and the verdict stage (audit | referee).
        self.audit = None

    @property
    def finished(self):
        return self.state in (DONE, FAILED)

    @property
    def remaining(self):
        return self.max_new - len(self.emitted)


class Router:
    """Fault-tolerant request dispatch over a replica fleet."""

    _ids = itertools.count(1)
    # session-affinity map bound (LRU eviction): affinity is a locality
    # hint, so forgetting a cold session costs one prefix re-prefill —
    # never correctness
    MAX_SESSIONS = 4096

    def __init__(self, supervisor=None, *, artifact=None, n_replicas=None,
                 engine_kwargs=None, ckpt_root=None, max_queue=64,
                 max_inflight_per_replica=None, session_affinity=True,
                 hang_timeout_s=0.0, max_restarts=3, log_dir=None,
                 env_extra=None, wait_ready=True, roles=None,
                 max_kv_retries=3, max_pending_handoffs=8,
                 idle_backoff=(0.0005, 0.05), slo_admission=False,
                 group_size=1, plan=None, audit_fraction=0.0):
        self._name = f"fleet#{next(Router._ids)}"
        engine_kwargs = dict(engine_kwargs or {})
        if supervisor is None:
            if artifact is None or n_replicas is None:
                raise ValueError("pass either a supervisor or "
                                 "artifact= + n_replicas=")
            # model-parallel replica groups (ISSUE 19): group_size > 1
            # makes every slot a multi-process group serving ONE
            # plan-sharded engine; `plan` is the JSON plan spec
            # ({"axes": {...}, "strategies": [...]}) every group member
            # rebuilds over its rendezvous'd global mesh. The router
            # itself is group-blind — a group is one handle, placed by
            # rank 0's engine-owned load like any other replica.
            config = {"artifact": artifact, "engine": engine_kwargs,
                      "ckpt_root": ckpt_root}
            if plan is not None:
                config["plan"] = plan
            supervisor = ReplicaSupervisor(
                n_replicas, config,
                hang_timeout_s=hang_timeout_s, max_restarts=max_restarts,
                log_dir=log_dir, env_extra=env_extra, instance=self._name,
                roles=roles, group_size=group_size)
            if wait_ready:
                try:
                    supervisor.wait_ready()
                except BaseException:
                    supervisor.shutdown()  # never leak worker processes
                    raise
        self.supervisor = supervisor
        self._ckpt_root = ckpt_root
        self.max_queue = int(max_queue)
        self.max_inflight_per_replica = int(
            max_inflight_per_replica
            or 2 * int(engine_kwargs.get("max_batch_size", 4) or 4))
        self.session_affinity = bool(session_affinity)
        # disaggregated handoff knobs (ISSUE 15): the transfer retry
        # budget (the utils.retry idiom — re-drive on transient failure,
        # typed KVTransferError past the budget) and the backpressure
        # bound on concurrently buffered handoffs (a stalled transfer
        # channel pauses NEW prefill placements; the bounded admission
        # queue then sheds with a typed error — never silent growth)
        self.max_kv_retries = int(max_kv_retries)
        self.max_pending_handoffs = int(max_pending_handoffs)
        # idle-backoff floor/ceiling for join/drain/stats wait loops
        self.idle_backoff = (float(idle_backoff[0]), float(idle_backoff[1]))
        self._degraded_warned = False
        self._reqs: dict[int, FleetRequest] = {}
        self._queue: deque[FleetRequest] = deque()
        self._inflight: dict[int, set] = {
            h.id: set() for h in supervisor.handles}
        self._load: dict[int, dict] = {}
        self._sessions: dict = {}
        self._draining: dict[int, dict] = {}
        self.drains_completed = 0
        self.reloads: list[tuple] = []  # (replica_id, checkpoint step)
        self._gids = itertools.count(1)
        self._closed = False
        # multi-tenant QoS (ISSUE 17): tenant envelopes declared via
        # configure_tenant — router-side hard quota + the config pushed
        # down to every replica incarnation (tracked per (id, inc) so
        # respawns and autoscaled newcomers get it too)
        self._tenants: dict[str, dict] = {}
        self._tenant_quota: dict[str, TenantQuota] = {}
        self._cfg_sent: set[tuple] = set()
        # SLO-aware admission: recent completion times feed the queue
        # drain-rate estimate (retry_after_s hints + feasibility); the
        # TTFT EMA estimates the prefill cost of a new request
        self.slo_admission = bool(slo_admission)
        self._done_times: deque[float] = deque(maxlen=256)
        self._ttft_ema = None
        # fleet autoscaling: armed by enable_autoscale, ticked in step()
        self._autoscale = None
        self.scale_ups = 0
        self.scale_downs = 0
        # serving integrity sentinel (ISSUE 20): a deterministic sample
        # of completed requests is replayed on a DIFFERENT replica as
        # batch-tier background work; mismatches escalate through a
        # third-replica referee into per-replica suspicion scores that
        # drive quarantine (drain from placement + forced restart)
        self.audit_fraction = float(audit_fraction)
        self._suspicion: dict[int, SuspicionScore] = {}
        self.audit_log: list[dict] = []
        for m in (_M_REDISPATCH, _M_SHED, _M_TIMEOUTS, _M_KV_PAGES,
                  _M_KV_RETRIES, _M_HANDOFFS, _M_FAILOVERS,
                  _M_QUOTA_REJECTED, _M_INFEASIBLE, _M_AUDITS,
                  _M_AUDIT_MISMATCH, _M_QUARANTINED):
            m.inc(0, instance=self._name)
        _G_QUEUE.set(0, instance=self._name)
        _G_DRAINING.set(0, instance=self._name)

    # ------------------------------------------------------------------
    # client API
    # ------------------------------------------------------------------
    def submit(self, prompt, *, max_new=32, eos=None, deadline_s=None,
               session=None, tenant=None, tier=None):
        """Admit a request; returns its fleet-wide id. Raises
        :class:`RequestTimeoutError` when the deadline is already spent,
        :class:`TenantQuotaExceededError` when the tenant's admission
        quota is exhausted, :class:`DeadlineInfeasibleError` when the
        SLO feasibility check (``slo_admission=True``) says the deadline
        cannot be met, and :class:`FleetOverloadedError` when the
        bounded queue is full — in every case NOTHING was queued or
        placed, and every load rejection carries a machine-readable
        ``retry_after_s``."""
        if self._closed:
            raise EngineClosedError(f"{self._name} is closed")
        deadline = (time.time() + float(deadline_s)
                    if deadline_s is not None else None)
        if deadline is not None and time.time() >= deadline:
            _M_TIMEOUTS.inc(instance=self._name)
            raise RequestTimeoutError(
                f"deadline_s={deadline_s} already expired at admission",
                deadline=deadline)
        req = FleetRequest(next(self._gids), prompt, max_new, eos,
                           deadline, session, tenant=tenant, tier=tier)
        try:
            # chaos hook: an armed tenant-flood site makes THIS submit
            # behave as if the fleet were drowning — the typed overload
            # path (retry_after_s included) fires without needing a real
            # thousand-request flood in the test
            _fi.fire("serve.tenant_flood")
        except Exception:
            _M_SHED.inc(instance=self._name)
            raise FleetOverloadedError(
                f"injected tenant flood: request from tenant "
                f"{req.tenant!r} shed",
                queue_depth=len(self._queue),
                retry_after_s=self._retry_after(len(self._queue) + 1))
        quota = self._tenant_quota.get(req.tenant)
        if quota is not None and not quota.admissible():
            _M_QUOTA_REJECTED.inc(instance=self._name)
            raise TenantQuotaExceededError(
                f"tenant {req.tenant!r} exhausted its admission quota; "
                "back off instead of hammering the router",
                tenant=req.tenant, retry_after_s=quota.retry_after())
        if (self.slo_admission and deadline_s is not None
                and req.tier == TIER_LATENCY):
            est = self._estimate_service_start()
            if est is not None and float(deadline_s) < est:
                _M_INFEASIBLE.inc(instance=self._name)
                raise DeadlineInfeasibleError(
                    f"deadline_s={deadline_s} cannot be met: estimated "
                    f"queue wait + prefill cost is {est:.3f}s; rejecting "
                    "at placement instead of expiring mid-decode",
                    deadline=deadline,
                    retry_after_s=max(0.05, est - float(deadline_s)))
        if len(self._queue) >= self.max_queue:
            _M_SHED.inc(instance=self._name)
            raise FleetOverloadedError(
                f"admission queue full ({self.max_queue} requests "
                "waiting); shedding instead of queuing unboundedly",
                queue_depth=len(self._queue),
                retry_after_s=self._retry_after(len(self._queue)))
        if quota is not None:
            # charge the bucket only once every rejection gate passed —
            # shed/infeasible requests must not burn quota
            quota.note(len(req.prompt) + req.max_new)
        self._reqs[req.gid] = req
        self._queue.append(req)
        _G_QUEUE.set(len(self._queue), instance=self._name)
        return req.gid

    # -- QoS estimation helpers (ISSUE 17) ------------------------------
    _RATE_WINDOW_S = 5.0

    def _drain_rate(self):
        """Recent completion rate (requests/s) over the rate window —
        the denominator of every retry_after_s hint."""
        now = time.time()
        n = sum(1 for t in self._done_times
                if now - t <= self._RATE_WINDOW_S)
        return n / self._RATE_WINDOW_S

    def _retry_after(self, n_ahead):
        """Seconds until ~``n_ahead`` queued requests should have
        drained at the observed completion rate (1.0s floor default
        when the rate is still unknown)."""
        rate = self._drain_rate()
        if rate <= 0.0:
            return 1.0
        return max(0.05, float(n_ahead) / rate)

    def _estimate_service_start(self):
        """Estimated submit→first-token latency for a request admitted
        NOW: queue wait at the observed drain rate plus the TTFT EMA.
        None (= admit; never guess-reject) before any completion
        history exists."""
        if self._ttft_ema is None:
            return None
        wait = 0.0
        rate = self._drain_rate()
        if rate > 0.0 and self._queue:
            wait = len(self._queue) / rate
        return wait + self._ttft_ema

    def _note_done(self, req):
        """Completion bookkeeping shared by every terminal transition:
        feeds the drain-rate window and the TTFT EMA."""
        if req.audit is not None:
            # background audit replays must not skew the SLO
            # estimators: their batch-tier latency is not what a
            # latency-tier admission decision should be priced on
            return
        self._done_times.append(time.time())
        if req.t_first is not None and req.t_submit is not None:
            dt = req.t_first - req.t_submit
            self._ttft_ema = (dt if self._ttft_ema is None
                              else 0.8 * self._ttft_ema + 0.2 * dt)

    # ------------------------------------------------------------------
    # sampled output audit + replica quarantine (ISSUE 20)
    # ------------------------------------------------------------------
    AUDIT_DEADLINE_S = 120.0

    def _incarnation(self, replica_id):
        h = self._handle(replica_id)
        return h.incarnation if h is not None else None

    def _note_audit(self, req):
        """Terminal-transition hook of the integrity sentinel. A
        normally finished request that the deterministic sampler picks
        spawns a batch-tier replay of the same work on a DIFFERENT
        replica; a finished audit is compared against the served stream
        and escalates (referee replay -> suspicion charge ->
        quarantine) on mismatch. Greedy decode is bit-exact across
        replicas, so two honest replicas CANNOT disagree — a mismatch
        is, by the core invariant, silent data corruption."""
        if req.audit is not None:
            self._audit_finished(req)
            return
        if req.state != DONE or not req.emitted:
            return
        if sum(1 for h in self.supervisor.handles if not h.retired) < 2:
            return  # no second replica to disagree with
        if not audit_sampled(req.gid, self.audit_fraction):
            return
        self._spawn_audit(req.prompt, req.max_new, req.eos, req.tenant, {
            "of": req.gid, "stage": "audit",
            "expect": list(req.emitted),
            "exclude": ([req.replica] if req.replica is not None else []),
            "server": req.replica,
            "server_inc": (self._incarnation(req.replica)
                           if req.replica is not None else None),
            "auditor": None, "auditor_inc": None,
        })

    def _spawn_audit(self, prompt, max_new, eos, tenant, audit):
        """Enqueue one audit replay. Bypasses every admission gate
        (quota, shed, SLO): audits are the sentinel's own background
        work, not tenant traffic — but they DO carry a deadline, so an
        audit the fleet cannot run ends inconclusive instead of
        pinning its request record forever."""
        req = FleetRequest(next(self._gids), prompt, max_new, eos,
                           time.time() + self.AUDIT_DEADLINE_S, None,
                           tenant=tenant, tier=TIER_BATCH)
        req.audit = audit
        self._reqs[req.gid] = req
        self._queue.append(req)
        return req.gid

    def _audit_finished(self, req):
        """Verdict logic for a finished audit/referee replay."""
        audit = req.audit
        self._reqs.pop(req.gid, None)  # audits self-release
        of, stage = audit["of"], audit["stage"]
        auditor, expect = audit.get("auditor"), audit["expect"]
        got = list(req.emitted)
        if req.state != DONE:
            # the replay itself failed (deadline, replica error):
            # inconclusive — never charge anyone for an audit the
            # fleet failed to run
            self.audit_log.append({"of": of, "stage": stage,
                                   "verdict": "inconclusive",
                                   "auditor": auditor})
            return
        server = audit.get("server")
        if stage == "audit":
            _M_AUDITS.inc(instance=self._name)
            if got == expect:
                self.audit_log.append({"of": of, "stage": stage,
                                       "verdict": "match",
                                       "auditor": auditor})
                return
            _M_AUDIT_MISMATCH.inc(instance=self._name)
            self.audit_log.append({"of": of, "stage": stage,
                                   "verdict": "mismatch",
                                   "auditor": auditor, "server": server})
            warnings.warn(
                f"{self._name}: output audit mismatch on request {of}: "
                f"replica {server} served a stream replica {auditor} "
                "could not reproduce — one of them is corrupt",
                RuntimeWarning)
            exclude = [x for x in (server, auditor) if x is not None]
            if self._audit_candidates(exclude):
                # referee replay on a THIRD replica majority-votes the
                # corrupt side
                self._spawn_audit(req.prompt, req.max_new, req.eos,
                                  req.tenant, {
                    "of": of, "stage": "referee",
                    "expect": expect, "exclude": exclude,
                    "server": server,
                    "server_inc": audit.get("server_inc"),
                    "auditor": None, "auditor_inc": None,
                    "auditor0": auditor,
                    "auditor0_inc": audit.get("auditor_inc"),
                    "audit_toks": got,
                })
            else:
                # no third replica: no majority possible — both
                # parties take one suspicion point, and whichever is
                # really corrupt keeps disagreeing until its bucket
                # overflows
                why = f"unrefereed audit mismatch on request {of}"
                self._charge_suspicion(server, 1, why,
                                       inc=audit.get("server_inc"))
                self._charge_suspicion(auditor, 1, why,
                                       inc=audit.get("auditor_inc"))
            return
        # stage == "referee": two of the three streams agree — the
        # odd one out is the corrupt replica (charged straight to the
        # quarantine threshold); three-way disagreement charges both
        # original parties one point each
        auditor0 = audit.get("auditor0")
        thr = SuspicionScore().threshold
        if got == expect:
            self.audit_log.append({"of": of, "stage": stage,
                                   "verdict": "auditor_corrupt",
                                   "corrupt": auditor0})
            self._charge_suspicion(
                auditor0, thr,
                f"referee confirmed replica {auditor0} corrupted the "
                f"audit replay of request {of}",
                inc=audit.get("auditor0_inc"))
        elif got == audit.get("audit_toks"):
            self.audit_log.append({"of": of, "stage": stage,
                                   "verdict": "server_corrupt",
                                   "corrupt": server})
            self._charge_suspicion(
                server, thr,
                f"referee confirmed replica {server} served a corrupt "
                f"stream for request {of}",
                inc=audit.get("server_inc"))
        else:
            self.audit_log.append({"of": of, "stage": stage,
                                   "verdict": "no_majority"})
            why = f"three-way audit disagreement on request {of}"
            self._charge_suspicion(server, 1, why,
                                   inc=audit.get("server_inc"))
            self._charge_suspicion(auditor0, 1, why,
                                   inc=audit.get("auditor0_inc"))

    def _charge_suspicion(self, replica_id, n, why, inc=None):
        """Charge ``n`` points against a replica's leaky-bucket
        suspicion score; crossing the threshold quarantines it. A
        charge whose evidence predates the replica's current
        incarnation is dropped — a restart already replaced the
        corrupt process, so old sins must not re-fell the fresh one."""
        if replica_id is None:
            return
        h = self._handle(replica_id)
        if h is None or h.retired:
            return
        if inc is not None and h.incarnation != inc:
            return
        s = self._suspicion.get(replica_id)
        if s is None:
            s = self._suspicion[replica_id] = SuspicionScore()
        if s.charge(n):
            self._quarantine(replica_id, why)

    def _quarantine(self, replica_id, why):
        """Remove a suspect replica from service NOW: the supervisor
        kills it (no grace — a corrupt replica must stop emitting),
        charges one restart-budget slot and schedules the respawn; its
        final events and in-flight requests ride the exact same
        recovery path as a crash, so every in-flight request is
        redispatched bit-exact on a healthy peer."""
        idx = next((i for i, h in enumerate(self.supervisor.handles)
                    if h.id == replica_id), None)
        if idx is None:
            return
        self._suspicion.pop(replica_id, None)
        death = self.supervisor.quarantine(idx)
        if death is None:
            return  # already retired or already pending respawn
        _M_QUARANTINED.inc(instance=self._name)
        warnings.warn(
            f"{self._name}: quarantining replica {replica_id}: {why}",
            RuntimeWarning)
        self.audit_log.append({"stage": "quarantine",
                               "replica": replica_id, "why": why})
        for ev in death["events"]:
            self._handle_event_from(death["replica"], ev)
        self._recover_replica(death["replica"])

    def _audit_candidates(self, exclude):
        return [h for h in self.supervisor.handles
                if self._role(h) != "prefill" and self._placeable(h)
                and h.id not in exclude]

    def _pick_audit_replica(self, req):
        return self._least_loaded(
            self._audit_candidates(req.audit["exclude"]))

    # -- tenant configuration (ISSUE 17) --------------------------------
    def configure_tenant(self, name, *, weight=1.0, rate_tokens_per_s=None,
                         window_s=1.0, host_blocks=None,
                         prefix_blocks=None):
        """Declare one tenant's QoS envelope fleet-wide: the router
        enforces a HARD admission quota (token demand — prompt +
        max_new — charged at submit against the leaky bucket; over it,
        submits raise :class:`TenantQuotaExceededError`), and the full
        envelope (weight, quota, cache shares) is pushed to every
        replica engine, where weighted-fair scheduling paces SERVED
        tokens. The push is tracked per replica incarnation, so respawns
        and autoscaled newcomers are configured automatically at the
        next :meth:`step`."""
        name = str(name)
        if not name:
            raise ValueError("tenant name must be non-empty")
        cfg = {"weight": float(weight), "window": float(window_s)}
        if rate_tokens_per_s is not None:
            cfg["rate"] = float(rate_tokens_per_s)
            self._tenant_quota[name] = TenantQuota(
                float(rate_tokens_per_s), window_s=float(window_s))
        else:
            self._tenant_quota.pop(name, None)
        if host_blocks is not None:
            cfg["host_blocks"] = int(host_blocks)
        if prefix_blocks is not None:
            cfg["prefix_blocks"] = int(prefix_blocks)
        self._tenants[name] = cfg
        # force a full re-push: config is idempotent on the replica side
        self._cfg_sent.clear()

    def _push_tenant_config(self):
        """Send the declared tenant envelopes to every live replica
        incarnation that has not received them yet (fresh boots,
        respawns after a crash, autoscaled newcomers)."""
        for h in self.supervisor.handles:
            if not (h.ready and h.alive and not h.retired):
                continue
            key = (h.id, h.incarnation)
            if key in self._cfg_sent:
                continue
            ok = True
            for name, cfg in self._tenants.items():
                ok = h.send({"op": "configure_tenant", "tenant": name,
                             **cfg}) and ok
            if ok:
                self._cfg_sent.add(key)

    # -- fleet autoscaling (ISSUE 17) -----------------------------------
    def enable_autoscale(self, min_replicas, max_replicas, **kw):
        """Arm the supervisor's autoscale tick inside :meth:`step`:
        queue pressure grows the fleet one replica at a time, calm
        shrinks it by draining the highest slot (``then="retire"`` — the
        PR-12 zero-drop path). ``kw`` forwards watermarks / cooldown /
        scale-event budget to :meth:`ReplicaSupervisor.autoscale`;
        disable again with :meth:`disable_autoscale`."""
        min_replicas, max_replicas = int(min_replicas), int(max_replicas)
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min ({min_replicas}) <= max ({max_replicas})")
        self._autoscale = {"min": min_replicas, "max": max_replicas,
                           "kw": dict(kw)}

    def disable_autoscale(self):
        self._autoscale = None

    def _mean_occupancy(self):
        """Mean decode-slot occupancy over live replicas' self-reported
        load gauges (replicas that never reported count as 0 — a booting
        replica is idle capacity, and treating it as busy would wedge
        scale-down forever)."""
        occ, n = 0.0, 0
        for h in self.supervisor.handles:
            if h.retired:
                continue
            n += 1
            occ += float(self._load.get(h.id, {}).get("occ", 0.0))
        return occ / n if n else 0.0

    def _autoscale_tick(self):
        cfg = self._autoscale
        decision = self.supervisor.autoscale(
            cfg["min"], cfg["max"], queue_depth=len(self._queue),
            occupancy=self._mean_occupancy(), **cfg["kw"])
        if decision is None:
            return
        action, rid = decision
        if action == "up":
            # the supervisor already spawned it — give it an in-flight
            # set so placement/recovery bookkeeping treats it as any
            # other slot (tenant config follows via _push_tenant_config)
            self._inflight.setdefault(rid, set())
            self.scale_ups += 1
            return
        # scale-down: zero-drop by construction — drain first, retire
        # only once the slot's in-flight set empties
        if rid in self._draining:
            return
        self.scale_downs += 1
        self.drain(rid, then="retire")
        try:
            # chaos hook (serve.scale_down_kill): SIGKILL the draining
            # replica mid-drain — its in-flight requests must redispatch
            # and still drop zero requests
            _fi.fire("serve.scale_down_kill")
        except Exception:
            h = self._handle(rid)
            if h is not None and h.proc.poll() is None:
                os.kill(h.pid, signal.SIGKILL)

    def request(self, gid):
        return self._reqs[gid]

    def tokens(self, gid):
        """Tokens emitted so far (partial results survive a stored
        error — a deadline-killed stream keeps what it produced)."""
        return list(self._reqs[gid].emitted)

    def result(self, gid):
        """Full prompt+generated array for a DONE request; re-raises the
        stored typed error for a FAILED one."""
        req = self._reqs[gid]
        if req.error is not None:
            raise req.error
        if req.state != DONE:
            raise RuntimeError(f"request {gid} is {req.state}")
        return np.concatenate(
            [req.prompt, np.asarray(req.emitted, np.int32)])

    def release(self, gid):
        req = self._reqs.get(gid)
        if req is not None and not req.finished:
            raise ValueError(f"request {gid} is {req.state}; only "
                             "finished requests can be released")
        self._reqs.pop(gid, None)

    def pending(self):
        return [r.gid for r in self._reqs.values() if not r.finished]

    def inflight(self, replica_id):
        """Request ids currently assigned to ``replica_id`` (the chaos
        drill picks its SIGKILL victim by load)."""
        return sorted(self._inflight.get(replica_id, ()))

    def join(self, timeout=None, poll_s=None):
        """Pump :meth:`step` until every submitted request finished.
        Idle ticks back off exponentially (``idle_backoff``
        floor→ceiling) instead of busy-polling — an idle fleet sleeps,
        it does not burn a core. ``poll_s`` (legacy) pins a fixed poll
        interval instead."""
        deadline = (time.time() + float(timeout)
                    if timeout is not None else None)
        backoff = (_IdleBackoff(poll_s, poll_s) if poll_s is not None
                   else _IdleBackoff(*self.idle_backoff))
        while self.pending():
            progressed = self.step()
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(
                    f"fleet join timed out with {len(self.pending())} "
                    "requests unfinished")
            if progressed:
                backoff.reset()
            else:
                backoff.idle()

    # ------------------------------------------------------------------
    # the pump
    # ------------------------------------------------------------------
    def step(self):
        """One router tick: consume replica events, recover deaths
        (redispatch), enforce deadlines, place queued requests, advance
        drains. Returns the number of events processed + placements made
        (0 = nothing to do right now)."""
        if self._closed:
            raise EngineClosedError(f"{self._name} is closed")
        progressed = 0
        # 1. replica events (tokens, loads, ready/reloaded acks)
        for h in list(self.supervisor.handles):
            for ev in h.events():
                progressed += 1
                self._handle_event(h, ev)
        # 2. supervision: deaths drain their final events first, then
        #    their in-flight requests are replayed elsewhere
        for death in self.supervisor.check():
            progressed += 1
            for ev in death["events"]:
                self._handle_event_from(death["replica"], ev)
            self._recover_replica(death["replica"])
        # 3. deadlines (queued + placed)
        self._expire_deadlines()
        # 3b. QoS config push + autoscale tick (ISSUE 17) — both no-ops
        #     unless armed
        if self._tenants:
            self._push_tenant_config()
        if self._autoscale is not None:
            self._autoscale_tick()
        # 4. placement
        progressed += self._place()
        # 5. drains
        self._advance_drains()
        _G_QUEUE.set(len(self._queue), instance=self._name)
        _G_DRAINING.set(len(self._draining), instance=self._name)
        return progressed

    # -- events ----------------------------------------------------------
    def _handle_event(self, handle, ev):
        self._handle_event_from(handle.id, ev)

    def _handle_event_from(self, replica_id, ev):
        kind = ev.get("e")
        if kind == "tok":
            req = self._reqs.get(ev.get("gid"))
            if req is None or req.finished:
                return
            # dedup contract: accept only the CURRENT assignment — same
            # replica AND same dispatch generation. A slow-but-alive
            # replica still emitting a superseded copy is ignored.
            if (req.state != PLACED or req.replica != replica_id
                    or ev.get("gen") != req.generation):
                return
            # first tokens from the decode worker ack the handed-off
            # pages arrived intact — the router's buffered copy can go
            # and the transfer retry budget re-arms (NOT at kvdone: a
            # decode side that keeps rejecting deliveries must still be
            # able to exhaust the budget into a typed KVTransferError)
            if ev.get("toks"):
                req.pages = None
                req.kv_retries = 0
            for tok in ev.get("toks", ()):
                if req.t_first is None:
                    req.t_first = time.perf_counter()
                req.emitted.append(int(tok))
            if ev.get("fin"):
                reason = ev.get("reason")
                self._inflight[replica_id].discard(req.gid)
                if reason == "timeout":
                    self._fail(req, RequestTimeoutError(
                        f"request {req.gid} hit its deadline mid-stream "
                        f"on replica {replica_id}", rid=req.gid,
                        deadline=req.deadline), reason)
                else:
                    req.state = DONE
                    req.finish_reason = reason
                    req.t_done = time.perf_counter()
                    self._note_done(req)
                    self._note_audit(req)
        elif kind == "kvpage":
            self._handle_kvpage(replica_id, ev)
        elif kind == "kvdone":
            self._handle_kvdone(replica_id, ev)
        elif kind == "load":
            self._load[replica_id] = ev
        elif kind == "err":
            req = self._reqs.get(ev.get("gid"))
            if req is not None and not req.finished:
                self._inflight[replica_id].discard(req.gid)
                if ev.get("kind") in ("KVTransferError",
                                      "KVIntegrityError"):
                    # the decode worker rejected the handed-off pages
                    # (corrupt/incomplete buffer, or the page CRCs
                    # failed verification at import): transient —
                    # re-drive the prefill under the transfer retry
                    # budget rather than ever decoding on garbage
                    self._kv_transfer_failed(
                        req, f"decode replica {replica_id} rejected the "
                             f"pages: {ev.get('msg')}")
                    return
                self._fail(req, RuntimeError(
                    f"replica {replica_id} rejected request {req.gid}: "
                    f"{ev.get('kind')}: {ev.get('msg')}"), "error")
        elif kind == "integrity":
            # a replica's periodic weight re-audit failed: its live
            # fingerprint drifted from the artifact's. The replica
            # reloads its own weights; the router charges one
            # suspicion point — repeated drift means the slot's
            # hardware cannot be trusted and quarantine restarts it
            self._charge_suspicion(
                replica_id, 1,
                f"weight fingerprint audit failed on replica "
                f"{replica_id} ({ev.get('kind')})",
                inc=self._incarnation(replica_id))
        elif kind == "reloaded":
            self.reloads.append((replica_id, ev.get("step")))
            d = self._draining.get(replica_id)
            if d is not None and d.get("state") == "reloading":
                d["reloaded_step"] = ev.get("step")
                self._finish_drain(replica_id)
        # "ready"/"stats"/"bye" need no router action (ready flips the
        # handle flag inside handle.events())

    def _fail(self, req, error, reason):
        req.state = FAILED
        req.error = error
        req.finish_reason = reason
        req.t_done = time.perf_counter()
        req.frames = {}
        req.pages = None
        self._note_done(req)
        if isinstance(error, RequestTimeoutError):
            _M_TIMEOUTS.inc(instance=self._name)
        self._note_audit(req)

    # -- disaggregated KV-page handoff (ISSUE 15) ------------------------
    def _handoff_current(self, replica_id, ev):
        """The in-flight handoff this frame/done event belongs to, or
        None when it is stale: wrong state, wrong replica, or a
        superseded handoff id — a zombie prefill worker re-delivering
        pages for an already re-driven transfer is dropped by id, so it
        can never double-deliver into the replayed stream."""
        req = self._reqs.get(ev.get("gid"))
        if (req is None or req.finished or req.state != PREFILLING
                or req.replica != replica_id
                or ev.get("hid") != req.hid):
            return None
        return req

    def _handle_kvpage(self, replica_id, ev):
        req = self._handoff_current(replica_id, ev)
        if req is None:
            return
        chunk = decode_frame(ev)
        if chunk is None:
            # corrupt frame: the WHOLE handoff is void — the prefill is
            # re-driven rather than ever decoded-on-garbage
            self._kv_transfer_failed(
                req, f"corrupt page frame {ev.get('seq')} from replica "
                     f"{replica_id}")
            return
        # keep the raw bytes (whole-payload CRC at kvdone) beside the
        # already-encoded data+crc (verified: crc == crc32(chunk)),
        # which forward verbatim to the decode worker
        req.frames[int(ev.get("seq", 0))] = (chunk, ev.get("data"),
                                             ev.get("crc"))
        _M_KV_PAGES.inc(instance=self._name)

    def _handle_kvdone(self, replica_id, ev):
        req = self._handoff_current(replica_id, ev)
        if req is None:
            return
        self._inflight[replica_id].discard(req.gid)
        if ev.get("fin") and ev.get("first_tok") is None:
            # prefill-side typed end before a first token existed
            # (deadline expired inside the prefill worker)
            req.frames = {}
            reason = ev.get("reason") or "error"
            if reason == "timeout":
                self._fail(req, RequestTimeoutError(
                    f"request {req.gid} hit its deadline during prefill "
                    f"on replica {replica_id}", rid=req.gid,
                    deadline=req.deadline), reason)
            else:
                self._fail(req, RuntimeError(
                    f"request {req.gid} ended during prefill on replica "
                    f"{replica_id}: {reason}"), reason)
            return
        total = int(ev.get("frames", 0))
        frames, req.frames = req.frames, {}
        blob, why = join_frames({i: c for i, (c, _, _) in frames.items()},
                                total, ev.get("crc"))
        if why is not None:
            self._kv_transfer_failed(
                req, f"{why} (from replica {replica_id})")
            return
        tok = int(ev["first_tok"])
        if req.t_first is None:
            req.t_first = time.perf_counter()
        req.emitted.append(tok)
        _M_HANDOFFS.inc(instance=self._name)
        if ev.get("fin") or req.remaining <= 0:
            # the first token already finished the request: no decode
            # stage, no pages to ship
            req.state = DONE
            req.finish_reason = ev.get("reason") or "length"
            req.t_done = time.perf_counter()
            self._note_done(req)
            self._note_audit(req)
            return
        # stage 2 pending: verified pages queue (front — oldest work)
        # for decode placement. Only the already-encoded frames are
        # kept — they forward verbatim, no re-encode.
        req.pages = {"frames": [(frames[i][1], frames[i][2])
                                for i in range(total)],
                     "crc": int(ev.get("crc", 0)), "count": total}
        req.state = QUEUED
        req.replica = None
        self._queue.appendleft(req)

    def _kv_transfer_failed(self, req, why, failover=False):
        """Void a handoff atomically — partial frames and buffered pages
        dropped, handoff id bumped so a zombie's stale deliveries miss —
        and re-drive the prefill elsewhere. Transient failures (corrupt
        frames, rejected deliveries) charge the transfer retry budget
        and fail with a typed :class:`KVTransferError` past it; worker
        deaths (``failover=True``) are counted as handoff failovers and
        governed by the supervisor's restart budget instead. The next
        prefill dispatch assigns a fresh handoff id; until then the
        QUEUED state alone fences stale deliveries."""
        req.frames = {}
        req.pages = None
        if req.state == PREFILLING and req.replica is not None:
            self._inflight.get(req.replica, set()).discard(req.gid)
        if failover:
            _M_FAILOVERS.inc(instance=self._name)
            _M_REDISPATCH.inc(instance=self._name)
            req.redispatches += 1
        else:
            req.kv_retries += 1
            if req.kv_retries > self.max_kv_retries:
                self._fail(req, KVTransferError(
                    f"request {req.gid}: KV-page handoff failed "
                    f"({why}); transfer retry budget "
                    f"({self.max_kv_retries}) exhausted",
                    gid=req.gid, retries=req.kv_retries), "kv_transfer")
                return
            _M_KV_RETRIES.inc(instance=self._name)
        req.state = QUEUED
        req.replica = None
        self._queue.appendleft(req)

    # -- death recovery --------------------------------------------------
    def _recover_replica(self, replica_id):
        """Requeue (at the FRONT, preserving age order) every in-flight
        request of a dead replica for replay elsewhere. The replay
        prompt is prompt + emitted-so-far; greedy determinism makes the
        resumed stream bit-identical to an undisturbed one. A handoff
        the dead replica was mid-transfer on is discarded atomically and
        the prefill re-driven (counted as a handoff failover)."""
        gids = sorted(self._inflight.get(replica_id, ()))
        self._inflight[replica_id] = set()
        self._load.pop(replica_id, None)
        # a dying replica cancels any drain it was serving
        self._draining.pop(replica_id, None)
        # session pins at the dead replica are stale either way: the
        # respawn rejoins with a COLD prefix cache, so steering the next
        # session request at the slot buys nothing and used to aim at a
        # corpse during the restart window (ISSUE 15 satellite)
        if self._sessions:
            self._sessions = {k: v for k, v in self._sessions.items()
                              if v != replica_id}
        for gid in reversed(gids):
            req = self._reqs.get(gid)
            if req is None or req.finished:
                continue
            if req.state == PREFILLING:
                # prefill worker died mid-transfer: partial pages are
                # dropped atomically, the prefill re-drives elsewhere —
                # decode streams of other requests never hiccup
                self._kv_transfer_failed(
                    req, f"prefill replica {replica_id} died "
                         "mid-transfer", failover=True)
                continue
            if req.audit is not None:
                # clean-room replay: an audit must be served start to
                # finish by ONE replica, or mismatch attribution is
                # meaningless — discard partial tokens, replay whole
                req.emitted = []
            if req.remaining <= 0:
                # everything was emitted; only the fin event was lost
                req.state = DONE
                req.finish_reason = "length"
                req.t_done = time.perf_counter()
                self._note_done(req)
                self._note_audit(req)
                continue
            req.state = QUEUED
            req.replica = None
            req.redispatches += 1
            # emitted moved past the handed-off pages: the replay
            # re-drives prefill from prompt+emitted, not stale pages
            req.frames = {}
            req.pages = None
            self._queue.appendleft(req)
            _M_REDISPATCH.inc(instance=self._name)

    # -- deadlines -------------------------------------------------------
    def _expire_deadlines(self):
        now = time.time()
        for req in list(self._reqs.values()):
            if req.finished or req.deadline is None or now < req.deadline:
                continue
            if req.state == QUEUED:
                try:
                    self._queue.remove(req)
                except ValueError:
                    pass
            elif req.state in (PLACED, PREFILLING):
                # free the replica's blocks; its own engine-side deadline
                # check races with this cancel — both are idempotent.
                # A mid-transfer handoff's partial pages die with the
                # request (_fail drops frames + pages).
                h = self._handle(req.replica)
                if h is not None:
                    h.send({"op": "cancel", "gid": req.gid,
                            "reason": "timeout"})
                self._inflight[req.replica].discard(req.gid)
            self._fail(req, RequestTimeoutError(
                f"request {req.gid} deadline expired "
                f"({'queued' if req.state == QUEUED else 'in flight'})",
                rid=req.gid, deadline=req.deadline), "timeout")

    # -- placement -------------------------------------------------------
    def _handle(self, replica_id):
        for h in self.supervisor.handles:
            if h.id == replica_id:
                return h
        return None

    def _placeable(self, h):
        return (h.ready and h.alive and not h.retired
                and h.id not in self._draining
                and len(self._inflight[h.id])
                < self.max_inflight_per_replica)

    # -- roles (ISSUE 15): prefill workers take stage-1 work only --------
    def _role(self, h):
        return getattr(h, "role", None) or "both"

    @property
    def split(self):
        """True when the fleet has dedicated prefill workers
        (role-disaggregated serving)."""
        return any(self._role(h) == "prefill"
                   for h in self.supervisor.handles)

    def _pending_handoffs(self):
        """Requests whose pages are buffered at the router (transfer in
        flight or awaiting decode placement) — the backpressure bound.
        Scans only the in-flight sets and the queue (both bounded), not
        the full request table: finished-but-unreleased requests on a
        long-lived server must not slow placement down."""
        n = 0
        for gids in self._inflight.values():
            for gid in gids:
                r = self._reqs.get(gid)
                if (r is not None and not r.finished
                        and (r.state == PREFILLING
                             or r.pages is not None)):
                    n += 1
        for r in self._queue:
            if r.pages is not None:
                n += 1
        return n

    def _least_loaded(self, candidates):
        best, best_score = None, None
        for h in candidates:
            load = self._load.get(h.id, {})
            score = (len(self._inflight[h.id]),
                     float(load.get("kv", 0.0))
                     + float(load.get("occ", 0.0)), h.id)
            if best_score is None or score < best_score:
                best, best_score = h, score
        return best

    def _pick_replica(self, req):
        """Decode-capable placement (any non-prefill role): session
        affinity first — the pin lives on the replica whose prefix
        cache is warm, i.e. the DECODE replica on a split fleet — then
        least-loaded."""
        if self.session_affinity and req.session is not None:
            rid = self._sessions.get(req.session)
            if rid is not None:
                h = self._handle(rid)
                if (h is not None and self._role(h) != "prefill"
                        and self._placeable(h)):
                    return h
        return self._least_loaded(
            h for h in self.supervisor.handles
            if self._role(h) != "prefill" and self._placeable(h))

    def _pick_prefill_replica(self):
        return self._least_loaded(
            h for h in self.supervisor.handles
            if self._role(h) == "prefill" and self._placeable(h))

    def _any_prefill_healthy(self):
        return any(h.alive and not h.retired
                   for h in self.supervisor.handles
                   if self._role(h) == "prefill")

    # -- dispatch helpers -----------------------------------------------
    def _replay_prompt(self, req):
        """Original prompt + everything already emitted — the greedy
        continuation from here is bit-identical."""
        return np.concatenate(
            [req.prompt, np.asarray(req.emitted, np.int32)]).tolist()

    def _send_checked(self, h, payload):
        try:
            _fi.fire("serve.dispatch")
        except Exception:
            return False
        return h.send(payload)

    def _dispatch_failed(self, req):
        """Requeue after a failed dispatch (dead pipe or injected
        fault): the bumped generation invalidates the half-delivered
        copy even if it arrived."""
        req.state = QUEUED
        req.replica = None
        req.redispatches += 1
        self._queue.appendleft(req)
        _M_REDISPATCH.inc(instance=self._name)

    def _note_session(self, req, h):
        if self.session_affinity and req.session is not None:
            # LRU-bounded: one entry per session key forever would
            # grow without bound on a long-lived server (the replica
            # worker bounds its gid bookkeeping the same way)
            self._sessions.pop(req.session, None)
            self._sessions[req.session] = h.id
            while len(self._sessions) > self.MAX_SESSIONS:
                self._sessions.pop(next(iter(self._sessions)))

    def _dispatch_submit(self, req, h):
        """Colocated dispatch: the replica prefills AND decodes."""
        self._queue.remove(req)
        req.generation += 1
        req.replica = h.id
        req.state = PLACED
        payload = {
            "op": "submit", "gid": req.gid, "gen": req.generation,
            "prompt": self._replay_prompt(req),
            "max_new": req.remaining, "eos": req.eos,
            "deadline": req.deadline, "tenant": req.tenant,
            "tier": req.tier,
        }
        if not self._send_checked(h, payload):
            self._dispatch_failed(req)
            return False
        self._inflight[h.id].add(req.gid)
        self._note_session(req, h)
        return True

    def _dispatch_prefill(self, req, h):
        """Stage 1: the prefill worker computes the pages and streams
        them back as CRC-framed kvpage events. A fresh handoff id fences
        the transfer — frames from any earlier assignment are void."""
        self._queue.remove(req)
        req.generation += 1
        req.hid += 1
        req.replica = h.id
        req.state = PREFILLING
        req.frames = {}
        payload = {
            "op": "prefill", "gid": req.gid, "gen": req.generation,
            "hid": req.hid, "prompt": self._replay_prompt(req),
            "max_new": req.remaining, "eos": req.eos,
            "deadline": req.deadline, "tenant": req.tenant,
            "tier": req.tier,
        }
        if not self._send_checked(h, payload):
            self._dispatch_failed(req)
            return False
        self._inflight[h.id].add(req.gid)
        return True

    def _dispatch_pages(self, req, h):
        """Stage 2: ship the CRC-verified pages down to the decode
        worker, then the submit that imports them. The decode prompt is
        prompt + emitted (exactly the prefill's first token at this
        point), the budget the remainder, and the deadline THE deadline
        — carried unchanged across the handoff."""
        self._queue.remove(req)
        req.generation += 1
        frames = req.pages["frames"]
        ok = True
        for seq, (data, crc) in enumerate(frames):
            # forwarded VERBATIM: the encoded form and CRC are the ones
            # the prefill worker produced and the router verified
            ok = h.send({"op": "kvpage", "gid": req.gid, "seq": seq,
                         "total": len(frames), "crc": crc, "data": data})
            if not ok:
                break
        if ok:
            ok = self._send_checked(h, {
                "op": "submit_pages", "gid": req.gid,
                "gen": req.generation,
                "prompt": self._replay_prompt(req),
                "max_new": req.remaining, "eos": req.eos,
                "deadline": req.deadline, "frames": len(frames),
                "crc": req.pages["crc"], "tenant": req.tenant,
                "tier": req.tier,
            })
        if not ok:
            # dead pipe: the verified pages stay buffered — the retry
            # ships the SAME pages to another decode replica next tick
            # (emitted has not advanced, so they are still exact)
            self._dispatch_failed(req)
            return False
        req.replica = h.id
        req.state = PLACED
        self._inflight[h.id].add(req.gid)
        self._note_session(req, h)
        return True

    def _place_stage2_behind_head(self):
        """Place pages-verified requests sitting BEHIND a
        backpressure-blocked stage-1 head. Stage-2 dispatch only ever
        DRAINS the transfer buffer, so letting it overtake cannot starve
        the head — it is what unblocks it. Without this, a stage-1
        replay requeued in front of a pages-ready request deadlocks the
        whole queue: the head waits on the pending-handoff count that
        only the request behind it can reduce."""
        placed = 0
        for req in [r for r in self._queue if r.pages is not None]:
            h = self._pick_replica(req)
            if h is None or not self._dispatch_pages(req, h):
                break
            placed += 1
        return placed

    def _place(self):
        placed = 0
        split = self.split
        deferred = []
        while self._queue:
            req = self._queue[0]
            if req.audit is not None:
                # integrity-audit replay: place on any decode-capable
                # replica NOT in the exclusion set (colocated even on
                # a split fleet — ONE replica must own the whole
                # replay or mismatch attribution is meaningless).
                # Unplaceable right now (every candidate excluded or
                # busy) -> defer past this tick: background audits
                # never wedge the head of the line for real traffic.
                h = self._pick_audit_replica(req)
                if h is None:
                    deferred.append(self._queue.popleft())
                    continue
                if not self._dispatch_submit(req, h):
                    break
                req.audit["auditor"] = h.id
                req.audit["auditor_inc"] = h.incarnation
                placed += 1
                continue
            if split and req.pages is not None:
                # stage 2: pages verified, awaiting a decode worker
                h = self._pick_replica(req)
                if h is None or not self._dispatch_pages(req, h):
                    break
                placed += 1
                continue
            if split:
                # stage 1: prefill placement. Backpressure: when the
                # transfer channel stalls (handoffs pile up buffered),
                # PAUSE new prefills — requests stay queued, and the
                # bounded admission queue sheds with a typed error
                # instead of growing silently.
                if self._pending_handoffs() >= self.max_pending_handoffs:
                    placed += self._place_stage2_behind_head()
                    break
                h = self._pick_prefill_replica()
                if h is None and not self._any_prefill_healthy():
                    # no healthy prefill worker at all: degrade
                    # gracefully to colocated prefill on the decode
                    # side, once-warned — serving beats stalling
                    h = self._pick_replica(req)
                    if h is None:
                        break
                    if not self._degraded_warned:
                        self._degraded_warned = True
                        warnings.warn(
                            f"{self._name}: no healthy prefill worker; "
                            "degrading to colocated prefill on decode "
                            "replicas until one rejoins",
                            RuntimeWarning)
                    if not self._dispatch_submit(req, h):
                        break
                    placed += 1
                    continue
                if h is None or not self._dispatch_prefill(req, h):
                    break
                placed += 1
                continue
            # colocated fleet: the PR-12 path
            h = self._pick_replica(req)
            if h is None or not self._dispatch_submit(req, h):
                # one retry per tick on a failed dispatch; if the pipe
                # is really dead the supervisor's next check() reports
                # the death and the replica leaves the placeable set
                break
            placed += 1
        if deferred:
            self._queue.extend(deferred)
        return placed

    # ------------------------------------------------------------------
    # graceful drain (part c)
    # ------------------------------------------------------------------
    def drain(self, replica_id, then="resume", ckpt_root=None, wait=False,
              timeout=120.0):
        """Stop placing requests on ``replica_id``; once its in-flight
        requests finish, run ``then``:

        * ``"resume"`` — just rejoin the placeable set;
        * ``"reload"`` — hot-swap weights from ``ckpt_root`` (default:
          the fleet's checkpoint root) via the worker's
          ``reload_weights``, then rejoin: the zero-drop rolling-update
          primitive;
        * ``"retire"`` — shut the replica down permanently.

        ``wait=True`` pumps :meth:`step` until the drain completes."""
        if then not in ("resume", "reload", "retire"):
            raise ValueError(f"unknown drain action {then!r}")
        if self._handle(replica_id) is None:
            raise ValueError(f"unknown replica {replica_id}")
        if then == "reload" and not (ckpt_root or self._ckpt_root):
            raise ValueError("drain(then='reload') needs ckpt_root= "
                             "(none configured on the fleet)")
        self._draining[replica_id] = {
            "state": "draining", "then": then,
            "root": ckpt_root or self._ckpt_root}
        _G_DRAINING.set(len(self._draining), instance=self._name)
        if wait:
            deadline = time.time() + float(timeout)
            backoff = _IdleBackoff(*self.idle_backoff)
            while replica_id in self._draining:
                if self.step():
                    backoff.reset()
                else:
                    backoff.idle()
                if time.time() > deadline:
                    raise TimeoutError(
                        f"drain of replica {replica_id} timed out")

    def _advance_drains(self):
        for rid, d in list(self._draining.items()):
            if d["state"] != "draining" or self._inflight.get(rid):
                continue
            if d["then"] == "retire":
                self.supervisor.retire(rid)
                self._finish_drain(rid)
            elif d["then"] == "reload":
                h = self._handle(rid)
                if h is None or not h.send({"op": "reload",
                                            "root": d["root"]}):
                    self._draining.pop(rid, None)  # died; recovery owns it
                else:
                    d["state"] = "reloading"
            else:  # resume
                self._finish_drain(rid)

    def _finish_drain(self, replica_id):
        self._draining.pop(replica_id, None)
        self.drains_completed += 1
        _G_DRAINING.set(len(self._draining), instance=self._name)

    # ------------------------------------------------------------------
    # introspection + teardown
    # ------------------------------------------------------------------
    def metrics(self):
        """Fleet-owned observability snapshot (the ``LLMEngine.metrics``
        discipline): registry-backed counters/gauges for THIS fleet."""
        inst = self._name
        from .supervisor import _G_LIVE, _M_RESTARTS

        # supervisor-owned series live under the SUPERVISOR's instance
        # label — identical to ours when we built it, but an injected
        # supervisor keeps its own name
        sup_inst = getattr(self.supervisor, "instance", inst)
        return {
            "instance": inst,
            "replicas_live": _G_LIVE.value(instance=sup_inst),
            "replica_restarts": int(_M_RESTARTS.value(instance=sup_inst)),
            "redispatches": int(_M_REDISPATCH.value(instance=inst)),
            "requests_shed": int(_M_SHED.value(instance=inst)),
            "deadline_expired": int(_M_TIMEOUTS.value(instance=inst)),
            "queue_depth": _G_QUEUE.value(instance=inst),
            "replicas_draining": _G_DRAINING.value(instance=inst),
            "drains_completed": self.drains_completed,
            # disaggregated handoff (ISSUE 15)
            "kv_pages_transferred": int(_M_KV_PAGES.value(instance=inst)),
            "kv_transfer_retries": int(
                _M_KV_RETRIES.value(instance=inst)),
            "prefill_handoffs": int(_M_HANDOFFS.value(instance=inst)),
            "handoff_failovers": int(_M_FAILOVERS.value(instance=inst)),
            # multi-tenant QoS + autoscale (ISSUE 17)
            "quota_rejections": int(
                _M_QUOTA_REJECTED.value(instance=inst)),
            "deadline_infeasible": int(
                _M_INFEASIBLE.value(instance=inst)),
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            # serving integrity sentinel (ISSUE 20)
            "audits_run": int(_M_AUDITS.value(instance=inst)),
            "audit_mismatches": int(
                _M_AUDIT_MISMATCH.value(instance=inst)),
            "replicas_quarantined": int(
                _M_QUARANTINED.value(instance=inst)),
        }

    def stats(self, timeout=10.0):
        """One-call fleet integrity/ops snapshot: the router's own
        :meth:`metrics` plus every live replica's synchronous ``stats``
        RPC (integrity counters included — pages verified/rejected,
        weight audits run/failed). On a tp-group fleet rank 0 answers
        for its whole group: SPMD lockstep means rank 0's counters ARE
        the group aggregate."""
        out = {"fleet": self.metrics(), "replicas": {}}
        for h in self.supervisor.handles:
            if h.alive and not h.retired:
                out["replicas"][h.id] = self.replica_stats(
                    h.id, timeout=timeout)
        return out

    def ttft_seconds(self):
        """Per-request submit→first-token latencies (finished requests
        that produced at least one token) — the drill's p99 source."""
        return [r.t_first - r.t_submit for r in self._reqs.values()
                if r.t_first is not None]

    def reset_replica_metrics(self):
        """Ask every live replica to reset its engine-owned metric
        series (the bench window discipline: warm-phase latency
        observations must not pollute a timed window's percentiles)."""
        for h in self.supervisor.handles:
            if h.alive and not h.retired:
                h.send({"op": "reset_metrics"})

    def replica_stats(self, replica_id, timeout=10.0):
        """Synchronous ``stats`` RPC to one replica (allocator cleanliness
        assertions in drills/tests). Every non-stats event drained while
        waiting is routed through the normal pump — ``events()`` is
        destructive, so returning mid-batch would drop live tokens."""
        h = self._handle(replica_id)
        if h is None or not h.send({"op": "stats"}):
            return None
        deadline = time.time() + timeout
        backoff = _IdleBackoff(*self.idle_backoff)
        while time.time() < deadline:
            stats = None
            evs = h.events()
            for ev in evs:
                if ev.get("e") == "stats" and stats is None:
                    stats = ev
                else:
                    self._handle_event(h, ev)
            if stats is not None:
                return stats
            if evs:
                backoff.reset()
            else:
                backoff.idle()
        return None

    def close(self):
        if self._closed:
            return
        self._closed = True
        self.supervisor.shutdown()
        for m in (_M_REDISPATCH, _M_SHED, _M_TIMEOUTS, _G_QUEUE,
                  _G_DRAINING, _M_KV_PAGES, _M_KV_RETRIES, _M_HANDOFFS,
                  _M_FAILOVERS, _M_QUOTA_REJECTED, _M_INFEASIBLE,
                  _M_AUDITS, _M_AUDIT_MISMATCH, _M_QUARANTINED):
            m.remove(instance=self._name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
