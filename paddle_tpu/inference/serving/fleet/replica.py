"""Replica worker process (ISSUE 12): one ``LLMEngine`` behind a
line-JSON RPC loop, runnable as
``python -m paddle_tpu.inference.serving.fleet.replica``.

Config arrives in ``PADDLE_REPLICA_CONFIG`` (JSON: ``artifact`` path
from :func:`~..engine.save_llama_artifact`, ``engine`` kwargs,
``hb_dir`` heartbeat directory, optional ``ckpt_root``, optional
``role`` — ``"both"``/``"prefill"``/``"decode"``, ISSUE 15). Protocol
(stdin commands → stdout events, one JSON object per line):

  {"op":"submit","gid":g,"gen":k,"prompt":[...],"max_new":n,
   "eos":t|null,"deadline":s|null}      -> tok events as tokens emerge
  {"op":"prefill","gid":g,"gen":k,"hid":h,...}  -> kvpage* + kvdone
  {"op":"kvpage","gid":g,"seq":i,"total":T,"crc":c,"data":b64}
  {"op":"submit_pages","gid":g,"gen":k,"prompt":[...],"frames":T,
   "crc":c,...}                         -> import pages, then tok events
  {"op":"cancel","gid":g}               -> blocks freed, slot recycled
  {"op":"reload","root":path}           -> {"e":"reloaded","step":s}
  {"op":"stats"}                        -> {"e":"stats",...}
  {"op":"shutdown"}                     -> drain in-flight, {"e":"bye"}

Events: ``ready`` (engine built, weights loaded — with the checkpoint
step it rejoined from, when a ``ckpt_root`` was given, and the slot's
``role``), ``tok`` (``{"gid","gen","toks":[...],"fin","reason"}``;
``gen`` echoes the dispatch generation so the router can drop emissions
from a superseded assignment), ``load`` (kv-utilization /
decode-occupancy after each step — the router's least-loaded signal),
``stats``, ``reloaded``, ``bye``.

Disaggregated handoff (ISSUE 15): a ``prefill``-role worker runs its
engine in ``prefill_only`` mode. On ``{"op":"prefill"}`` it admits the
request, and the moment the engine samples the request's FIRST token
(prefill complete) it exports the KV pages, streams them up as
CRC-framed ``kvpage`` events (``crc`` = zlib.crc32 of the raw chunk;
``hid`` echoes the dispatch's handoff id so the router can drop a
zombie's stale frames) followed by a ``kvdone`` carrying the first
token and the whole-payload CRC, then frees the request's blocks. A
``decode``-capable worker buffers ``kvpage`` command frames, verifies
each CRC, and on ``submit_pages`` imports the payload via
``LLMEngine.add_request_with_pages`` — a corrupt or incomplete buffer
is rejected with a typed ``err`` event (kind ``KVTransferError``) so
the router re-drives the prefill instead of decoding on garbage.
stdout carries ONLY protocol lines; everything chatty goes to stderr
(the supervisor routes it to a per-replica log file).

Heartbeats (``distributed.launch.heartbeat.write`` — the PR-4 files)
are written at every loop tick, engine-stepping or idle; the two chaos
sites fire at the loop head:

* ``serve.replica_crash`` — SIGKILL self (the OOM-killer/node-loss
  shape; nothing is flushed, the supervisor must recover everything);
* ``serve.replica_hang``  — wedge forever without heartbeating (the
  stuck-collective shape; only the supervisor's watchdog can end it);
* ``serve.prefill_crash`` — fired between kvpage frame emissions:
  SIGKILL self MID-TRANSFER, the partial-pages recovery shape;
* ``serve.kv_transfer_corrupt`` — fired per kvpage frame: the frame's
  payload is corrupted after its CRC was computed, so the receiver's
  CRC check must catch it.
* ``serve.bit_flip`` — silent data corruption (ISSUE 20): flips bits in
  a weight buffer, a host-tier KV entry, or a KV pool page
  (``CHAOS_SERVE_BIT_FLIP_TARGET`` = ``weights`` | ``host_entry`` |
  ``kv_page``). Nothing crashes and nothing raises — the integrity
  sentinel (page CRCs / sampled output audit / weight re-audit) must
  catch it.

The periodic weight re-audit (ISSUE 20) is armed by
``PADDLE_SERVE_WEIGHT_AUDIT_TICKS=N``: every N loop ticks the worker
re-hashes the live weights against the fingerprint captured at load; a
mismatch emits ``{"e":"integrity","kind":"weight_audit"}`` (a suspicion
charge at the router) and hot-reloads the artifact's clean weights.

Chaos arming is env-driven so drills can poison exactly one replica:
``CHAOS_SERVE_SITE`` + ``CHAOS_SERVE_REPLICA`` + optional
``CHAOS_SERVE_AFTER_STEPS`` — armed only in incarnation 0, so the
respawned replica runs clean (the marker-file discipline of
``chaos_train.py``, enforced by the incarnation counter instead). A
drill that poisons SEVERAL replicas at once (the disagg storm) sets
``CHAOS_SERVE_SITES`` instead: a JSON list of
``{"site","replica","after"}`` specs.
"""

from __future__ import annotations

import json
import os
import queue
import signal
import sys
import threading
import time
import zlib

from .framing import decode_frame, encode_frame, join_frames, split_frames
from .supervisor import (ENV_CONFIG, ENV_COORD_PORT, ENV_GROUP_RANK,
                         ENV_GROUP_SIZE, ENV_ID, ENV_INCARNATION)

__all__ = ["replica_worker_main"]

# non-zero group ranks run the SAME engine in SPMD lockstep but own no
# RPC stream — rank 0 is the one mouth of the group, so everyone else's
# protocol emissions are suppressed (their stdout is a log file)
_SILENT = [False]


def _emit(obj):
    if _SILENT[0]:
        return
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


class _GroupChannel:
    """Rank-0 → member command broadcast for a multi-process replica
    group, over the group's own jax coordination service KV store (the
    PR-4 transport — no second socket layer). The contract is SPMD
    lockstep: rank 0 publishes one ``fleet.tick.<seq>`` entry per busy
    loop iteration carrying exactly the commands it is about to apply;
    every member applies the same commands to an identical engine and
    then steps — so the collectives inside the compiled step line up by
    construction. Idle iterations publish nothing (no collectives run);
    members poll with a timeout so their heartbeats stay fresh while
    idle. Consumed entries are garbage-collected ``_GC_LAG`` ticks
    behind the publisher — members can never lag further than one
    in-flight collective."""

    _GC_LAG = 512

    def __init__(self):
        from jax._src import distributed as jdist

        self._client = jdist.global_state.client
        self._seq = 0

    def publish(self, cmds):
        self._client.key_value_set(f"fleet.tick.{self._seq}",
                                   json.dumps(cmds))
        old = self._seq - self._GC_LAG
        if old >= 0:
            try:
                self._client.key_value_delete(f"fleet.tick.{old}")
            except Exception:
                pass
        self._seq += 1

    def fetch(self, timeout_ms=250):
        """The next tick's commands, or ``None`` on timeout (idle)."""
        try:
            raw = self._client.blocking_key_value_get(
                f"fleet.tick.{self._seq}", int(timeout_ms))
        except Exception:
            return None
        self._seq += 1
        return json.loads(raw)


# the armed inject() context managers must outlive _arm_chaos: a GC'd
# contextmanager generator runs its finally block, silently DISARMING
# the site — module-global keeps them alive for the process lifetime
_CHAOS_CMS: list = []


def _chaos_specs(replica_id, group_rank=0):
    """Armed (site, after, max_fires) specs for THIS process. Specs may
    carry a ``"rank"`` (default 0) so a group drill can poison exactly
    one member — e.g. ``serve.group_member_crash`` on rank 1 while rank
    0 keeps answering the router until the supervisor fells the group."""
    multi = os.environ.get("CHAOS_SERVE_SITES")
    if multi:
        try:
            specs = json.loads(multi)
        except ValueError:
            return []
        return [(s["site"], int(s.get("after", 1) or 1),
                 s.get("max_fires")) for s in specs
                if str(s.get("replica")) == str(replica_id)
                and int(s.get("rank", 0) or 0) == int(group_rank)]
    site = os.environ.get("CHAOS_SERVE_SITE")
    if site and os.environ.get("CHAOS_SERVE_REPLICA") == str(replica_id) \
            and int(os.environ.get("CHAOS_SERVE_RANK", "0")
                    or 0) == int(group_rank):
        return [(site,
                 int(os.environ.get("CHAOS_SERVE_AFTER_STEPS", "1") or 1),
                 None)]
    return []


def _arm_chaos(replica_id, group_rank=0):
    if int(os.environ.get(ENV_INCARNATION, "0") or 0) != 0:
        return  # restarted incarnations run clean
    from ....utils import fault_injection as fi

    for site, after, max_fires in _chaos_specs(replica_id, group_rank):
        # armed for the process lifetime (the fault ends or taints only
        # this incarnation)
        cm = fi.inject(site, every_n=after, max_fires=max_fires)
        cm.__enter__()
        _CHAOS_CMS.append(cm)


def replica_worker_main():
    replica_id = int(os.environ[ENV_ID])
    group_size = int(os.environ.get(ENV_GROUP_SIZE, "1") or 1)
    group_rank = int(os.environ.get(ENV_GROUP_RANK, "0") or 0)
    _SILENT[0] = group_rank != 0
    cfg = json.loads(os.environ[ENV_CONFIG])
    _arm_chaos(replica_id, group_rank)

    if group_size > 1:
        # multi-process replica group (ISSUE 19): rendezvous on the
        # incarnation's PRIVATE coordination service (fresh port per
        # incarnation — a respawned group must never rendezvous with a
        # half-dead predecessor) before any backend work. gloo backs the
        # CPU cross-process collectives; real TPU pods override the
        # platform via env and ride the default backend.
        if os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
            # CPU simulation: each member owns an EQUAL share of the
            # plan's devices, so the group's global mesh is exactly the
            # plan — regardless of any device count the parent baked
            # into XLA_FLAGS (the test harness forces 8 virtual devices
            # per process, which would hand a 2-process tp=2 group 16
            # global devices and a mesh living entirely on rank 0).
            # XLA_FLAGS is still honored here: backends init lazily and
            # no array op has run yet.
            import re

            spec = cfg.get("plan") or {}
            total = 1
            for v in (spec.get("axes") or {}).values():
                total *= int(v)
            per = max(total // group_size, 1)
            flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                           "", os.environ.get("XLA_FLAGS", ""))
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={per}"
            ).strip()
        import jax

        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            f"127.0.0.1:{os.environ[ENV_COORD_PORT]}",
            num_processes=group_size, process_id=group_rank)

    import numpy as np

    from ....distributed.launch import heartbeat as hb
    from ....utils import fault_injection as fi
    from .. import integrity as _integrity
    from ..engine import LLMEngine, load_llama_artifact
    from ..errors import RequestTimeoutError
    from ..kv_cache import pack_kv_pages, unpack_kv_pages
    from ..scheduler import SamplingParams

    model = load_llama_artifact(cfg["artifact"])
    role = cfg.get("role") or "both"
    engine_kw = dict(cfg.get("engine") or {})
    plan_spec = cfg.get("plan")
    if plan_spec:
        # sharding plan from its JSON spec ({"axes": {...}, "strategies":
        # [...]}): the mesh is built over jax.devices() — the group's
        # GLOBAL device set after the rendezvous above, or this process's
        # virtual devices for in-process tp (XLA_FLAGS via env_extra)
        from ....distributed.plan import Plan

        engine_kw["plan"] = Plan.build(
            dict(plan_spec["axes"]),
            list(plan_spec.get("strategies") or ()))
    if engine_kw.get("prefix_store_path"):
        # each replica persists its own prefix-store shard — a literal
        # shared path would have every worker clobbering one store file
        # at close(), so the fleet API takes a ``{replica}`` template
        engine_kw["prefix_store_path"] = str(
            engine_kw["prefix_store_path"]).replace(
                "{replica}", str(replica_id))
    eng = LLMEngine(model, ingest_async=False,
                    prefill_only=(role == "prefill"),
                    **engine_kw)
    reloaded = None
    root = cfg.get("ckpt_root")
    if root:
        # rejoin contract: a (re)started replica serves the newest
        # healthy checkpoint, never the artifact's possibly-stale weights
        from ....distributed.checkpoint.manager import CheckpointManager

        mgr = CheckpointManager(root)
        if (mgr.latest_healthy_step() is not None
                or mgr.latest_valid_step() is not None):
            reloaded = eng.reload_weights(mgr)
    hb_dir = cfg.get("hb_dir")
    # group members heartbeat under hb.<replica>.<rank> — EVERY member
    # beats, so the watchdog condemns the group when ANY member wedges
    # (single-process replicas keep the bare hb.<replica> name)
    hb_rank = (f"{replica_id}.{group_rank}" if group_size > 1
               else replica_id)
    hb.write(step=0, dir=hb_dir, rank=hb_rank)

    # In-graph/window engines (decode_steps_per_sync > 1) warm their
    # decode executable BEFORE reporting ready: the first-call compile of
    # a fused k-step window can outlast the hang watchdog — especially
    # with every replica compiling at once — and a replica must never
    # look wedged for unavoidable one-time work. Boot time is covered by
    # the supervisor's boot grace, not the heartbeat. Default engines
    # keep the lazy first-call compile (pre-window boot behavior) —
    # EXCEPT replica groups, which pre-compile EVERY admissible prefill
    # bucket: a post-ready first-touch compile stalls the whole group's
    # collectives with every heartbeat silent, long enough to read as a
    # hang, and boot (covered by the group-scaled boot grace) is the
    # only place one-time work belongs. Both ranks run this identical
    # warmup, so the compile-time collectives line up by construction.
    if (getattr(eng, "_in_graph", False) or group_size > 1) \
            and role != "prefill":
        cap = min(eng.max_model_len,
                  (eng.cache.num_blocks - 1) * eng.block_size)
        lens = [4]
        if group_size > 1:
            lens, prev = [], 0
            for b in eng.prefill_buckets:
                ln = min(b - 1, cap - 1)
                if ln > prev:
                    lens.append(ln)
                prev = b
        for k, ln in enumerate(lens):
            wid = eng.add_request(
                np.zeros(ln, dtype=np.int64),
                SamplingParams(max_new_tokens=2 if k == 0 else 1))
            while not any(o.rid == wid and o.finished
                          for o in eng.step()):
                pass
            eng.release(wid)
        eng.reset_metrics()
        eng.reset_block_high_water()
        # the warmup compiles ran long past the boot-time heartbeat:
        # refresh it BEFORE ready flips, or the watchdog reads the whole
        # warmup as staleness the moment boot grace stops protecting us
        hb.write(step=0, dir=hb_dir, rank=hb_rank)

    chan = None
    if group_size > 1:
        # ready only after ALL ranks ack warm-up (ISSUE 19 satellite):
        # the barrier proves every member built its engine, committed
        # the plan-sharded weights and warmed its executables — a group
        # where one rank is still compiling must not take traffic
        from ....distributed.checkpoint import sync_processes

        sync_processes("fleet.group.warmup")
        # ranks can skew by whole compiles at the barrier; every member
        # re-beats on release so nobody's wait reads as a wedge
        hb.write(step=0, dir=hb_dir, rank=hb_rank)
        chan = _GroupChannel()

    _emit({"e": "ready", "replica": replica_id, "role": role,
           "incarnation": int(os.environ.get(ENV_INCARNATION, "0") or 0),
           "reloaded_step": reloaded, "group_size": group_size})

    cmd_q: queue.Queue = queue.Queue()

    def _reader():
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                cmd_q.put(json.loads(line))
            except ValueError:
                continue
        cmd_q.put({"op": "shutdown"})  # EOF: the router is gone

    if group_rank == 0:
        # only rank 0 owns an RPC stream; a member's stdin is /dev/null
        # and its EOF must not shut the group down at boot
        threading.Thread(target=_reader, daemon=True).start()

    rid_of = {}    # gid -> engine rid
    meta = {}      # gid -> {"gen": k}
    handoff = {}   # gid -> {"gen","hid"}: op=prefill requests (ISSUE 15)
    page_buf = {}  # gid -> {"frames": {seq: bytes}, "bad": reason|None}
    steps = 0
    shutting = False
    # periodic weight re-audit (ISSUE 20): every N loop ticks, re-hash
    # the live weights against the load-time fingerprint. Single-process
    # replicas only — a group rank's params are plan-sharded device
    # arrays, and the group's SPMD lockstep must not fork on a
    # host-side reload.
    audit_every = int(os.environ.get("PADDLE_SERVE_WEIGHT_AUDIT_TICKS",
                                     "0") or 0)
    if group_size > 1:
        audit_every = 0

    def _stream_pages(gid, out):
        """Prefill finished for a handed-off request: export its pages,
        stream CRC-framed ``kvpage`` events (the mid-transfer chaos
        probes fire between frames), emit ``kvdone`` with the first
        sampled token, then free the request's blocks — the decode
        worker owns it from here."""
        hm = handoff.pop(gid)
        rid = rid_of.pop(gid)
        if out.token < 0:
            # aborted before/without a first token (deadline expiry):
            # typed end, no pages, nothing held
            _emit({"e": "kvdone", "gid": gid, "hid": hm["hid"],
                   "first_tok": None, "fin": True,
                   "reason": out.finish_reason, "frames": 0, "crc": 0})
            eng.release(rid)
            return
        if out.finished:
            # the first token already ends the request (max_new=1 or
            # EOS): nothing left to decode, nothing to transfer
            _emit({"e": "kvdone", "gid": gid, "hid": hm["hid"],
                   "first_tok": int(out.token), "fin": True,
                   "reason": out.finish_reason, "frames": 0, "crc": 0})
            eng.release(rid)
            return
        pages = eng.export_kv_pages(rid)
        blob = pack_kv_pages(pages)
        frames = split_frames(blob)
        for seq, chunk in enumerate(frames):
            if fi.should_fire("serve.prefill_crash"):
                os.kill(os.getpid(), signal.SIGKILL)  # mid-transfer
            fr = encode_frame(
                chunk,
                corrupt=fi.should_fire("serve.kv_transfer_corrupt"))
            _emit({"e": "kvpage", "gid": gid, "hid": hm["hid"],
                   "seq": seq, "total": len(frames), **fr})
        _emit({"e": "kvdone", "gid": gid, "hid": hm["hid"],
               "first_tok": int(out.token), "fin": False, "reason": None,
               "frames": len(frames), "crc": zlib.crc32(blob),
               "nbytes": len(blob), "covered": int(pages["covered"])})
        # handoff delivered: this worker's part is done — free the blocks
        eng.cancel(rid, reason="handoff")
        eng.release(rid)

    def _handle(cmd):
        nonlocal shutting
        op = cmd.get("op")
        if op == "submit":
            gid = cmd["gid"]
            try:
                rid = eng.add_request(
                    np.asarray(cmd["prompt"], np.int32),
                    SamplingParams(max_new_tokens=int(cmd["max_new"]),
                                   eos_token_id=cmd.get("eos")),
                    deadline=cmd.get("deadline"),
                    tenant=cmd.get("tenant"), tier=cmd.get("tier"))
            except RequestTimeoutError:
                _emit({"e": "tok", "gid": gid, "gen": cmd.get("gen", 0),
                       "toks": [], "fin": True, "reason": "timeout"})
                return
            except Exception as ex:  # typed errors -> router surfaces
                _emit({"e": "err", "gid": gid,
                       "kind": type(ex).__name__, "msg": str(ex)})
                return
            rid_of[gid] = rid
            meta[gid] = {"gen": cmd.get("gen", 0)}
        elif op == "prefill":
            # disaggregated stage 1 (ISSUE 15): admit normally; the
            # output loop intercepts the first sampled token and streams
            # the KV pages up instead of emitting it as a tok event
            gid = cmd["gid"]
            try:
                rid = eng.add_request(
                    np.asarray(cmd["prompt"], np.int32),
                    SamplingParams(max_new_tokens=int(cmd["max_new"]),
                                   eos_token_id=cmd.get("eos")),
                    deadline=cmd.get("deadline"),
                    tenant=cmd.get("tenant"), tier=cmd.get("tier"))
            except RequestTimeoutError:
                _emit({"e": "kvdone", "gid": gid,
                       "hid": cmd.get("hid", 0), "first_tok": None,
                       "fin": True, "reason": "timeout", "frames": 0,
                       "crc": 0})
                return
            except Exception as ex:
                _emit({"e": "err", "gid": gid,
                       "kind": type(ex).__name__, "msg": str(ex)})
                return
            rid_of[gid] = rid
            handoff[gid] = {"gen": cmd.get("gen", 0),
                            "hid": cmd.get("hid", 0)}
        elif op == "kvpage":
            # disaggregated stage 2, inbound frame: buffer + verify CRC
            gid = cmd["gid"]
            buf = page_buf.setdefault(gid, {"frames": {}, "bad": None})
            chunk = decode_frame(cmd)
            if chunk is None:
                buf["bad"] = f"frame {cmd.get('seq')} corrupt"
                return
            buf["frames"][int(cmd["seq"])] = chunk
            # bound the staging dict: frames whose submit_pages never
            # arrives (router died mid-send) must not grow forever
            while len(page_buf) > 32:
                page_buf.pop(next(iter(page_buf)))
        elif op == "submit_pages":
            gid = cmd["gid"]
            buf = page_buf.pop(gid, None) or {"frames": {}, "bad": None}
            why = buf["bad"]
            pages = None
            if why is None:
                blob, why = join_frames(buf["frames"],
                                        cmd.get("frames", 0),
                                        cmd.get("crc"))
            if why is None:
                try:
                    pages = unpack_kv_pages(blob)
                except ValueError as ex:
                    why = str(ex)
            if why is not None:
                # typed rejection: the router re-drives the prefill under
                # its transfer retry budget — NEVER decode on garbage
                _emit({"e": "err", "gid": gid, "kind": "KVTransferError",
                       "msg": f"rejecting handed-off pages: {why}"})
                return
            try:
                rid = eng.add_request_with_pages(
                    np.asarray(cmd["prompt"], np.int32), pages,
                    SamplingParams(max_new_tokens=int(cmd["max_new"]),
                                   eos_token_id=cmd.get("eos")),
                    deadline=cmd.get("deadline"),
                    tenant=cmd.get("tenant"), tier=cmd.get("tier"))
            except RequestTimeoutError:
                # expired between prefill completion and decode
                # admission: imported pages dropped, typed end
                _emit({"e": "tok", "gid": gid, "gen": cmd.get("gen", 0),
                       "toks": [], "fin": True, "reason": "timeout"})
                return
            except Exception as ex:
                _emit({"e": "err", "gid": gid,
                       "kind": type(ex).__name__, "msg": str(ex)})
                return
            rid_of[gid] = rid
            meta[gid] = {"gen": cmd.get("gen", 0)}
        elif op == "cancel":
            gid = cmd["gid"]
            page_buf.pop(gid, None)
            handoff.pop(gid, None)
            rid = rid_of.get(gid)
            if rid is not None:
                eng.cancel(rid, reason=cmd.get("reason", "cancelled"))
                # cancelled requests emit no fin event — drop the
                # bookkeeping here or it grows for the server's life
                rid_of.pop(gid, None)
                meta.pop(gid, None)
                eng.release(rid)
        elif op == "reload":
            from ....distributed.checkpoint.manager import CheckpointManager

            step = eng.reload_weights(CheckpointManager(cmd["root"]))
            _emit({"e": "reloaded", "replica": replica_id, "step": step})
        elif op == "stats":
            s = eng.stats()
            m = eng.metrics()
            _emit({"e": "stats", "replica": replica_id, "role": role,
                   "blocks_free": s["blocks_free"],
                   "blocks_high_water": s["blocks_high_water"],
                   "waiting": s["waiting"], "running": s["running"],
                   "steps": s["steps"], "tokens_out": s["tokens_out"],
                   # engine-owned latency percentiles (ISSUE 15): the
                   # disagg bench reads DECODE-worker ITL from here, so
                   # the comparison is engine-measured, not bench-timed
                   "itl_p50_ms": m["itl_ms"]["p50"],
                   "itl_p99_ms": m["itl_ms"]["p99"],
                   "ttft_p99_ms": m["ttft_ms"]["p99"],
                   # per-replica QoS counters (ISSUE 17): the qos drill
                   # and bench sum these fleet-wide to prove batch-tier
                   # work YIELDED slots rather than being dropped
                   "quota_throttled": s["quota_throttled"],
                   "batch_yields": s["batch_yields"],
                   # integrity counters (ISSUE 20). For tp groups, rank
                   # 0 is the group's one mouth and runs in SPMD
                   # lockstep with every member, so its engine-owned
                   # counters ARE the group's aggregate.
                   "kv_pages_verified": m["kv_pages_verified"],
                   "kv_pages_rejected": m["kv_pages_rejected"],
                   "weight_audits": m["weight_audits"],
                   "weight_audit_failures": m["weight_audit_failures"]})
        elif op == "configure_tenant":
            # QoS envelope push (ISSUE 17): idempotent — the router
            # re-sends the full set to every new incarnation. Cache
            # shares only apply where the subsystem exists; a fleet
            # without tiering/prefix-sharing serves the tenant without
            # those caps rather than erroring the whole config.
            eng.configure_tenant(
                cmd["tenant"], weight=cmd.get("weight", 1.0),
                rate_tokens_per_s=cmd.get("rate"),
                window_s=cmd.get("window", 1.0),
                host_blocks=(cmd.get("host_blocks")
                             if eng.kv_tier is not None else None),
                prefix_blocks=(cmd.get("prefix_blocks")
                               if eng.prefix_cache is not None else None))
        elif op == "reset_metrics":
            # window discipline (bench): warm-phase latency observations
            # must not pollute the timed window's percentiles
            eng.reset_metrics()
            eng.reset_block_high_water()
        elif op == "shutdown":
            shutting = True

    gid_by_rid = {}
    # heartbeat/load-report throttles: an atomic file replace and a JSON
    # line per ~1ms engine step is pure overhead — the watchdog judges
    # in seconds and the router's load signal tolerates 100ms staleness
    last_hb = [0.0]
    last_load = [0.0]

    def _beat():
        now = time.monotonic()
        if now - last_hb[0] >= 0.25:
            last_hb[0] = now
            hb.write(step=steps, dir=hb_dir, rank=hb_rank)

    while True:
        # chaos probes count BUSY ticks only: a crash/hang while idle
        # exercises nothing — the interesting failure is mid-serve, with
        # in-flight requests for the router to recover. The group sites
        # are armed on ONE member (the spec's "rank"): member_crash is
        # the partial-group OOM-kill shape, member_hang wedges this rank
        # so the next collective stalls the WHOLE group — every member's
        # heartbeat goes stale and only the watchdog can end it.
        if eng.has_work():
            if fi.should_fire("serve.replica_crash"):
                os.kill(os.getpid(), signal.SIGKILL)
            if fi.should_fire("serve.group_member_crash"):
                os.kill(os.getpid(), signal.SIGKILL)
            if fi.should_fire("serve.replica_hang") or \
                    fi.should_fire("serve.group_member_hang"):
                while True:  # wedged: no heartbeat, no service, no exit
                    time.sleep(3600)
            if fi.should_fire("serve.bit_flip"):
                # SILENT corruption: nothing raises, nothing exits — the
                # flip lands and this replica keeps serving wrong bytes
                # until the integrity sentinel catches it
                _integrity.flip_bit(
                    eng, os.environ.get("CHAOS_SERVE_BIT_FLIP_TARGET",
                                        "weights"))
        if chan is not None and group_rank > 0:
            # member rank: commands arrive ONLY on the broadcast channel,
            # in rank 0's exact application order (SPMD lockstep); a
            # fetch timeout is an idle tick — heartbeat and re-poll
            cmds = chan.fetch()
            if cmds is None:
                steps += 1
                _beat()
                continue
            for cmd in cmds:
                _handle(cmd)
        else:
            try:
                cmd = (cmd_q.get_nowait() if eng.has_work() or shutting
                       else cmd_q.get(timeout=0.05))
            except queue.Empty:
                cmd = None
            cmds = []
            while cmd is not None:
                cmds.append(cmd)
                try:
                    cmd = cmd_q.get_nowait()
                except queue.Empty:
                    cmd = None
            if chan is not None:
                # group lockstep cannot follow wall clocks: a deadline
                # expiring between two ranks' admission checks would
                # desynchronize the collectives, so group replicas strip
                # it — deadline enforcement stays at the router, whose
                # cancel commands ride this same ordered channel
                for c in cmds:
                    c.pop("deadline", None)
                if cmds or eng.has_work():
                    chan.publish(cmds)
            for cmd in cmds:
                _handle(cmd)
        if eng.has_work():
            gid_by_rid = {rid: gid for gid, rid in rid_of.items()}
            per_gid = {}
            for out in eng.step():
                gid = gid_by_rid.get(out.rid)
                if gid is None:
                    continue
                if gid in handoff:
                    # prefill handoff: the first token triggers the page
                    # transfer instead of a tok event
                    _stream_pages(gid, out)
                    continue
                rec = per_gid.setdefault(
                    gid, {"toks": [], "fin": False, "reason": None})
                if out.token >= 0:
                    rec["toks"].append(int(out.token))
                if out.finished:
                    rec["fin"] = True
                    rec["reason"] = out.finish_reason
            for gid, rec in per_gid.items():
                _emit({"e": "tok", "gid": gid, "gen": meta[gid]["gen"],
                       "toks": rec["toks"], "fin": rec["fin"],
                       "reason": rec["reason"]})
                if rec["fin"]:
                    rid = rid_of.pop(gid)
                    meta.pop(gid, None)
                    eng.release(rid)
            now = time.monotonic()
            if now - last_load[0] >= 0.1:
                last_load[0] = now
                m = eng.metrics()
                _emit({"e": "load", "replica": replica_id,
                       "kv": m["kv_block_utilization"] or 0.0,
                       "occ": m["decode_batch_occupancy"] or 0.0,
                       "waiting": len(eng.scheduler.waiting)})
        steps += 1
        if audit_every and steps % audit_every == 0 and not shutting:
            if not eng.audit_weights():
                # in-place weight corruption: tell the router (suspicion
                # charge) and hot-swap the artifact's clean weights so
                # this replica stops serving wrong bytes NOW — the
                # router may still quarantine-restart it
                _emit({"e": "integrity", "kind": "weight_audit",
                       "replica": replica_id})
                try:
                    eng.reload_weights(cfg["artifact"])
                except Exception as ex:  # pragma: no cover - defensive
                    _emit({"e": "err", "gid": None,
                           "kind": type(ex).__name__,
                           "msg": f"reload after failed weight audit: "
                                  f"{ex}"})
        _beat()
        if shutting and not eng.has_work():
            eng.close()
            _emit({"e": "bye", "replica": replica_id})
            return 0


if __name__ == "__main__":
    sys.exit(replica_worker_main())
