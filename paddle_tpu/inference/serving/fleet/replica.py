"""Replica worker process (ISSUE 12): one ``LLMEngine`` behind a
line-JSON RPC loop, runnable as
``python -m paddle_tpu.inference.serving.fleet.replica``.

Config arrives in ``PADDLE_REPLICA_CONFIG`` (JSON: ``artifact`` path
from :func:`~..engine.save_llama_artifact`, ``engine`` kwargs,
``hb_dir`` heartbeat directory, optional ``ckpt_root``). Protocol
(stdin commands → stdout events, one JSON object per line):

  {"op":"submit","gid":g,"gen":k,"prompt":[...],"max_new":n,
   "eos":t|null,"deadline":s|null}      -> tok events as tokens emerge
  {"op":"cancel","gid":g}               -> blocks freed, slot recycled
  {"op":"reload","root":path}           -> {"e":"reloaded","step":s}
  {"op":"stats"}                        -> {"e":"stats",...}
  {"op":"shutdown"}                     -> drain in-flight, {"e":"bye"}

Events: ``ready`` (engine built, weights loaded — with the checkpoint
step it rejoined from, when a ``ckpt_root`` was given), ``tok``
(``{"gid","gen","toks":[...],"fin","reason"}``; ``gen`` echoes the
dispatch generation so the router can drop emissions from a superseded
assignment), ``load`` (kv-utilization / decode-occupancy after each
step — the router's least-loaded signal), ``stats``, ``reloaded``,
``bye``. stdout carries ONLY these lines; everything chatty goes to
stderr (the supervisor routes it to a per-replica log file).

Heartbeats (``distributed.launch.heartbeat.write`` — the PR-4 files)
are written at every loop tick, engine-stepping or idle; the two chaos
sites fire at the loop head:

* ``serve.replica_crash`` — SIGKILL self (the OOM-killer/node-loss
  shape; nothing is flushed, the supervisor must recover everything);
* ``serve.replica_hang``  — wedge forever without heartbeating (the
  stuck-collective shape; only the supervisor's watchdog can end it).

Chaos arming is env-driven so drills can poison exactly one replica:
``CHAOS_SERVE_SITE`` + ``CHAOS_SERVE_REPLICA`` + optional
``CHAOS_SERVE_AFTER_STEPS`` — armed only in incarnation 0, so the
respawned replica runs clean (the marker-file discipline of
``chaos_train.py``, enforced by the incarnation counter instead).
"""

from __future__ import annotations

import json
import os
import queue
import signal
import sys
import threading
import time

from .supervisor import ENV_CONFIG, ENV_ID, ENV_INCARNATION

__all__ = ["replica_worker_main"]


def _emit(obj):
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


# the armed inject() context manager must outlive _arm_chaos: a GC'd
# contextmanager generator runs its finally block, silently DISARMING
# the site — module-global keeps it alive for the process lifetime
_CHAOS_CM = None


def _arm_chaos(replica_id):
    site = os.environ.get("CHAOS_SERVE_SITE")
    if not site:
        return
    if os.environ.get("CHAOS_SERVE_REPLICA") != str(replica_id):
        return
    if int(os.environ.get(ENV_INCARNATION, "0") or 0) != 0:
        return  # restarted incarnations run clean
    from ....utils import fault_injection as fi

    global _CHAOS_CM
    after = int(os.environ.get("CHAOS_SERVE_AFTER_STEPS", "1") or 1)
    # armed for the process lifetime (the fault ends this incarnation)
    _CHAOS_CM = fi.inject(site, every_n=after)
    _CHAOS_CM.__enter__()


def replica_worker_main():
    replica_id = int(os.environ[ENV_ID])
    cfg = json.loads(os.environ[ENV_CONFIG])
    _arm_chaos(replica_id)

    import numpy as np

    from ....distributed.launch import heartbeat as hb
    from ....utils import fault_injection as fi
    from ..engine import LLMEngine, load_llama_artifact
    from ..errors import RequestTimeoutError
    from ..scheduler import SamplingParams

    model = load_llama_artifact(cfg["artifact"])
    eng = LLMEngine(model, ingest_async=False, **cfg.get("engine") or {})
    reloaded = None
    root = cfg.get("ckpt_root")
    if root:
        # rejoin contract: a (re)started replica serves the newest
        # healthy checkpoint, never the artifact's possibly-stale weights
        from ....distributed.checkpoint.manager import CheckpointManager

        mgr = CheckpointManager(root)
        if (mgr.latest_healthy_step() is not None
                or mgr.latest_valid_step() is not None):
            reloaded = eng.reload_weights(mgr)
    hb_dir = cfg.get("hb_dir")
    hb.write(step=0, dir=hb_dir, rank=replica_id)
    _emit({"e": "ready", "replica": replica_id,
           "incarnation": int(os.environ.get(ENV_INCARNATION, "0") or 0),
           "reloaded_step": reloaded})

    cmd_q: queue.Queue = queue.Queue()

    def _reader():
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                cmd_q.put(json.loads(line))
            except ValueError:
                continue
        cmd_q.put({"op": "shutdown"})  # EOF: the router is gone

    threading.Thread(target=_reader, daemon=True).start()

    rid_of = {}   # gid -> engine rid
    meta = {}     # gid -> {"gen": k}
    steps = 0
    shutting = False

    def _handle(cmd):
        nonlocal shutting
        op = cmd.get("op")
        if op == "submit":
            gid = cmd["gid"]
            try:
                rid = eng.add_request(
                    np.asarray(cmd["prompt"], np.int32),
                    SamplingParams(max_new_tokens=int(cmd["max_new"]),
                                   eos_token_id=cmd.get("eos")),
                    deadline=cmd.get("deadline"))
            except RequestTimeoutError:
                _emit({"e": "tok", "gid": gid, "gen": cmd.get("gen", 0),
                       "toks": [], "fin": True, "reason": "timeout"})
                return
            except Exception as ex:  # typed errors -> router surfaces
                _emit({"e": "err", "gid": gid,
                       "kind": type(ex).__name__, "msg": str(ex)})
                return
            rid_of[gid] = rid
            meta[gid] = {"gen": cmd.get("gen", 0)}
        elif op == "cancel":
            gid = cmd["gid"]
            rid = rid_of.get(gid)
            if rid is not None:
                eng.cancel(rid, reason=cmd.get("reason", "cancelled"))
                # cancelled requests emit no fin event — drop the
                # bookkeeping here or it grows for the server's life
                rid_of.pop(gid, None)
                meta.pop(gid, None)
                eng.release(rid)
        elif op == "reload":
            from ....distributed.checkpoint.manager import CheckpointManager

            step = eng.reload_weights(CheckpointManager(cmd["root"]))
            _emit({"e": "reloaded", "replica": replica_id, "step": step})
        elif op == "stats":
            s = eng.stats()
            _emit({"e": "stats", "replica": replica_id,
                   "blocks_free": s["blocks_free"],
                   "blocks_high_water": s["blocks_high_water"],
                   "waiting": s["waiting"], "running": s["running"],
                   "steps": s["steps"], "tokens_out": s["tokens_out"]})
        elif op == "shutdown":
            shutting = True

    gid_by_rid = {}
    # heartbeat/load-report throttles: an atomic file replace and a JSON
    # line per ~1ms engine step is pure overhead — the watchdog judges
    # in seconds and the router's load signal tolerates 100ms staleness
    last_hb = [0.0]
    last_load = [0.0]

    def _beat():
        now = time.monotonic()
        if now - last_hb[0] >= 0.25:
            last_hb[0] = now
            hb.write(step=steps, dir=hb_dir, rank=replica_id)

    while True:
        # chaos probes count BUSY ticks only: a crash/hang while idle
        # exercises nothing — the interesting failure is mid-serve, with
        # in-flight requests for the router to recover
        if eng.has_work():
            if fi.should_fire("serve.replica_crash"):
                os.kill(os.getpid(), signal.SIGKILL)
            if fi.should_fire("serve.replica_hang"):
                while True:  # wedged: no heartbeat, no service, no exit
                    time.sleep(3600)
        try:
            cmd = (cmd_q.get_nowait() if eng.has_work() or shutting
                   else cmd_q.get(timeout=0.05))
        except queue.Empty:
            cmd = None
        while cmd is not None:
            _handle(cmd)
            try:
                cmd = cmd_q.get_nowait()
            except queue.Empty:
                cmd = None
        if eng.has_work():
            gid_by_rid = {rid: gid for gid, rid in rid_of.items()}
            per_gid = {}
            for out in eng.step():
                gid = gid_by_rid.get(out.rid)
                if gid is None:
                    continue
                rec = per_gid.setdefault(
                    gid, {"toks": [], "fin": False, "reason": None})
                if out.token >= 0:
                    rec["toks"].append(int(out.token))
                if out.finished:
                    rec["fin"] = True
                    rec["reason"] = out.finish_reason
            for gid, rec in per_gid.items():
                _emit({"e": "tok", "gid": gid, "gen": meta[gid]["gen"],
                       "toks": rec["toks"], "fin": rec["fin"],
                       "reason": rec["reason"]})
                if rec["fin"]:
                    rid = rid_of.pop(gid)
                    meta.pop(gid, None)
                    eng.release(rid)
            now = time.monotonic()
            if now - last_load[0] >= 0.1:
                last_load[0] = now
                m = eng.metrics()
                _emit({"e": "load", "replica": replica_id,
                       "kv": m["kv_block_utilization"] or 0.0,
                       "occ": m["decode_batch_occupancy"] or 0.0,
                       "waiting": len(eng.scheduler.waiting)})
        steps += 1
        _beat()
        if shutting and not eng.has_work():
            eng.close()
            _emit({"e": "bye", "replica": replica_id})
            return 0


if __name__ == "__main__":
    sys.exit(replica_worker_main())
