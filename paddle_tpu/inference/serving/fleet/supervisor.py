"""Replica process supervision (ISSUE 12 tentpole, part a).

``ReplicaSupervisor`` is the serving-side twin of the training
launcher's ``CollectiveController`` (PR 4): it spawns N replica worker
processes (``fleet.replica``, each owning one ``LLMEngine`` over a
shared model artifact) and keeps them alive:

* **Crash**: a replica exiting for any reason (SIGKILL'd by the OOM
  killer, a real crash, a chaos drill) is detected by ``check()`` and
  respawned under a per-replica leaky-bucket
  :class:`~paddle_tpu.distributed.launch.controllers.collective.RestartBudget`
  — the SAME budget/backoff machinery the training launcher uses, with
  a typed :class:`~..errors.ReplicaCrashLoopError` once a slot's budget
  is exhausted (a poisoned replica must not flap forever).
* **Hang**: replicas heartbeat through ``distributed.launch.heartbeat``
  (atomic ``hb.<replica>`` files, written at every engine ``step()``
  boundary and on idle ticks); a heartbeat older than
  ``hang_timeout_s`` triggers the SIGTERM→SIGKILL escalation and the
  replica is restarted like a crash — a worker wedged in a compile or a
  device call cannot silently hold its share of the fleet.
* **Rejoin**: a restarted replica reloads weights from the fleet's
  checkpoint root (``reload_weights(latest_healthy_step())`` inside the
  worker) before reporting ready, so a crash during a rolling weight
  update cannot resurrect stale weights.

The supervisor only manages processes; request-level recovery
(redispatching the dead replica's in-flight requests) is the Router's
job — ``check()`` hands it the death events WITH the dying process's
final token events (drained to EOF first), so tokens emitted before the
crash are never lost and never double-counted.

ISSUE 17 adds **fleet autoscaling**: :meth:`ReplicaSupervisor.autoscale`
is a pure decision tick driven by the router's ``fleet_queue_depth`` and
occupancy gauges — sustained pressure above the high watermark grows the
fleet by one slot (:meth:`add_replica`), calm below the low watermark
nominates the highest live slot for the caller to drain-then-retire
(riding the PR-12 zero-drop drain; the supervisor never kills a slot
that may hold in-flight work). Hysteresis (distinct watermarks + a
cooldown between events) and a leaky-bucket scale-event budget (the
:class:`RestartBudget` machinery again) keep flapping load from
crash-looping the fleet through churn.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import warnings

from ....distributed.launch import heartbeat as _hb
from ....distributed.launch.controllers.collective import RestartBudget
from ....observability import metrics as _obs_metrics
from ..errors import ReplicaCrashLoopError

__all__ = ["ReplicaHandle", "ReplicaSupervisor"]

# fleet liveness (ISSUE 12): how many replicas look alive RIGHT NOW —
# process running and (when the hang watchdog is armed) heartbeat fresh.
# Transitions are appended to <log_dir>/fleet_liveness.log so the chaos
# drill can assert the gauge dipped during a kill/hang and recovered.
_G_LIVE = _obs_metrics.gauge(
    "fleet_replicas_live",
    "replicas currently alive (process running + heartbeat fresh when "
    "the hang watchdog is armed)")
_M_RESTARTS = _obs_metrics.counter(
    "fleet_replica_restarts_total",
    "replica respawns performed by the supervisor (crash or hang)")
_M_SCALE_UP = _obs_metrics.counter(
    "fleet_scale_up_total",
    "replicas added by autoscale (queue pressure above the high "
    "watermark past the cooldown)")
_M_SCALE_DOWN = _obs_metrics.counter(
    "fleet_scale_down_total",
    "replicas nominated for drain-then-retire by autoscale (fleet calm "
    "below the low watermark past the cooldown)")
# model-parallel replica groups (ISSUE 19): per-replica member liveness
# and whole-group restarts. A group is atomic — members_live < group_size
# is a transient state the supervisor resolves by felling the whole
# group, never a serving state.
_G_GROUP_MEMBERS = _obs_metrics.gauge(
    "fleet_group_members_live",
    "processes of this replica group currently running (a value below "
    "the group size means the group is being felled or respawned — a "
    "partial group never serves)")
_M_GROUP_RESTARTS = _obs_metrics.counter(
    "fleet_group_restarts_total",
    "whole-group respawns performed by the supervisor (any member "
    "crash/hang fells and restarts the entire group, charging ONE "
    "restart-budget slot)")

# repo root (five levels up: fleet/serving/inference/paddle_tpu/<repo>)
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))))

ENV_ID = "PADDLE_REPLICA_ID"
ENV_CONFIG = "PADDLE_REPLICA_CONFIG"
ENV_INCARNATION = "PADDLE_REPLICA_INCARNATION"
# model-parallel replica groups (ISSUE 19)
ENV_GROUP_SIZE = "PADDLE_REPLICA_GROUP_SIZE"
ENV_GROUP_RANK = "PADDLE_REPLICA_GROUP_RANK"
ENV_COORD_PORT = "PADDLE_REPLICA_COORD_PORT"


def _free_port():
    """A currently free TCP port for an incarnation's private
    coordination service (racy-but-fine: the group binds it within
    milliseconds, and a collision just fails the boot — which the
    watchdog turns into an ordinary group restart on a NEW port)."""
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


class ReplicaHandle:
    """One replica worker process + its line-JSON RPC plumbing.

    Commands go down the child's stdin (one JSON object per line);
    events come back on stdout, pumped by a daemon reader thread into an
    internal queue that :meth:`events` drains. stderr goes to a per-
    replica log file (jax chatter must never corrupt the RPC stream).

    ``group_size > 1`` (ISSUE 19) makes the handle a multi-process
    GROUP: rank 0 keeps the RPC pipes (``proc``/``pid`` stay rank 0, so
    the router's one-handle-one-target view is unchanged) and ranks 1+
    are spawned headless (stdin ``/dev/null``, stdout+stderr to their
    own log). The group is ATOMIC: :attr:`alive` demands every member
    running, and :meth:`kill` fells them all — a half-dead tp group must
    never answer.
    """

    def __init__(self, replica_id, config, *, env=None, log_path=None,
                 incarnation=0, group_size=1, coord_port=None):
        self.id = int(replica_id)
        self.incarnation = int(incarnation)
        self.group_size = int(group_size)
        self.coord_port = coord_port
        self.spawn_time = time.time()
        self.ready = False
        self.ready_info = None
        self.retired = False
        self._lock = threading.Lock()
        self._events: list = []
        self._log_file = open(log_path, "ab") if log_path else None
        self._member_logs = []
        child_env = dict(env if env is not None else os.environ)
        child_env[ENV_ID] = str(self.id)
        child_env[ENV_CONFIG] = json.dumps(config)
        child_env[ENV_INCARNATION] = str(self.incarnation)
        child_env["PYTHONPATH"] = (_REPO + os.pathsep
                                   + child_env.get("PYTHONPATH", ""))
        if self.group_size > 1:
            child_env[ENV_GROUP_SIZE] = str(self.group_size)
            child_env[ENV_COORD_PORT] = str(coord_port)
            child_env[ENV_GROUP_RANK] = "0"
        self.proc = subprocess.Popen(
            [sys.executable, "-u", "-m",
             "paddle_tpu.inference.serving.fleet.replica"],
            env=child_env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=(self._log_file or subprocess.DEVNULL), text=True,
            bufsize=1)
        # ranks 1+: same engine in SPMD lockstep, no RPC stream — their
        # stdout would corrupt nothing, but it belongs in a log
        self.members = []
        for rank in range(1, self.group_size):
            member_env = dict(child_env)
            member_env[ENV_GROUP_RANK] = str(rank)
            mlog = (open(f"{log_path}.r{rank}", "ab") if log_path
                    else None)
            self._member_logs.append(mlog)
            self.members.append(subprocess.Popen(
                [sys.executable, "-u", "-m",
                 "paddle_tpu.inference.serving.fleet.replica"],
                env=member_env, stdin=subprocess.DEVNULL,
                stdout=(mlog or subprocess.DEVNULL),
                stderr=(mlog or subprocess.DEVNULL)))
        self._reader = threading.Thread(target=self._read, daemon=True,
                                        name=f"replica{self.id}-reader")
        self._reader.start()

    def _read(self):
        try:
            for line in self.proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue  # stray non-RPC print; never kill the reader
                with self._lock:
                    self._events.append(ev)
        except (OSError, ValueError):
            pass

    @property
    def alive(self):
        """Every member running (group-atomic: a group missing ANY
        member must not look placeable)."""
        return (not self.retired and self.proc.poll() is None
                and all(m.poll() is None for m in self.members))

    @property
    def pid(self):
        return self.proc.pid

    @property
    def members_live(self):
        """Running member processes (rank 0 included) — the
        ``fleet_group_members_live`` gauge."""
        n = 1 if self.proc.poll() is None else 0
        return n + sum(1 for m in self.members if m.poll() is None)

    def dead_member(self):
        """``(rank, rc)`` of the first exited member, or ``None`` when
        all are running — the supervisor's group-crash probe, naming the
        failing rank for the crash-loop error."""
        if self.proc.poll() is not None:
            return 0, self.proc.poll()
        for rank, m in enumerate(self.members, start=1):
            if m.poll() is not None:
                return rank, m.poll()
        return None

    def send(self, obj):
        """Write one command line; False when the pipe is gone (the
        caller treats it as a dead replica and redispatches)."""
        try:
            with self._lock:
                self.proc.stdin.write(json.dumps(obj) + "\n")
                self.proc.stdin.flush()
            return True
        except (OSError, ValueError, AttributeError):
            return False

    def events(self):
        """Drain queued events (ready events also flip :attr:`ready`)."""
        with self._lock:
            out, self._events = self._events, []
        for ev in out:
            if ev.get("e") == "ready":
                self.ready = True
                self.ready_info = ev
        return out

    def push_back(self, evs):
        """Requeue events at the front (``wait_ready`` peeks without
        consuming the router's view of the stream)."""
        with self._lock:
            self._events = list(evs) + self._events

    def final_events(self, timeout=2.0):
        """Join the reader (EOF after death) and drain what's left —
        tokens the replica emitted before dying must reach the router."""
        self._reader.join(timeout=timeout)
        return self.events()

    def kill(self, grace_s=5.0):
        """SIGTERM → wait ``grace_s`` → SIGKILL (the launcher's
        escalation) — applied to EVERY group member: survivors of a
        partial failure are felled, never left to answer. SIGTERM goes
        to all members first so the grace window is shared, not
        per-process."""
        procs = [self.proc] + list(self.members)
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.time() + float(grace_s)
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(deadline - time.time(), 0.0))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
        for f in [self._log_file] + self._member_logs:
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
        self._log_file = None
        self._member_logs = []

    def close(self):
        """Polite shutdown: ask, wait briefly, then escalate."""
        self.send({"op": "shutdown"})
        try:
            self.proc.wait(timeout=3.0)
        except subprocess.TimeoutExpired:
            pass
        self.kill(grace_s=1.0)


class ReplicaSupervisor:
    """Spawn + watch ``n_replicas`` replica workers (see module doc)."""

    def __init__(self, n_replicas, config, *, hang_timeout_s=0.0,
                 max_restarts=3, term_grace_s=5.0, boot_grace_s=120.0,
                 log_dir=None, env_extra=None, instance="fleet",
                 roles=None, group_size=1):
        if int(n_replicas) < 1:
            raise ValueError("n_replicas must be >= 1")
        # model-parallel replica groups (ISSUE 19): every slot is a
        # group of `group_size` processes serving ONE plan-sharded
        # engine in SPMD lockstep (group_size=1 is the exact PR-12
        # single-process replica, byte-for-byte)
        self.group_size = int(group_size)
        if self.group_size < 1:
            raise ValueError("group_size must be >= 1")
        if self.group_size > 1 and roles is not None \
                and any(r == "prefill" for r in roles):
            raise ValueError(
                "prefill-role slots cannot be multi-process groups: the "
                "disaggregated handoff exports KV pages to one host, "
                "which a process-spanning plan does not support yet")
        # role-disaggregated serving (ISSUE 15): each slot is "prefill",
        # "decode" or "both" (the colocated default). The role is part of
        # the SLOT, not the incarnation — a restarted replica respawns
        # with the same role, so a crash can never silently turn a
        # prefill worker into a decode worker.
        if roles is not None:
            roles = [str(r) for r in roles]
            if len(roles) != int(n_replicas):
                raise ValueError(
                    f"roles has {len(roles)} entries for {n_replicas} "
                    "replicas")
            bad = [r for r in roles if r not in ("prefill", "decode",
                                                 "both")]
            if bad:
                raise ValueError(f"unknown replica roles {bad}; expected "
                                 "'prefill', 'decode' or 'both'")
        self._roles = roles
        self.instance = instance
        self.hang_timeout_s = float(hang_timeout_s or 0.0)
        self.term_grace_s = float(term_grace_s)
        # a replica writes its first heartbeat only after the framework
        # import + engine build, so a booting (not-yet-ready) replica is
        # judged against this LONGER grace — otherwise a tight watchdog
        # condemns every restart before it can possibly beat, and the
        # budget drains on phantom hangs (the launch bootstrap solves
        # this with a pre-jax heartbeat; here the import IS the boot).
        # Groups boot slower still — collective jax.distributed
        # rendezvous + plan-sharded weight commit + an all-ranks warmup
        # barrier — so the grace SCALES with the group size (the PR-12
        # boot_grace_s lesson, re-proven for groups: a phantom boot hang
        # must never drain the restart budget)
        self.boot_grace_s = (max(float(boot_grace_s), self.hang_timeout_s)
                             * max(1, self.group_size))
        self.log_dir = log_dir
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._hb_dir = os.path.join(log_dir, "heartbeats")
        else:
            self._hb_dir = tempfile.mkdtemp(prefix="paddle_fleet_hb.")
        os.makedirs(self._hb_dir, exist_ok=True)
        self._config = dict(config)
        self._config["hb_dir"] = self._hb_dir
        self._env = dict(os.environ)
        self._env.pop("PALLAS_AXON_POOL_IPS", None)  # never grab the TPU
        # replicas default to the CPU backend: N extra processes fighting
        # over one accelerator is never what a test/drill wants; a real
        # deployment overrides via env_extra
        self._env.setdefault("JAX_PLATFORMS", "cpu")
        self._env.update(env_extra or {})
        # sleep=no-op: backoff() only COMPUTES the delay — the supervisor
        # schedules the respawn at now+delay instead of sleeping inside
        # the router's single-threaded pump (a synchronous backoff sleep
        # would freeze token events, placements and the redispatch the
        # death just triggered, for every healthy replica too)
        self._max_restarts = int(max_restarts)
        self._budgets = [RestartBudget(max_restarts, sleep=lambda s: None)
                         for _ in range(int(n_replicas))]
        self._pending_respawn: dict[int, float] = {}
        self.handles = [self._spawn(i, 0) for i in range(int(n_replicas))]
        self._last_live = None
        # autoscale state (ISSUE 17): budget created lazily at the first
        # autoscale() tick (its shape is a caller decision)
        self._scale_budget = None
        self._last_scale_t = None
        self._scale_warned = False
        self._note_liveness()

    # -- lifecycle -------------------------------------------------------
    def role(self, i):
        """The slot's serving role ("both" when undeclared)."""
        return self._roles[i] if self._roles else "both"

    def _spawn(self, i, incarnation):
        log_path = (os.path.join(self.log_dir, f"replica.{i}.log")
                    if self.log_dir else None)
        config = self._config
        if self._roles is not None:
            config = dict(config, role=self._roles[i])
        # fresh coordination port per incarnation: a respawned group's
        # rendezvous must never reach a predecessor's half-dead service
        port = _free_port() if self.group_size > 1 else None
        h = ReplicaHandle(i, config, env=self._env,
                          log_path=log_path, incarnation=incarnation,
                          group_size=self.group_size, coord_port=port)
        h.role = self.role(i)
        return h

    def wait_ready(self, timeout=180.0):
        """Block until every live replica reported ``ready`` (engine
        built, weights loaded/reloaded). Peeked events are pushed back
        for the router's pump."""
        deadline = time.time() + float(timeout)
        for h in self.handles:
            while not h.ready and not h.retired:
                evs = h.events()
                if evs:
                    h.push_back(evs)
                if h.ready:
                    break
                dead = h.dead_member()
                if dead is not None:
                    rank, rc = dead
                    raise RuntimeError(
                        f"replica {h.id} (group rank {rank}) died "
                        f"during startup (rc={rc}); see its log"
                        + (f" in {self.log_dir}" if self.log_dir else ""))
                if time.time() > deadline:
                    raise TimeoutError(
                        f"replica {h.id} not ready within {timeout}s")
                time.sleep(0.05)

    def retire(self, i):
        """Permanently stop replica ``i`` (the drain-then-retire path) —
        no restart, excluded from liveness."""
        h = self.handles[i]
        h.retired = True
        h.close()
        self._note_liveness()

    def shutdown(self):
        for h in self.handles:
            if not h.retired:
                h.close()
        _G_LIVE.remove(instance=self.instance)
        _M_RESTARTS.remove(instance=self.instance)
        _M_SCALE_UP.remove(instance=self.instance)
        _M_SCALE_DOWN.remove(instance=self.instance)
        if self.group_size > 1:
            _M_GROUP_RESTARTS.remove(instance=self.instance)
            for h in self.handles:
                _G_GROUP_MEMBERS.remove(instance=self.instance,
                                        replica=h.id)

    # -- fleet autoscaling (ISSUE 17) -----------------------------------
    @property
    def n_active(self):
        """Slots not retired (live, booting, or pending respawn) — the
        fleet size autoscale reasons about."""
        return sum(1 for h in self.handles if not h.retired)

    def add_replica(self, role="both"):
        """Grow the fleet by one slot (the autoscale-up action). The new
        slot appends at the end — slot id == handles index stays true for
        every existing slot — with a fresh restart budget and incarnation
        0. Returns the new slot id."""
        i = len(self.handles)
        if self._roles is not None:
            role = str(role)
            if role not in ("prefill", "decode", "both"):
                raise ValueError(f"unknown replica role {role!r}")
            self._roles.append(role)
        self._budgets.append(
            RestartBudget(self._max_restarts, sleep=lambda s: None))
        self.handles.append(self._spawn(i, 0))
        _M_SCALE_UP.inc(instance=self.instance)
        self._note_liveness()
        return i

    def autoscale(self, min_replicas, max_replicas, *, queue_depth,
                  occupancy, high_water=0.75, low_water=0.25,
                  cooldown_s=5.0, max_events=8, window_s=60.0, now=None):
        """One autoscale decision tick, driven by the router's gauges:
        ``queue_depth`` (requests waiting at the router) and
        ``occupancy`` (mean decode-slot occupancy across live replicas,
        0..1).

        * **Up** — work is queued AND the fleet is busy (``occupancy >=
          high_water``) with room to grow: spawn one replica
          (:meth:`add_replica`) and return ``("up", new_id)``.
        * **Down** — nothing queued AND the fleet is idle (``occupancy
          <= low_water``) above the floor: return ``("down",
          victim_id)`` nominating the highest live slot; the CALLER
          drains it (zero-drop) and calls :meth:`retire` — the
          supervisor never kills a slot that may hold in-flight work.
        * Otherwise (or inside the hysteresis band / cooldown / an
          exhausted scale-event budget) return ``None``.

        Hysteresis is the gap between the watermarks plus ``cooldown_s``
        between events; the leaky-bucket scale-event budget
        (``max_events`` per rolling ``window_s``, fixed at the first
        tick) stops flapping load from churning replicas forever — past
        it, autoscale goes quiet (one warning) instead of crash-looping
        the fleet."""
        min_replicas, max_replicas = int(min_replicas), int(max_replicas)
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min ({min_replicas}) <= max ({max_replicas})")
        if not low_water < high_water:
            raise ValueError(
                f"need low_water ({low_water}) < high_water "
                f"({high_water}) — the gap IS the hysteresis band")
        now = time.time() if now is None else now
        n = self.n_active
        want_up = (queue_depth > 0 and occupancy >= high_water
                   and n < max_replicas)
        want_down = (queue_depth == 0 and occupancy <= low_water
                     and n > min_replicas)
        if not (want_up or want_down):
            return None
        if (self._last_scale_t is not None
                and now - self._last_scale_t < cooldown_s):
            return None
        if self._scale_budget is None:
            self._scale_budget = RestartBudget(
                int(max_events), window_s=float(window_s),
                sleep=lambda s: None)
        if not self._scale_budget.try_acquire():
            if not self._scale_warned:
                self._scale_warned = True
                warnings.warn(
                    f"{self.instance}: scale-event budget exhausted "
                    f"({self._scale_budget.max_restarts} per "
                    f"{self._scale_budget.window_s:.0f}s); autoscale "
                    "pausing — flapping load, widen the watermarks",
                    RuntimeWarning)
            return None
        self._last_scale_t = now
        if want_up:
            return ("up", self.add_replica())
        victim = max(h.id for h in self.handles if not h.retired)
        _M_SCALE_DOWN.inc(instance=self.instance)
        return ("down", victim)

    # -- the watchdog tick ----------------------------------------------
    def _hung(self, h, beats, now):
        if self.hang_timeout_s <= 0 or not h.alive:
            return False
        if not h.ready:
            # still booting: only the boot grace can condemn it
            return (now - h.spawn_time) > self.boot_grace_s
        if getattr(h, "group_size", 1) > 1:
            # groups run in SPMD lockstep, so ONE wedged rank stalls
            # every member's next collective: judge the group by its
            # STALEST member's hb.<replica>.<rank> heartbeat
            ts = []
            for r in range(h.group_size):
                t = beats.get(f"{h.id}.{r}", {}).get("time")
                ts.append(h.spawn_time if t is None else float(t))
            return (now - min(ts)) > self.hang_timeout_s
        t = beats.get(str(h.id), {}).get("time")
        if t is None:
            t = h.spawn_time  # not-yet-written grace, like launch.stale
        return (now - float(t)) > self.hang_timeout_s

    def check(self, now=None):
        """One supervision tick. Detects dead and hung replicas, kills
        the hung ones, respawns both under the per-replica restart
        budget, and returns the death events for the router::

            [{"replica": i, "reason": "crash"|"hang", "rc": rc,
              "events": [<final events drained after EOF>]}]

        Raises :class:`ReplicaCrashLoopError` when a slot's budget is
        exhausted. Also refreshes the ``fleet_replicas_live`` gauge
        (transition log: ``<log_dir>/fleet_liveness.log``)."""
        now = time.time() if now is None else now
        beats = _hb.read_all(self._hb_dir)
        deaths = []
        for i, h in enumerate(self.handles):
            if h.retired:
                continue
            if i in self._pending_respawn:
                # death already reported; respawn when the backoff lapses
                if now >= self._pending_respawn[i]:
                    del self._pending_respawn[i]
                    # stale heartbeats must not re-condemn the new life
                    # (hb.<i> and every group member's hb.<i>.<rank>)
                    self._clear_heartbeats(i)
                    self.handles[i] = self._spawn(i, h.incarnation + 1)
                    _M_RESTARTS.inc(instance=self.instance)
                    if self.group_size > 1:
                        _M_GROUP_RESTARTS.inc(instance=self.instance)
                continue
            reason = None
            rank = None
            dead = (h.dead_member() if hasattr(h, "dead_member")
                    else ((0, h.proc.poll())
                          if h.proc.poll() is not None else None))
            if dead is not None:
                # ANY member exiting fells the WHOLE group atomically: a
                # half-dead tp group must never answer — survivors are
                # SIGTERM→SIGKILL'd before the death is even reported
                reason = "crash"
                rank, _ = dead
                h.kill(grace_s=self.term_grace_s)
            elif self._hung(h, beats, now):
                reason = "hang"
                h.kill(grace_s=self.term_grace_s)
            if reason is None:
                continue
            rc = dead[1] if dead is not None else h.proc.poll()
            leftovers = h.final_events()
            # the dip must be visible BEFORE the respawn restores it
            self._note_liveness()
            budget = self._budgets[i]
            if not budget.try_acquire():
                self.shutdown()
                at_rank = f" at group rank {rank}" if rank else ""
                raise ReplicaCrashLoopError(
                    f"replica {i} crash loop ({reason}{at_rank}, "
                    f"rc={rc}): restart budget exhausted "
                    f"({budget.max_restarts} per "
                    f"{budget.window_s:.0f}s window, "
                    f"{budget.total_restarts} performed)",
                    replica=i, exit_code=rc if rc is not None else 1,
                    restarts=budget.total_restarts)
            # schedule (never sleep in the pump): the death event returns
            # NOW so the router redispatches immediately; the slot stays
            # un-placeable (dead handle) until the delayed respawn
            self._pending_respawn[i] = now + budget.backoff()
            deaths.append({"replica": i, "reason": reason, "rc": rc,
                           "rank": rank, "events": leftovers})
        self._note_liveness(beats=beats, now=now)
        return deaths

    def quarantine(self, i, now=None):
        """Integrity quarantine (ISSUE 20): kill replica ``i`` NOW —
        group-atomic, exactly like a watchdog kill — charge ONE restart-
        budget slot and schedule the respawn through the normal
        ``_pending_respawn`` path (so a supervision tick racing this
        call can never double-restart the slot: ``check`` skips slots
        already pending). Returns the death dict (``reason:
        "quarantine"``) for the router to replay/redispatch from, or
        ``None`` when the slot is retired / already dying. Raises
        :class:`ReplicaCrashLoopError` when the budget is exhausted —
        a replica that keeps corrupting after restarts is poisoned
        hardware, not bad luck."""
        now = time.time() if now is None else now
        h = self.handles[i]
        if h.retired or i in self._pending_respawn:
            return None
        # no SIGTERM grace: a corrupt replica must stop emitting tokens
        # immediately, not drain them
        h.kill(grace_s=0.0)
        rc = h.proc.poll()
        leftovers = h.final_events()
        self._note_liveness()  # the dip precedes the respawn
        budget = self._budgets[i]
        if not budget.try_acquire():
            self.shutdown()
            raise ReplicaCrashLoopError(
                f"replica {i} quarantine loop: restart budget exhausted "
                f"({budget.max_restarts} per {budget.window_s:.0f}s "
                f"window, {budget.total_restarts} performed) — the slot "
                "keeps serving corrupt output; suspect the hardware",
                replica=i, exit_code=rc if rc is not None else 1,
                restarts=budget.total_restarts)
        self._pending_respawn[i] = now + budget.backoff()
        return {"replica": i, "reason": "quarantine", "rc": rc,
                "rank": None, "events": leftovers}

    def _clear_heartbeats(self, i):
        """Remove slot ``i``'s heartbeat files — the bare ``hb.<i>`` and
        every group member's ``hb.<i>.<rank>``."""
        for r in [None] + list(range(self.group_size)):
            fn = f"hb.{i}" if r is None else f"hb.{i}.{r}"
            try:
                os.remove(os.path.join(self._hb_dir, fn))
            except OSError:
                pass

    def _note_liveness(self, beats=None, now=None):
        now = time.time() if now is None else now
        if beats is None:
            beats = _hb.read_all(self._hb_dir)
        n = sum(1 for h in self.handles
                if h.alive and not self._hung(h, beats, now))
        _G_LIVE.set(n, instance=self.instance)
        if self.group_size > 1:
            for h in self.handles:
                _G_GROUP_MEMBERS.set(
                    0 if h.retired else h.members_live,
                    instance=self.instance, replica=h.id)
        if n != self._last_live:
            self._last_live = n
            if self.log_dir:
                try:
                    with open(os.path.join(self.log_dir,
                                           "fleet_liveness.log"), "a") as f:
                        f.write(f"{now:.3f} {n}\n")
                except OSError:
                    pass
        return n
