"""CRC-framed KV-page transfer format (ISSUE 15): the ONE
implementation shared by the prefill worker (emit), the router
(verify + forward) and the decode worker (verify + join). The format
is deliberately line-JSON-friendly — raw page bytes are split into
``FRAME_BYTES`` chunks, each carried base64-encoded beside the
zlib.crc32 of the RAW chunk, with a whole-payload CRC checked after
the join — so one future change to the frame shape cannot silently
desynchronize an emitter from a verifier.
"""

from __future__ import annotations

import base64
import os
import zlib

__all__ = ["FRAME_BYTES", "split_frames", "encode_frame", "decode_frame",
           "join_frames"]

# Raw payload bytes per frame. Small enough that a mid-transfer kill
# genuinely interrupts a handoff, large enough that base64+JSON
# overhead stays negligible; drills shrink it via env to force
# multi-frame transfers on tiny models.
FRAME_BYTES = int(os.environ.get("PADDLE_KV_FRAME_BYTES", "65536") or
                  65536)


def split_frames(blob, frame_bytes=None):
    """``blob`` as a list of raw chunks of at most ``frame_bytes``."""
    n = int(frame_bytes or FRAME_BYTES)
    return [blob[i:i + n] for i in range(0, len(blob), n)]


def encode_frame(chunk, corrupt=False):
    """``{"crc", "data"}`` fields for one raw chunk. ``corrupt=True``
    (the ``serve.kv_transfer_corrupt`` fault site) flips bits AFTER the
    CRC was computed, so the receiver's check must catch exactly this."""
    data = chunk
    if corrupt and data:
        data = bytes([data[0] ^ 0xFF]) + data[1:]
    return {"crc": zlib.crc32(chunk),
            "data": base64.b64encode(data).decode()}


def decode_frame(ev):
    """The raw chunk bytes of one frame event/command, or ``None`` when
    the payload is undecodable or fails its CRC — the caller treats
    either as a corrupt transfer."""
    try:
        chunk = base64.b64decode(ev.get("data") or "")
    except (ValueError, TypeError):
        return None
    if zlib.crc32(chunk) != ev.get("crc"):
        return None
    return chunk


def join_frames(frames, total, crc):
    """Reassemble ``{seq: chunk}`` into ``(blob, None)``, or
    ``(None, why)`` when frames are missing or the whole-payload CRC
    disagrees."""
    total = int(total)
    got = sum(1 for i in range(total) if i in frames)
    if got != total:
        return None, f"only {got}/{total} frames arrived"
    blob = b"".join(frames[i] for i in range(total))
    if total and zlib.crc32(blob) != crc:
        return None, "payload CRC mismatch"
    return blob, None
