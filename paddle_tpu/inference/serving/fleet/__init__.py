"""paddle.inference.serving.fleet — fault-tolerant serving fleet
(ISSUE 12).

The layer above ``LLMEngine`` that the "millions of users" north star
needs: N replica worker processes (``replica``) supervised PR-4-style
(``supervisor``: heartbeats, hang watchdog with SIGTERM→SIGKILL
escalation, leaky-bucket restart budget, checkpoint rejoin) behind a
front-end ``Router`` (least-loaded + session-affinity dispatch,
per-request deadlines, bounded admission with load shedding, redispatch
of in-flight requests off dead replicas, graceful drain for zero-drop
rolling updates). See DESIGN_DECISIONS.md "Serving fleet supervision &
redispatch" and ``scripts/chaos_serve.py`` — the acceptance drill.
"""

from ..errors import (  # noqa: F401
    EngineClosedError, FleetOverloadedError, KVTransferError,
    ReplicaCrashLoopError, RequestTimeoutError,
)
from .supervisor import ReplicaHandle, ReplicaSupervisor  # noqa: F401
from .router import FleetRequest, Router  # noqa: F401

__all__ = [
    "Router", "FleetRequest", "ReplicaSupervisor", "ReplicaHandle",
    "RequestTimeoutError", "FleetOverloadedError", "EngineClosedError",
    "ReplicaCrashLoopError", "KVTransferError",
]
