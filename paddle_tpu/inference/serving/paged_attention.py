"""Paged decode attention — kernel routing + pure-``lax`` fallback.

The serving engine's decode step calls :func:`paged_decode_attention` once
per layer inside its compiled graph. On TPU (or in Pallas interpret mode)
it routes to the Pallas kernel in ``ops/pallas/paged_attention.py``; on
CPU it runs the pure-``lax`` fallback below — a gather of each request's
pages out of the pool followed by a masked dense attention — which is the
numerical reference the kernel (and the tests) are matched against.

CPU-fallback contract (see DESIGN_DECISIONS.md): same signature, same
ragged-length semantics, outputs matched to the dense llama attention —
only the memory-traffic shape differs (the fallback materializes the
gathered [B, P*block, Hkv, D] view; the kernel never does).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from ...nn.functional.flash_attention import _sdpa_ref

__all__ = ["paged_decode_attention", "paged_multiquery_attention"]


def _gather_kv(pool, scale_pool, block_tables):
    """Gather a request-major [B, P*block, Hkv, D] view of the pool,
    dequantizing int8 codes with their per-row scales when a scale pool
    is given — the SAME ``codes * scale`` multiply the Pallas kernel
    does in VMEM, just materialized (this is the fallback's documented
    memory-traffic difference)."""
    b, p = block_tables.shape
    n, block_size, hkv, d = pool.shape
    g = pool[block_tables].reshape(b, p * block_size, hkv, d)
    if scale_pool is not None:
        s = scale_pool[block_tables].reshape(b, p * block_size, hkv)
        g = g.astype(jnp.float32) * s[..., None]
    return g


def _lax_fallback(q, k_pool, v_pool, block_tables, context_lens, scale,
                  k_scale=None, v_scale=None):
    """q [B, 1, H, D] -> [B, 1, H, D] via gather + masked dense sdpa."""
    b, p = block_tables.shape
    block_size = k_pool.shape[1]
    k = _gather_kv(k_pool, k_scale, block_tables)
    v = _gather_kv(v_pool, v_scale, block_tables)
    pos = jnp.arange(p * block_size, dtype=jnp.int32)[None, :]
    mask = (pos < context_lens[:, None])[:, None, None, :]  # [B,1,1,S]
    return _sdpa_ref.raw_fn(q, k, v, attn_mask=mask, scale=scale)


def paged_decode_attention(q, k_pool, v_pool, block_tables, context_lens,
                           scale=None, k_scale=None, v_scale=None):
    """One decode token per request against the paged pool.

    q: [B, 1, H, D] (the just-written token's query); pools
    [N, block, Hkv, D]; block_tables [B, P] int32; context_lens [B] int32
    counting tokens INCLUDING the one just written. Returns [B, 1, H, D].
    ``k_scale``/``v_scale`` ([N, block, Hkv] f32) arm the int8
    dequant-in-kernel path (ISSUE 14) when the pools hold codes.
    """
    d = q.shape[-1]
    block_size = k_pool.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    from ...ops.pallas.paged_attention import (
        paged_decode_attention_pallas, use_pallas_paged)

    if use_pallas_paged(d, block_size):
        out = paged_decode_attention_pallas(
            q[:, 0], k_pool, v_pool, block_tables, context_lens, scale,
            k_scale=k_scale, v_scale=v_scale)
        return out[:, None]
    return _lax_fallback(q, k_pool, v_pool, block_tables, context_lens,
                         float(scale), k_scale=k_scale, v_scale=v_scale)


def _lax_multiquery_fallback(q, k_pool, v_pool, block_tables, context_lens,
                             q_start, scale, k_scale=None, v_scale=None):
    """q [B, T, H, D] -> [B, T, H, D]: gather + per-row causal mask."""
    b, t = q.shape[0], q.shape[1]
    block_size = k_pool.shape[1]
    p = block_tables.shape[1]
    k = _gather_kv(k_pool, k_scale, block_tables)
    v = _gather_kv(v_pool, v_scale, block_tables)
    pos = jnp.arange(p * block_size, dtype=jnp.int32)[None, None, :]
    row = jnp.arange(t, dtype=jnp.int32)[None, :, None]
    # query row i sits at absolute position q_start+i: it may attend to
    # every token at position <= q_start+i that is inside the context
    allowed = (pos <= q_start[:, None, None] + row) \
        & (pos < context_lens[:, None, None])
    return _sdpa_ref.raw_fn(q, k, v, attn_mask=allowed[:, None], scale=scale)


def paged_multiquery_attention(q, k_pool, v_pool, block_tables, context_lens,
                               q_start, scale=None, k_scale=None,
                               v_scale=None):
    """T query tokens per request against the paged pool — the shared
    primitive behind chunked prefill (a block-aligned chunk of the prompt
    at offset ``q_start``) and speculative verify (k+1 draft positions
    scored in one step).

    q: [B, T, H, D] (queries at absolute positions ``q_start[b] + t``);
    pools [N, block, Hkv, D]; block_tables [B, P] int32; context_lens [B]
    int32 — total visible tokens INCLUDING the last real query row (rows
    past ``context_lens - q_start`` are padding; their output is
    unspecified and must be ignored by the caller). Causal within the
    window: row t attends to positions <= q_start + t. Returns
    [B, T, H, D].
    """
    d = q.shape[-1]
    block_size = k_pool.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    from ...ops.pallas.paged_attention import (
        paged_multiquery_attention_pallas, use_pallas_paged)

    if use_pallas_paged(d, block_size):
        return paged_multiquery_attention_pallas(
            q, k_pool, v_pool, block_tables, context_lens, q_start,
            float(scale), k_scale=k_scale, v_scale=v_scale)
    return _lax_multiquery_fallback(q, k_pool, v_pool, block_tables,
                                    context_lens, q_start, float(scale),
                                    k_scale=k_scale, v_scale=v_scale)
