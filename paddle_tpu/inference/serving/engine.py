"""LLM serving engine front-end (ISSUE 7 tentpole, part d; ISSUE 11 adds
prefix sharing, chunked prefill and speculative decoding).

``LLMEngine`` turns a ``LlamaForCausalLM`` into a continuously-batched
server:

* ``add_request`` enqueues a prompt; a ``DevicePrefetcher``-style ingest
  thread pads it to its prefill bucket (PR-1 ``BucketSpec`` semantics, via
  ``io.prefetch.np_pad_to_bucket``) and starts the host→device transfer
  off the decode thread's critical path;
* ``step`` runs one scheduler tick: admit queued prompts (charging only
  blocks the prefix cache cannot supply), advance prefills by at most
  ``max_prefill_tokens_per_step`` tokens of block-aligned chunks (so long
  prompts interleave with decode instead of monopolizing steps), then ONE
  fixed-shape decode step for every decode-ready slot against the paged
  KV pool — the decode graph compiles once and is reused for the life of
  the engine (``paddle.jit.cache_stats()`` row ``llm_engine_decode#n``
  proves it);
* with ``enable_prefix_cache=True``, full prompt blocks are registered
  under hash-chain identities after prefill: N requests sharing a prompt
  prefix prefill its full blocks ONCE, later admissions ``acquire`` the
  shared blocks (ref-counted, copy-on-write guarded) and prefill only
  their unshared tail;
* with ``draft_model=``, decode runs **speculative**: the draft llama
  proposes ``spec_tokens`` greedy continuations per step (its own paged
  pools indexed by the SAME block tables), and a single multi-query
  paged-attention verify step scores all k+1 positions at once with
  in-graph accept counting; rollback rewinds the block-table length and
  frees over-allocated tail blocks, so greedy outputs stay bit-exact
  versus the non-speculative arm;
* ``stream`` iterates steps and yields tokens as they are produced;
* ``reload_weights`` hot-swaps weights from a ``CheckpointManager``
  (``latest_healthy_step()`` — the divergence-sentinel-approved step)
  WITHOUT recompiling: weights are jit arguments, not baked constants.

Pool writes happen in-graph (``lax.dynamic_update_slice``); attention
reads route through ``serving.paged_attention`` (Pallas on TPU, pure-lax
gather on CPU). Sampling is host-side per request via
``models.llama.sample_next_tokens`` — the same function the eager
``generate`` path uses, so engine outputs are bit-exact against it.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import queue
import threading
import time
import warnings

import numpy as np

from ...observability import metrics as _obs_metrics
from ...observability import trace as _obs_trace
from .errors import (EngineClosedError, KVIntegrityError,
                     RequestTimeoutError)
from .integrity import (_M_PAGES_REJECTED, _M_PAGES_VERIFIED,
                        _M_WEIGHT_AUDIT_FAIL)
from .integrity import verify_pages as _verify_pages
from .kv_cache import (PagedKVCache, PrefixCache, HostKVTier,
                       _G_HOST_BLOCKS, _H_REVIVE_MS, _H_SPILL_MS,
                       _M_HOST_EVICT, _M_REVIVES, _M_REVIVE_BYTES,
                       _M_SPILLS, _M_SPILL_BYTES)
from .prefix_store import (PrefixStoreMismatch, load_prefix_store,
                           pool_geometry, save_prefix_store,
                           weights_fingerprint, _M_STORE_LOADED,
                           _M_STORE_REJECTED, _M_STORE_SAVED)
from .scheduler import (Request, SamplingParams, Scheduler,
                        _M_ADMITTED, _M_BATCH_YIELD, _M_COW, _M_EVICTIONS,
                        _M_FINISHED, _M_PREFIX_REUSED, _M_QUEUED_EXH,
                        _M_TENANT_TOKENS, _M_THROTTLED)

__all__ = ["LLMEngine", "StepOutput", "save_llama_artifact",
           "load_llama_artifact", "load_llama_state_dict",
           "is_quantized_artifact", "quantize_state_dict",
           "dequantize_state_dict", "EngineClosedError",
           "RequestTimeoutError"]

# engine-owned latency/utilization observability (ISSUE 10): TTFT and
# inter-token latency are recorded HERE, from host timestamps the engine
# already takes at its sampling points (post-fetch — sampling is host-side
# by design), so bench_serving reports serving percentiles from the
# engine's own histograms instead of bench-side timing. Labeled by engine
# instance; request ids ride in trace spans (bounded rows), never labels.
_H_TTFT = _obs_metrics.histogram(
    "serving_ttft_ms", "time to first token per request (submit -> first "
    "sampled token)", buckets=_obs_metrics.DEFAULT_MS_BUCKETS)
_H_ITL = _obs_metrics.histogram(
    "serving_itl_ms", "inter-token latency per decoded token",
    buckets=_obs_metrics.DEFAULT_MS_BUCKETS)
_M_TOKENS = _obs_metrics.counter(
    "serving_tokens_out_total", "tokens sampled across all requests")
_M_PREFILLS = _obs_metrics.counter(
    "serving_prefills_total", "prefill completions (incl. eviction "
    "re-prefills)")
_M_PREFILL_CHUNKS = _obs_metrics.counter(
    "serving_prefill_chunks_total",
    "block-aligned prefill chunk executions (chunked prefill splits one "
    "prompt across several of these)")
_M_SPEC_PROPOSED = _obs_metrics.counter(
    "serving_spec_proposed_total",
    "draft tokens proposed by the speculative decoder")
_M_SPEC_ACCEPTED = _obs_metrics.counter(
    "serving_spec_accepted_total",
    "draft tokens accepted by the verify step")
_G_SPEC_RATIO = _obs_metrics.gauge(
    "serving_spec_accept_ratio",
    "running accepted/proposed ratio of the speculative decoder")
_G_KV_UTIL = _obs_metrics.gauge(
    "serving_kv_block_utilization",
    "fraction of usable KV pool blocks in use after the last step")
_G_OCCUPANCY = _obs_metrics.gauge(
    "serving_decode_batch_occupancy",
    "fraction of decode slots occupied after the last step")
_M_DEADLINE = _obs_metrics.counter(
    "serving_deadline_expired_total",
    "requests aborted by the engine because their deadline expired "
    "(admission-time rejections raise before a request exists and are "
    "not counted here)")
_M_KV_SAVED = _obs_metrics.counter(
    "serving_kv_bytes_saved_total",
    "pool bytes saved by int8 KV quantization vs the same pool in the "
    "model dtype (scale sidecars charged against the saving; counted "
    "once at engine construction)")
_G_QUANT_BLOCKS = _obs_metrics.gauge(
    "serving_quantized_kv_blocks_in_use",
    "int8-quantized KV pool blocks held by live requests after the last "
    "step (0 series absent on unquantized engines) — the occupancy the "
    "halved block memory buys")
# device-resident decode (ISSUE 18): how often the decode loop blocks on
# a device->host fetch and how many bytes it pulls. Host-side sampling
# fetches [B, V] f32 logits per emitted token; in-graph sampling fetches
# [B] int32 tokens; a fused k-step window fetches [B, k] int32 once.
_M_HOST_SYNCS = _obs_metrics.counter(
    "serving_host_syncs_total",
    "blocking device->host fetches made by the decode loop (logits or "
    "sampled tokens); one per decode round-trip, prefill fetches excluded")
_M_FETCH_BYTES = _obs_metrics.counter(
    "serving_decode_fetch_bytes_total",
    "bytes fetched device->host by the decode loop: B*V*4 per step under "
    "host-side sampling, B*4 per step with in-graph sampling, B*k*4 per "
    "fused k-step decode window")

# the ONE list of every serving metric handle an engine instance owns —
# metrics() and reset_metrics() both iterate it, so a new metric cannot
# be added to one and silently missed by the other (a reset that skips a
# histogram would leak warm-phase samples into bench percentiles)
_SERVING_METRICS = (_M_ADMITTED, _M_EVICTIONS, _M_FINISHED, _M_QUEUED_EXH,
                    _M_PREFIX_REUSED, _M_COW, _M_PREFILLS,
                    _M_PREFILL_CHUNKS, _M_SPEC_PROPOSED, _M_SPEC_ACCEPTED,
                    _M_TOKENS, _M_DEADLINE, _M_KV_SAVED, _H_TTFT, _H_ITL,
                    _G_SPEC_RATIO, _G_KV_UTIL, _G_OCCUPANCY,
                    _G_QUANT_BLOCKS,
                    # KV tiering + prefix store (ISSUE 16);
                    # _M_STORE_REJECTED is reason-labeled (ISSUE 20), so
                    # metrics()/reset_metrics() handle it like
                    # _M_TENANT_TOKENS (exact-match remove can't reach it)
                    _M_SPILLS, _M_REVIVES, _M_SPILL_BYTES, _M_REVIVE_BYTES,
                    _M_HOST_EVICT, _G_HOST_BLOCKS, _H_SPILL_MS,
                    _H_REVIVE_MS, _M_STORE_SAVED, _M_STORE_LOADED,
                    # multi-tenant QoS (ISSUE 17); _M_TENANT_TOKENS is
                    # tenant-labeled, so metrics()/reset_metrics() handle
                    # it separately (exact-match remove can't reach it)
                    _M_THROTTLED, _M_BATCH_YIELD,
                    # device-resident decode (ISSUE 18)
                    _M_HOST_SYNCS, _M_FETCH_BYTES,
                    # serving integrity (ISSUE 20)
                    _M_PAGES_VERIFIED, _M_PAGES_REJECTED,
                    _M_WEIGHT_AUDIT_FAIL)


@dataclasses.dataclass
class StepOutput:
    rid: int
    token: int
    finished: bool
    finish_reason: str | None = None


def _default_buckets(block_size, max_model_len):
    """Doubling ladder of prefill lengths, block-aligned: one compiled
    prefill graph per rung."""
    buckets, b = [], block_size
    while b < max_model_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_model_len)
    return buckets


class _IngestThread:
    """Push-based analog of ``io.DevicePrefetcher``'s transfer thread:
    pads each queued prompt to its prefill bucket on the host and starts
    the device transfer, so admission never blocks decode on H2D. Dies
    once, warns once, and the engine degrades to synchronous staging."""

    def __init__(self, stage_fn, name):
        self._stage = stage_fn
        self._q: queue.Queue = queue.Queue()
        self._ready: list = []
        self._cond = threading.Condition()
        self._pending = 0  # submitted but not yet drained
        self._dead = False
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name=f"{name}-ingest")
        self._thread.start()

    def _worker(self):
        while True:
            req = self._q.get()
            if req is None:
                return
            try:
                self._stage(req)
            except BaseException as e:
                warnings.warn(
                    f"LLMEngine ingest thread died ({e!r}); degrading to "
                    "synchronous request staging", RuntimeWarning)
                with self._cond:
                    # _dead flips and the queue flushes under ONE lock
                    # acquisition: submit() holds the same lock across its
                    # dead-check and enqueue, so a request can never land in
                    # _q after this flush and be stranded there forever
                    self._dead = True
                    # flush EVERYTHING un-staged (the failing request AND
                    # anything still queued behind it) back to the engine —
                    # step() re-stages synchronously; stranding them would
                    # leave has_work() true forever with nothing to drain
                    self._ready.append(req)
                    while True:
                        try:
                            nxt = self._q.get_nowait()
                        except queue.Empty:
                            break
                        if nxt is not None:
                            self._ready.append(nxt)
                    self._cond.notify_all()
                return
            with self._cond:
                self._ready.append(req)
                self._cond.notify_all()

    @property
    def pending(self):
        with self._cond:
            return self._pending

    def submit(self, req):
        # dead-check and enqueue under one lock hold: the worker's death
        # path flips _dead and flushes _q while holding the same lock, so
        # either this put lands before the flush (and gets flushed) or we
        # observe _dead and hand the request straight to _ready
        with self._cond:
            self._pending += 1
            if self._dead:
                self._ready.append(req)
                self._cond.notify_all()
                return
            self._q.put(req)

    def drain(self, wait=False, timeout=1.0):
        """Staged requests since the last drain. ``wait=True`` blocks (up
        to ``timeout``) until at least one lands — the engine uses it when
        it would otherwise spin on an empty scheduler while requests are
        in flight on the ingest thread."""
        with self._cond:
            if wait and not self._ready and self._pending:
                self._cond.wait_for(lambda: self._ready, timeout=timeout)
            out, self._ready = self._ready, []
            self._pending -= len(out)
        return out

    def close(self):
        if not self._dead:
            self._q.put(None)
            self._thread.join(timeout=2.0)


class LLMEngine:
    """Continuous-batching paged-KV serving engine over a llama model."""

    _instance_ids = itertools.count(1)

    def __init__(self, model, *, num_blocks=64, block_size=16,
                 max_batch_size=4, max_model_len=None, prefill_buckets=None,
                 max_prefills_per_step=1, ingest_async=True, plan=None,
                 enable_prefix_cache=False, max_prefill_tokens_per_step=None,
                 draft_model=None, spec_tokens=2, kv_dtype=None,
                 prefill_only=False, kv_host_blocks=0,
                 prefix_store_path=None, prefix_store_autosave_chains=None,
                 fuse_draft_catchup=True, decode_steps_per_sync=1,
                 in_graph_sampling=None, capture_logits=False,
                 kv_page_checksums=False, weight_audit=False):
        from ...models.llama import LlamaForCausalLM, sample_next_tokens

        if not isinstance(model, LlamaForCausalLM):
            raise TypeError("LLMEngine serves LlamaForCausalLM models; got "
                            f"{type(model).__name__}")
        self.model = model
        # sharding plan (distributed.plan.Plan): weights are committed to
        # the plan's layouts (e.g. Megatron tp for pod-scale serving) and
        # both engine executables lower through compile_step_with_plan —
        # the ONE compile layer shared with FusedTrainStep and hapi fit.
        # GSPMD propagates the committed weight placements through the
        # prefill/decode bodies; plan=None keeps the exact single-device
        # program (same entry point, no fork).
        self._plan = plan
        if plan is not None:
            plan.apply_to_model(model)
        # multi-process plan (ISSUE 19): the plan's mesh spans jax
        # processes (one engine rank per process, SPMD lockstep — the
        # fleet's tp replica groups). Host-side control flow stays
        # identical on every rank; device arrays the engine feeds its
        # compiled steps must live REPLICATED on the global mesh (_g),
        # outputs are pinned replicated (_build_jits), and host fetches
        # read the locally addressable shard (_fetch).
        self._mp = False
        if plan is not None and plan.mesh.devices.size > 1:
            import jax
            pi = jax.process_index()
            self._mp = any(d.process_index != pi
                           for d in plan.mesh.devices.flat)
        if self._mp:
            # features whose data path fetches pool pages to the host
            # (or runs a second model) are incompatible with a
            # process-spanning mesh; fail at construction, not mid-burst
            for flag, why in (
                    (int(kv_host_blocks) > 0,
                     "kv_host_blocks > 0 (host KV tier spills pool "
                     "pages to host RAM)"),
                    (prefix_store_path is not None,
                     "prefix_store_path (the store exports pool "
                     "pages)"),
                    (draft_model is not None,
                     "draft_model (speculative decoding)"),
                    (bool(prefill_only),
                     "prefill_only (disaggregated handoff exports "
                     "pool pages)")):
                if flag:
                    raise ValueError(
                        f"a plan whose mesh spans multiple processes "
                        f"does not support {why}; run these features "
                        "on single-process engines")
        self.config = model.config
        was_training = model.training
        model.eval()
        self._was_training = was_training
        limit = self.config.max_position_embeddings
        self.block_size = int(block_size)
        requested_len = min(int(max_model_len or limit), limit)
        # block-alignment invariant: prefill writes whole pages only, so a
        # max_model_len that is not a block multiple would leave the prompt
        # tail out of the pool at the top bucket — silently wrong decodes.
        # Round DOWN to whole pages; the truncated tail was unservable anyway.
        self.max_model_len = (requested_len // self.block_size
                              ) * self.block_size
        if self.max_model_len == 0:
            raise ValueError(
                f"max_model_len={requested_len} is smaller than "
                f"block_size={self.block_size}; nothing fits in one page")
        if self.max_model_len != requested_len:
            warnings.warn(
                f"max_model_len={requested_len} is not a multiple of "
                f"block_size={self.block_size}; rounding down to "
                f"{self.max_model_len} so prefill stays page-aligned",
                RuntimeWarning)
        self.max_pages = self.max_model_len // self.block_size
        dtype = model.llama.layers[0].self_attn.k_proj.weight.dtype
        # int8 paged-KV quantization (ISSUE 14): pools store codes +
        # per-row scale sidecars, dequantized inside the attention
        # kernels; everything identity-shaped (allocator, prefix cache,
        # COW, tables) is payload-dtype-blind and composes unchanged
        self.kv_dtype = kv_dtype
        self.cache = PagedKVCache(self.config, num_blocks, block_size,
                                  dtype=dtype, kv_dtype=kv_dtype)
        # serving integrity (ISSUE 20): arm per-block CRC sealing of
        # every host-materialized page payload; read-back boundaries
        # (tier revive, page import, prefix-store entries) verify and
        # degrade to re-prefill on mismatch
        self.cache.page_checksums = bool(kv_page_checksums)
        if self._mp:
            self._globalize_cache(self.cache)
        self._kv_bytes_saved = self.cache.bytes_saved_vs_unquantized(
            self.config)
        # prefix sharing (ISSUE 11): content-hashed block identity over the
        # pool — admission charges only unshared blocks
        self.prefix_cache = (PrefixCache(self.cache.allocator,
                                         self.block_size)
                             if enable_prefix_cache else None)
        # chunked prefill budget: NEW prompt tokens materialized per step.
        # None = whole prompts in one chunk (the PR-7 behavior); a budget
        # bounds decode inter-token latency by the chunk, not the prompt.
        if max_prefill_tokens_per_step is not None:
            max_prefill_tokens_per_step = int(max_prefill_tokens_per_step)
            if max_prefill_tokens_per_step < 1:
                raise ValueError("max_prefill_tokens_per_step must be >= 1")
        self.max_prefill_tokens_per_step = max_prefill_tokens_per_step
        n = next(LLMEngine._instance_ids)
        self._name = f"llm_engine#{n}"
        # KV tiering (ISSUE 16): a host-RAM page tier behind the device
        # pool. Preempted decode-ready requests and reclaimed prefix
        # blocks spill to it instead of being recomputed; revival is
        # import_request_pages — bit-exact by construction.
        kv_host_blocks = int(kv_host_blocks)
        if kv_host_blocks < 0:
            raise ValueError("kv_host_blocks must be >= 0")
        self.kv_tier = (HostKVTier(self.cache, kv_host_blocks,
                                   instance=self._name)
                        if kv_host_blocks > 0 else None)
        if self.kv_tier is not None and self.prefix_cache is not None:
            self.prefix_cache.on_spill = self.kv_tier.spill_blocks
        # persistent prefix store (ISSUE 16): hash chains survive process
        # death as a CRC-framed shard; boot re-imports them into the host
        # tier so the next matching prompt revives instead of re-prefills.
        if prefix_store_path is not None:
            if self.prefix_cache is None:
                raise ValueError(
                    "prefix_store_path requires enable_prefix_cache=True: "
                    "the store persists prefix hash chains")
            if self.kv_tier is None:
                raise ValueError(
                    "prefix_store_path requires kv_host_blocks > 0: "
                    "loaded entries land in the host tier until a "
                    "matching request revives them")
        self._store_path = prefix_store_path
        if prefix_store_autosave_chains is not None:
            prefix_store_autosave_chains = int(prefix_store_autosave_chains)
            if prefix_store_autosave_chains < 1:
                raise ValueError(
                    "prefix_store_autosave_chains must be >= 1")
            if prefix_store_path is None:
                raise ValueError("prefix_store_autosave_chains without "
                                 "prefix_store_path saves nowhere")
        self._store_autosave = prefix_store_autosave_chains
        self._store_fingerprint = None
        self._store_saved_chains = -1  # force the first autosave crossing
        self.scheduler = Scheduler(self.cache.allocator, block_size,
                                   max_batch_size, max_prefills_per_step,
                                   instance=self._name,
                                   prefix_cache=self.prefix_cache,
                                   kv_tier=self.kv_tier)
        if self.cache.quantized:
            _M_KV_SAVED.inc(self._kv_bytes_saved, instance=self._name)
            _G_QUANT_BLOCKS.set(0, instance=self._name)
        self.max_batch_size = int(max_batch_size)
        buckets = prefill_buckets or _default_buckets(self.block_size,
                                                      self.max_model_len)
        # block-align every rung so prefill writes whole pages
        self.prefill_buckets = sorted({
            min(-(-int(b) // self.block_size) * self.block_size,
                self.max_model_len)
            for b in buckets})
        self._prefill_name = f"llm_engine_prefill#{n}"
        self._decode_name = f"llm_engine_decode#{n}"
        self._params = model._unique_params()
        self._prefill_jit = None
        self._decode_jit = None
        # prefill-only mode (ISSUE 15): the disaggregated prefill worker
        # runs prefills (and samples each request's FIRST token from the
        # final chunk's logits) but never decodes — requests sit
        # decode-ready until the caller exports their pages
        # (export_kv_pages) and cancels them; step() skips the decode
        # phase entirely, so the decode graph never compiles here.
        self.prefill_only = bool(prefill_only)
        if self.prefill_only and draft_model is not None:
            raise ValueError("prefill_only engines never decode; a "
                             "draft_model would be dead weight")
        # speculative decoding (ISSUE 11): the draft llama shares the
        # target's allocator/block tables; its pools are its own shapes
        self.draft_model = draft_model
        self._spec_k = 0
        if draft_model is not None:
            if not isinstance(draft_model, LlamaForCausalLM):
                raise TypeError("draft_model must be a LlamaForCausalLM; "
                                f"got {type(draft_model).__name__}")
            if draft_model.config.vocab_size != self.config.vocab_size:
                raise ValueError(
                    "draft_model vocab_size "
                    f"{draft_model.config.vocab_size} != target "
                    f"{self.config.vocab_size}: verify compares token ids")
            if int(spec_tokens) < 1:
                raise ValueError("spec_tokens must be >= 1")
            self._spec_k = int(spec_tokens)
            self._draft_was_training = draft_model.training
            draft_model.eval()
            if plan is not None:
                plan.apply_to_model(draft_model)
            ddtype = (draft_model.llama.layers[0].self_attn.k_proj
                      .weight.dtype)
            self.draft_cache = PagedKVCache(
                draft_model.config, num_blocks, block_size, dtype=ddtype,
                allocator=self.cache.allocator, kv_dtype=kv_dtype)
            self._draft_params = draft_model._unique_params()
            self._draft_prefill_name = f"llm_engine_draft_prefill#{n}"
            self._draft_decode_name = f"llm_engine_draft_decode#{n}"
            self._verify_name = f"llm_engine_verify#{n}"
            self._draft_prefill_jit = None
            self._draft_decode_jit = None
            self._verify_jit = None
        # device-resident decode (ISSUE 18): in-graph greedy sampling
        # shrinks the per-step fetch from [B, V] f32 logits to [B] int32
        # tokens; fused windows (decode_steps_per_sync=k) run k decode
        # iterations inside one fori_loop graph and fetch [B, k] tokens
        # per host round-trip. k=1 with in_graph_sampling unset keeps the
        # pre-ISSUE-18 host-sampling path byte-identical.
        k = int(decode_steps_per_sync)
        if k < 1:
            raise ValueError(
                f"decode_steps_per_sync must be >= 1, got {k}")
        if k > 1 and draft_model is not None:
            raise ValueError(
                "decode_steps_per_sync > 1 and speculative decoding are "
                "mutually exclusive: the verify window already batches "
                "device work and samples in-graph")
        if in_graph_sampling is None:
            in_graph_sampling = k > 1
        in_graph_sampling = bool(in_graph_sampling)
        if k > 1 and not in_graph_sampling:
            raise ValueError(
                "decode_steps_per_sync > 1 requires in_graph_sampling: a "
                "fused window cannot round-trip logits to the host "
                "between its iterations")
        if in_graph_sampling and draft_model is not None:
            raise ValueError(
                "in_graph_sampling applies to the plain decode path; the "
                "speculative verify step already samples in-graph")
        if capture_logits and in_graph_sampling:
            raise ValueError(
                "capture_logits=True requires host-side sampling "
                "(in_graph_sampling=False, decode_steps_per_sync=1): "
                "device-resident decode never fetches the logits rows")
        self._decode_window = k
        self._in_graph = in_graph_sampling
        self.capture_logits = bool(capture_logits)
        self._window_name = f"llm_engine_decode_window#{n}"
        self._window_jit = None
        self._warned_do_sample = False
        # hoisted from _emit (ISSUE 18 satellite): one import at
        # construction instead of one per emitted token
        self._sample_next_tokens = sample_next_tokens
        # device block-table cache (ISSUE 11 satellite): rebuilt only when
        # the scheduler's table version moves, so steady-state decode does
        # ZERO table H2D
        self._tables_version = None
        self._tables_dev = None
        self._requests: dict[int, Request] = {}
        self._closed = False
        # fused ragged draft catch-up (ISSUE 16 perf satellite): one
        # fori_loop graph per power-of-two feed-length bucket instead of
        # F sequential dispatches of the single-token draft decode.
        self._fuse_catchup = bool(fuse_draft_catchup)
        self._catchup_jits = {}
        if self.kv_tier is not None:
            # publish the tier series at zero so metrics() and dashboards
            # see them from boot, not from the first spill
            for m in (_M_SPILLS, _M_REVIVES, _M_SPILL_BYTES,
                      _M_REVIVE_BYTES, _M_HOST_EVICT):
                m.inc(0, instance=self._name)
            _G_HOST_BLOCKS.set(0, instance=self._name)
        if self.cache.page_checksums:
            # publish the verify/reject series at zero from boot
            _M_PAGES_VERIFIED.inc(0, instance=self._name)
            _M_PAGES_REJECTED.inc(0, instance=self._name)
        # weight integrity re-audit (ISSUE 20): capture the live
        # fingerprint at construction; audit_weights() re-hashes and
        # compares — a divergence means the weights changed IN PLACE
        # (silent corruption), not a reload (reload_weights re-captures)
        self._weight_audit = bool(weight_audit)
        self._weight_audits = 0
        self._weight_audit_ref = (weights_fingerprint(model)
                                  if weight_audit else None)
        if weight_audit:
            _M_WEIGHT_AUDIT_FAIL.inc(0, instance=self._name)
        self._store_geometry = None
        if self._store_path is not None:
            self._store_fingerprint = weights_fingerprint(model)
            self._store_geometry = pool_geometry(self.cache, self.config)
            self._load_prefix_store()
        self._ingest = (_IngestThread(self._stage_request, self._name)
                        if ingest_async else None)
        self.stats_extra = {"steps": 0, "prefills": 0, "tokens_out": 0}

    # ------------------------------------------------------------------
    # persistent prefix store (ISSUE 16)
    # ------------------------------------------------------------------
    def _prefix_store_entries(self):
        """Chain entries worth persisting: every device-registered chain
        (exported from the pool) plus every host-tier-resident chain a
        prior boot loaded or a reclaim demoted — deduped by hash, device
        copy wins (it is the one requests are actively sharing)."""
        entries = {}
        for h, b in self.prefix_cache.registered_chains():
            entries[h] = self.cache.export_request_pages([b],
                                                         self.block_size)
        for h, pages in self.kv_tier.prefix_items():
            entries.setdefault(h, pages)
        return list(entries.items())

    def save_prefix_store(self):
        """Serialize the current prefix chains to ``prefix_store_path``
        (atomic publish; the previous store stays intact on any failure).
        Returns the number of entries written."""
        if self._store_path is None:
            raise ValueError(f"{self._name} has no prefix_store_path")
        entries = self._prefix_store_entries()
        save_prefix_store(self._store_path, entries,
                          fingerprint=self._store_fingerprint,
                          geometry=self._store_geometry,
                          instance=self._name)
        self._store_saved_chains = len(self.prefix_cache)
        return len(entries)

    def _load_prefix_store(self):
        """Import the on-disk store into the host tier; any mismatch
        (CRC, fingerprint, geometry) degrades to a clean cold start."""
        try:
            entries = load_prefix_store(
                self._store_path, fingerprint=self._store_fingerprint,
                geometry=self._store_geometry, instance=self._name)
        except PrefixStoreMismatch as e:
            warnings.warn(
                f"{self._name}: rejecting prefix store "
                f"(reason={e.reason}): {e}; cold-starting the prefix "
                "cache", RuntimeWarning)
            return 0
        if entries is None:
            return 0
        loaded = 0
        for h, pages in entries:
            if self.kv_tier.put_prefix_payload(h, pages):
                loaded += 1
        return loaded

    def _maybe_autosave_store(self):
        if self._store_path is None or self._store_autosave is None:
            return
        grown = len(self.prefix_cache) - max(self._store_saved_chains, 0)
        if (grown >= self._store_autosave
                or self._store_saved_chains < 0 and len(self.prefix_cache)):
            try:
                self.save_prefix_store()
            except OSError as e:
                # saving is an optimisation; the serving loop never dies
                # for it (the previous store on disk stays intact)
                warnings.warn(f"{self._name}: prefix store autosave "
                              f"failed: {e}", RuntimeWarning)
                self._store_saved_chains = len(self.prefix_cache)

    def _ensure_open(self):
        if self._closed:
            raise EngineClosedError(
                f"{self._name} is closed; create a new LLMEngine "
                "(close() joined the ingest thread, freed scheduler "
                "blocks and removed this instance's metric series)")

    # ------------------------------------------------------------------
    # multi-process placement helpers (ISSUE 19)
    # ------------------------------------------------------------------
    def _g(self, x):
        """Device placement for a step input: on a single-process mesh
        this is plain ``jnp.asarray`` (byte-identical to the pre-group
        engine); on a process-spanning mesh the value is committed
        REPLICATED over the plan's global mesh — every rank passes the
        same host value (SPMD lockstep), so the commit is collective-free
        and keeps jit from refusing to mix local and global arrays."""
        if not self._mp:
            import jax.numpy as jnp
            return jnp.asarray(x)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.device_put(
            np.asarray(x), NamedSharding(self._plan.mesh,
                                         PartitionSpec()))

    def _fetch(self, arr):
        """Host fetch of a step output. Outputs on a process-spanning
        mesh are pinned replicated (``_build_jits``), so every rank reads
        the SAME value from its locally addressable shard —
        ``np.asarray`` on the global array itself would raise (it spans
        non-addressable devices)."""
        if not self._mp:
            return np.asarray(arr)
        return np.asarray(arr.addressable_data(0))

    def _globalize_cache(self, cache):
        """Re-commit freshly zeroed pool arrays (created on the local
        default device) replicated over the global mesh so the compiled
        steps can donate and rebind them."""
        cache.k = [self._g(x) for x in cache.k]
        cache.v = [self._g(x) for x in cache.v]
        cache.k_scale = [self._g(x) for x in cache.k_scale]
        cache.v_scale = [self._g(x) for x in cache.v_scale]

    # ------------------------------------------------------------------
    # request lifecycle
    # ------------------------------------------------------------------
    def _bucket_for(self, n):
        for b in self.prefill_buckets:
            if b >= n:
                return b
        raise ValueError(f"prompt of {n} tokens exceeds the largest "
                         f"prefill bucket {self.prefill_buckets[-1]}")

    def _stage_request(self, req):
        """Pad the request's current prefix to its prefill bucket and start
        the H2D transfer (ingest thread / re-prefill staging)."""
        import jax

        from ...io.prefetch import np_pad_to_bucket
        from ...jit.cache import BucketSpec

        toks = req.tokens
        bucket = self._bucket_for(len(toks))
        spec = BucketSpec({1: (bucket,)})
        ids, _ = np_pad_to_bucket(toks[None].astype(np.int32), spec,
                                  lengths={1: len(toks)})
        ids_dev = self._g(ids) if self._mp else jax.device_put(ids)
        req._staged = (ids_dev, bucket, len(toks))

    def add_request(self, prompt_ids, sampling: SamplingParams | None = None,
                    arrival_t=None, deadline=None, tenant=None, tier=None):
        """Enqueue a prompt; returns the request id. Never blocks on pool
        exhaustion — the request queues until blocks free up.

        ``deadline`` is an absolute ``time.time()`` wall-clock deadline
        (ISSUE 12): an already-expired deadline raises
        :class:`RequestTimeoutError` HERE — before the request is
        registered, staged, or any allocator/scheduler state moves — and
        a deadline expiring later aborts the request at the next step
        (blocks freed, slot recycled, stream finished with reason
        ``"timeout"``).

        ``tenant``/``tier`` (ISSUE 17) attach a QoS identity — defaults
        (``"default"``/latency) keep the exact pre-QoS FIFO behavior."""
        self._ensure_open()
        if deadline is not None and time.time() >= float(deadline):
            raise RequestTimeoutError(
                f"deadline {deadline} already expired at admission "
                f"(now={time.time():.3f}); request rejected before any "
                "block allocation", deadline=deadline)
        req = Request(prompt_ids, sampling, arrival_t=arrival_t,
                      deadline=deadline, tenant=tenant, tier=tier)
        self._check_admissible(req)
        # observability clock zero: TTFT and the queued span both measure
        # from the moment the engine accepted the request
        req.t_submit = req.t_queue_start = time.perf_counter_ns()
        self._requests[req.rid] = req
        if self._ingest is not None:
            self._ingest.submit(req)
        else:
            self._stage_request(req)
            self.scheduler.waiting.append(req)
        return req.rid

    def configure_tenant(self, name, *, weight=1.0, rate_tokens_per_s=None,
                         window_s=1.0, host_blocks=None,
                         prefix_blocks=None):
        """Declare one tenant's QoS envelope (ISSUE 17) in one call:
        fair-share ``weight`` and leaky-bucket token-rate quota land in
        the scheduler, ``host_blocks`` caps its resident host-tier pages
        (requires a KV tier), and ``prefix_blocks`` caps how many
        device-pool prefix blocks it may keep published (over-share
        demotes its own oldest to the host tier, never other tenants').
        Unconfigured tenants serve at weight 1 with no quota — QoS stays
        fully off until the first call."""
        self._ensure_open()
        st = self.scheduler.configure_tenant(
            name, weight=weight, rate_tokens_per_s=rate_tokens_per_s,
            window_s=window_s)
        if host_blocks is not None:
            if self.kv_tier is None:
                raise ValueError(
                    "host_blocks needs a host tier; construct the engine "
                    "with kv_host_blocks=")
            self.kv_tier.set_tenant_share(name, host_blocks)
        if prefix_blocks is not None:
            if self.prefix_cache is None:
                raise ValueError(
                    "prefix_blocks needs prefix sharing; construct the "
                    "engine with enable_prefix_cache=True")
            self.prefix_cache.set_tenant_share(name, prefix_blocks)
        return st

    def _check_admissible(self, req):
        """Admission validation shared by ``add_request`` and
        ``add_request_with_pages`` (ISSUE 15): greedy-only under
        speculation, pool/length caps, re-prefill bucket coverage, sane
        budget — all typed, all BEFORE any request or allocator state
        moves. One copy, so the two admission doors can never drift."""
        if self._spec_k and req.sampling.do_sample:
            raise ValueError(
                "speculative decoding is greedy-only (the verify step "
                "accepts by argmax identity); submit do_sample requests "
                "to an engine without a draft_model")
        total = len(req.prompt) + req.sampling.max_new_tokens
        cap = min(self.max_model_len,
                  (self.cache.num_blocks - 1) * self.block_size)
        # the speculative verify window writes spec_k lookahead positions
        # past the final token — they must fit in the pool too
        if total + self._spec_k > cap:
            raise ValueError(
                f"request needs {total + self._spec_k} tokens (incl. "
                f"{self._spec_k} speculative lookahead) but the engine "
                f"caps at {cap} (max_model_len={self.max_model_len}, pool="
                f"{self.cache.num_blocks - 1} usable blocks x "
                f"{self.block_size})")
        # an evicted request re-prefills from its full prefix (up to
        # total-1 tokens): with custom prefill_buckets the largest rung
        # must cover that, or staging would fail mid-stream
        if total - 1 > self.prefill_buckets[-1]:
            raise ValueError(
                f"request may need a {total - 1}-token prefill (prompt + "
                f"re-prefill after eviction) but the largest prefill "
                f"bucket is {self.prefill_buckets[-1]}")
        if req.sampling.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")

    # -- disaggregated prefill/decode handoff (ISSUE 15) ----------------
    def export_kv_pages(self, rid):
        """Export a request's materialized KV pages (the prefill-worker
        side of the handoff): the pool content of its blocks holding the
        ``num_cached`` tokens written so far, scales included on int8
        pools. The request must have finished prefill (decode-ready) —
        exporting a half-prefilled request would hand off pages the
        first token was never sampled from."""
        if self._mp:
            raise ValueError(
                "export_kv_pages is not supported on a plan whose mesh "
                "spans multiple processes: pool pages cannot be fetched "
                "to one host (sharded disagg handoff is future work)")
        req = self._requests[rid]
        if req.finished or req.prefilling or req.num_cached < 1:
            raise ValueError(
                f"request {rid} is not decode-ready "
                f"(state={req.state}, prefilling={req.prefilling}); only "
                "a completed prefill exports pages")
        n_pages = -(-req.num_cached // self.block_size)
        return self.cache.export_request_pages(req.blocks[:n_pages],
                                               req.num_cached)

    def add_request_with_pages(self, prompt_ids, pages,
                               sampling: SamplingParams | None = None,
                               deadline=None, tenant=None, tier=None):
        """Admit a request whose prompt KV pages were computed by a
        prefill worker (the decode side of the disaggregated handoff):
        ``prompt_ids`` is the original prompt PLUS the first token the
        prefill worker sampled, and ``pages`` (an ``export_kv_pages``
        payload) covers every position but the last. Admission allocates
        blocks normally (queues on exhaustion, FIFO); the next ``step``
        imports the payload into them and the request decodes from its
        first step — no prefill graph runs, and greedy continuation is
        bit-identical to a colocated engine because the imported pages
        are byte-identical to what local prefill would have written.

        An expired ``deadline`` raises :class:`RequestTimeoutError` HERE,
        before any request or allocator state moves; a deadline expiring
        while the request waits for admission aborts it with the typed
        reason and the never-imported pages are simply dropped."""
        self._ensure_open()
        if self.prefill_only:
            raise ValueError("prefill_only engines never decode; "
                             "imported pages have nowhere to go")
        if deadline is not None and time.time() >= float(deadline):
            raise RequestTimeoutError(
                f"deadline {deadline} already expired at admission "
                f"(now={time.time():.3f}); imported pages rejected before "
                "any block allocation", deadline=deadline)
        req = Request(prompt_ids, sampling, deadline=deadline,
                      tenant=tenant, tier=tier)
        covered = int(pages["covered"])
        if covered != len(req.prompt) - 1:
            raise ValueError(
                f"pages cover {covered} tokens but the prompt has "
                f"{len(req.prompt)} — the handoff prompt is the original "
                "prompt plus the prefill worker's first sampled token, "
                "so coverage must be len(prompt) - 1")
        # full geometry validation (dtype/block_size/shapes/scale rows)
        # happens HERE, before the request exists — not at import time,
        # when blocks are already allocated and pools about to move
        n_payload = self.cache.validate_request_pages(pages)
        # ISSUE 20 read-back boundary: a sealed payload must verify
        # before admission — typed KVIntegrityError instead of decoding
        # from corrupt transferred pages (unsealed payloads pass)
        _verify_pages(pages, instance=self._name,
                      key=("import", req.rid))
        if n_payload != -(-covered // self.block_size):
            raise ValueError(
                f"pages hold {n_payload} blocks but cover {covered} "
                f"tokens ({-(-covered // self.block_size)} blocks at "
                f"block_size={self.block_size})")
        self._check_admissible(req)
        req.preloaded = pages
        req.t_submit = req.t_queue_start = time.perf_counter_ns()
        self._requests[req.rid] = req
        # no staging needed (nothing to prefill): straight to the queue
        self.scheduler.waiting.append(req)
        return req.rid

    def _adopt_preloaded(self, req):
        """Write a just-admitted preloaded request's imported pages into
        its allocated blocks (host-triggered, before this step's decode)
        and publish their identities to the prefix cache so later
        admissions can share them. One-shot: after this, the request is
        indistinguishable from one prefilled locally — an eviction
        re-prefills through the normal staged path."""
        pages = req.preloaded
        req.preloaded = None
        revived = req.revived_from_tier
        req.revived_from_tier = False
        t0 = time.perf_counter()
        self.cache.import_request_pages(req.blocks, pages)
        if revived:
            # tier revival (ISSUE 16): the session came back from host
            # RAM instead of re-prefilling
            _M_REVIVES.inc(instance=self._name)
            _M_REVIVE_BYTES.inc(
                sum(int(v.nbytes) for v in pages.values()
                    if isinstance(v, np.ndarray)), instance=self._name)
            _H_REVIVE_MS.observe((time.perf_counter() - t0) * 1e3,
                                 instance=self._name)
        if self.prefix_cache is not None:
            # sound because imported pages are byte-identical to local
            # prefill output (per-row quantization is pure)
            self.prefix_cache.register(req.tokens, req.blocks,
                                       req.num_cached, tenant=req.tenant)
        req.t_decode_start = time.perf_counter_ns()
        _obs_trace.add_complete(
            "request.import", getattr(req, "_t_admit", req.t_queue_start),
            req.t_decode_start, cat="request", tid=req.rid,
            args={"rid": req.rid, "engine": self._name,
                  "covered": req.num_cached})

    def request(self, rid):
        return self._requests[rid]

    def output_tokens(self, rid):
        """np prompt+generated tokens for a request."""
        r = self._requests[rid]
        return np.concatenate(
            [r.prompt, np.asarray(r.output_tokens, np.int32)])

    def release(self, rid):
        """Drop a FINISHED request's bookkeeping (prompt + output token
        arrays). A long-lived server must release requests once their
        outputs are delivered or host memory grows without bound —
        ``generate`` releases automatically; ``stream`` consumers that
        read tokens incrementally can release on the finished
        ``StepOutput``."""
        req = self._requests.get(rid)
        if req is None:
            return
        if not req.finished:
            raise ValueError(f"request {rid} is {req.state}; only "
                             "finished requests can be released")
        del self._requests[rid]

    def cancel(self, rid, reason="cancelled"):
        """Abort a live request: blocks freed (decref under sharing), its
        decode slot recycled for the next admission, and the request
        finishes with ``finish_reason() == reason``. No-op on unknown or
        already-finished ids (cancellation races are benign). Returns
        True when a live request was actually aborted."""
        req = self._requests.get(rid)
        if req is None or req.finished:
            return False
        self._abort(req, reason)
        return True

    def _abort(self, req, reason):
        self.scheduler.abort(req, reason)
        if reason == "timeout":
            _M_DEADLINE.inc(instance=self._name)

    def _expire_deadlines(self, outputs):
        """Abort every queued/running request whose deadline has passed
        (checked once per step, BEFORE admission and decode, so an
        expired request never takes blocks it is about to release). Each
        expiry emits a final ``StepOutput`` (token ``-1``, finished,
        reason ``"timeout"``) so stream consumers see the typed end of
        the partial stream."""
        now = time.time()
        for req in (list(self.scheduler.waiting)
                    + list(self.scheduler.running)):
            if req.deadline is not None and now >= req.deadline:
                self._abort(req, "timeout")
                outputs.append(StepOutput(req.rid, -1, True, "timeout"))

    def has_work(self):
        if self._closed:
            return False
        if self._ingest is not None and self._ingest.pending:
            return True
        return self.scheduler.has_work()

    # ------------------------------------------------------------------
    # compiled graphs
    # ------------------------------------------------------------------
    def _head_fn(self, model):
        def _head(h):
            from ...nn import functional as F

            if model.lm_head is not None:
                return model.lm_head(h)
            return F.linear(h, model.llama.embed_tokens.weight.t())
        return _head

    @staticmethod
    def _arr(x):
        from ...core.tensor import Tensor

        return x._data if isinstance(x, Tensor) else x

    def _make_chunk_fn(self, model, params):
        """Pure chunk-prefill step over ``model``: ``(param_arrays,
        ids [1, C], start, true_upto, tables_row [max_pages], k_pools,
        v_pools, k_scales, v_scales) -> (logits [1, V] at absolute
        position true_upto-1, pools, scale pools)``. ``start`` is the
        block-aligned absolute offset of the chunk (0 for a whole-prompt
        prefill; the shared-prefix boundary or the previous chunk's end
        otherwise); queries attend causally over pool pages
        [0, true_upto) via paged multi-query attention, so one graph per
        chunk-length bucket serves every offset. Quantized caches
        (non-empty scale lists) quantize each page's rows on write and
        store the per-row scales beside the codes (ISSUE 14)."""
        from ...core import state as _state
        from ...core.tensor import Tensor

        block_size = self.block_size
        _head = self._head_fn(model)
        _arr = self._arr

        def chunk_pure(param_arrays, ids, start, true_upto, tables_row,
                       k_pools, v_pools, k_scales, v_scales):
            import jax
            import jax.numpy as jnp

            from ...models.llama import _rope_apply_at
            from ...ops import manipulation as M
            from .kv_cache import quantize_kv_rows
            from .paged_attention import paged_multiquery_attention

            quantized = len(k_scales) > 0
            ks_in = k_scales if quantized else [None] * len(k_pools)
            vs_in = v_scales if quantized else [None] * len(v_pools)
            old = [p._data for p in params]
            try:
                for p, a in zip(params, param_arrays):
                    p._data = a
                with _state.trace_guard():
                    sb = ids.shape[1]
                    pages = sb // block_size
                    start = jnp.asarray(start, jnp.int32)
                    upto = jnp.asarray(true_upto, jnp.int32)
                    page0 = start // block_size
                    tables2 = tables_row[None]  # [1, P]
                    x = model.llama.embed_tokens(Tensor._wrap(ids))
                    cos_t = _arr(model.llama.rope_cos)
                    sin_t = _arr(model.llama.rope_sin)
                    new_k, new_v, new_ks, new_vs = [], [], [], []
                    for layer, kp, vp, ksc, vsc in zip(model.llama.layers,
                                                       k_pools, v_pools,
                                                       ks_in, vs_in):
                        attn = layer.self_attn
                        h = layer.input_layernorm(x)
                        b, s = 1, sb
                        q = M.reshape(attn.q_proj(h),
                                      [b, s, attn.num_heads, attn.head_dim])
                        k = M.reshape(attn.k_proj(h),
                                      [b, s, attn.num_kv_heads,
                                       attn.head_dim])
                        v = M.reshape(attn.v_proj(h),
                                      [b, s, attn.num_kv_heads,
                                       attn.head_dim])
                        qa = _rope_apply_at.raw_fn(_arr(q), cos_t, sin_t,
                                                   start)
                        ka = _rope_apply_at.raw_fn(_arr(k), cos_t, sin_t,
                                                   start)
                        va = _arr(v)
                        for j in range(pages):
                            sl = slice(j * block_size, (j + 1) * block_size)
                            blk = tables_row[page0 + j]
                            if quantized:
                                qk, sk = quantize_kv_rows(ka[0:1, sl])
                                qv, sv = quantize_kv_rows(va[0:1, sl])
                                kp = jax.lax.dynamic_update_slice(
                                    kp, qk, (blk, 0, 0, 0))
                                vp = jax.lax.dynamic_update_slice(
                                    vp, qv, (blk, 0, 0, 0))
                                ksc = jax.lax.dynamic_update_slice(
                                    ksc, sk, (blk, 0, 0))
                                vsc = jax.lax.dynamic_update_slice(
                                    vsc, sv, (blk, 0, 0))
                            else:
                                kp = jax.lax.dynamic_update_slice(
                                    kp, ka[0:1, sl].astype(kp.dtype),
                                    (blk, 0, 0, 0))
                                vp = jax.lax.dynamic_update_slice(
                                    vp, va[0:1, sl].astype(vp.dtype),
                                    (blk, 0, 0, 0))
                        out = paged_multiquery_attention(
                            qa, kp, vp, tables2, upto[None], start[None],
                            scale=1.0 / math.sqrt(attn.head_dim),
                            k_scale=ksc, v_scale=vsc)
                        attn_out = attn.o_proj(
                            M.reshape(Tensor._wrap(out), [b, s, -1]))
                        x = x + attn_out
                        x = x + layer.mlp(layer.post_attention_layernorm(x))
                        new_k.append(kp)
                        new_v.append(vp)
                        if quantized:
                            new_ks.append(ksc)
                            new_vs.append(vsc)
                    h = model.llama.norm(x)
                    h_arr = _arr(h)
                    last = jax.lax.dynamic_slice(
                        h_arr, (0, upto - 1 - start, 0),
                        (1, 1, h_arr.shape[-1]))
                    logits = _head(Tensor._wrap(last))
            finally:
                for p, a in zip(params, old):
                    p._data = a
            return _arr(logits)[:, 0], new_k, new_v, new_ks, new_vs

        return chunk_pure

    def _make_decode_core(self, model):
        """The traced one-token decode body, shared verbatim between the
        plain decode executable and the fused catch-up loop (ISSUE 16):
        the fused path must run the IDENTICAL op sequence per step or
        draft proposals — and therefore acceptance counts — would drift
        between modes. Assumes params are already swapped in and the
        caller is inside ``trace_guard``.

        ``active`` (jnp [B] bool, optional) is the fused decode window's
        EOS-freeze mask (ISSUE 18): rows marked inactive have their K/V
        write redirected to the reserved null block 0 at offset 0 — the
        same scratch target empty slots already write through their
        all-zero table rows — so a finished row can ride out the rest of
        the window without corrupting live pages."""
        from ...core.tensor import Tensor

        block_size = self.block_size
        _head = self._head_fn(model)
        _arr = self._arr

        def core(ids, positions, tables, k_pools, v_pools, ks_in, vs_in,
                 active=None):
            import jax
            import jax.numpy as jnp

            from ...ops import manipulation as M
            from .kv_cache import quantize_kv_rows
            from .paged_attention import paged_decode_attention

            quantized = ks_in[0] is not None if ks_in else False
            bsz = ids.shape[0]
            x = model.llama.embed_tokens(Tensor._wrap(ids))
            cos_t = _arr(model.llama.rope_cos)
            sin_t = _arr(model.llama.rope_sin)
            # batched rope at per-request positions
            c = cos_t[positions][:, None, None, :]
            sn = sin_t[positions][:, None, None, :]
            new_k, new_v, new_ks, new_vs = [], [], [], []
            for layer, kp, vp, ksc, vsc in zip(model.llama.layers,
                                               k_pools, v_pools,
                                               ks_in, vs_in):
                attn = layer.self_attn
                h = layer.input_layernorm(x)
                q = M.reshape(attn.q_proj(h),
                              [bsz, 1, attn.num_heads, attn.head_dim])
                k = M.reshape(attn.k_proj(h),
                              [bsz, 1, attn.num_kv_heads,
                               attn.head_dim])
                v = M.reshape(attn.v_proj(h),
                              [bsz, 1, attn.num_kv_heads,
                               attn.head_dim])

                def rope(t):
                    a = _arr(t)
                    d2 = a.shape[-1] // 2
                    a1, a2 = a[..., :d2], a[..., d2:]
                    cc = c.astype(a.dtype)
                    ss = sn.astype(a.dtype)
                    return jnp.concatenate(
                        [a1 * cc - a2 * ss, a2 * cc + a1 * ss], -1)

                qa, ka, va = rope(q), rope(k), _arr(v)
                blk = tables[jnp.arange(bsz),
                             positions // block_size]
                off = positions % block_size
                if active is not None:
                    # EOS-freeze: park frozen rows' writes on the null
                    # block (reserved, never allocated to a request)
                    blk = jnp.where(active, blk, 0)
                    off = jnp.where(active, off, 0)
                if quantized:
                    qk, sk = quantize_kv_rows(ka)   # [B,1,Hkv,D]
                    qv, sv = quantize_kv_rows(va)
                for i in range(bsz):
                    if quantized:
                        kp = jax.lax.dynamic_update_slice(
                            kp, qk[i:i + 1], (blk[i], off[i], 0, 0))
                        vp = jax.lax.dynamic_update_slice(
                            vp, qv[i:i + 1], (blk[i], off[i], 0, 0))
                        ksc = jax.lax.dynamic_update_slice(
                            ksc, sk[i:i + 1], (blk[i], off[i], 0))
                        vsc = jax.lax.dynamic_update_slice(
                            vsc, sv[i:i + 1], (blk[i], off[i], 0))
                    else:
                        kp = jax.lax.dynamic_update_slice(
                            kp, ka[i:i + 1].astype(kp.dtype),
                            (blk[i], off[i], 0, 0))
                        vp = jax.lax.dynamic_update_slice(
                            vp, va[i:i + 1].astype(vp.dtype),
                            (blk[i], off[i], 0, 0))
                out = paged_decode_attention(
                    qa, kp, vp, tables, positions + 1,
                    scale=1.0 / math.sqrt(attn.head_dim),
                    k_scale=ksc, v_scale=vsc)
                attn_out = attn.o_proj(
                    M.reshape(Tensor._wrap(out), [bsz, 1, -1]))
                x = x + attn_out
                x = x + layer.mlp(layer.post_attention_layernorm(x))
                new_k.append(kp)
                new_v.append(vp)
                if quantized:
                    new_ks.append(ksc)
                    new_vs.append(vsc)
            h = model.llama.norm(x)
            logits = _head(h[:, -1:])
            return _arr(logits)[:, 0], new_k, new_v, new_ks, new_vs

        return core

    def _make_decode_fn(self, model, params):
        """Pure one-token decode over ``model``: ``(param_arrays,
        ids [B, 1], positions [B], tables [B, P], k_pools, v_pools,
        k_scales, v_scales) -> (logits [B, V], pools, scale pools)``.
        Writes each token at ``positions``, attends over ``positions+1``
        ragged lengths. Quantized caches quantize the written row and
        store its per-head scale beside the codes (ISSUE 14)."""
        from ...core import state as _state

        core = self._make_decode_core(model)

        def decode_pure(param_arrays, ids, positions, tables,
                        k_pools, v_pools, k_scales, v_scales):
            quantized = len(k_scales) > 0
            ks_in = k_scales if quantized else [None] * len(k_pools)
            vs_in = v_scales if quantized else [None] * len(v_pools)
            old = [p._data for p in params]
            try:
                for p, a in zip(params, param_arrays):
                    p._data = a
                with _state.trace_guard():
                    logits, new_k, new_v, new_ks, new_vs = core(
                        ids, positions, tables, k_pools, v_pools,
                        ks_in, vs_in)
            finally:
                for p, a in zip(params, old):
                    p._data = a
            return logits, new_k, new_v, new_ks, new_vs

        return decode_pure

    def _make_window_fn(self, model, params, window):
        """Fused k-step decode window (ISSUE 18 tentpole): ``(param_arrays,
        ids [B, 1], positions [B], active [B] bool, budget [B] int32,
        eos_ids [B] int32, tables [B, P], k_pools, v_pools, k_scales,
        v_scales) -> (tokens [B, window] int32, pools, scale pools)``.

        A ``fori_loop`` body runs one full decode iteration — paged
        attention, KV write at the advanced position, in-graph greedy
        argmax — then advances each ACTIVE row's position/input token and
        freezes rows that emitted their ``eos_ids`` entry or exhausted
        their per-row ``budget`` (min(window, tokens remaining), computed
        host-side). Frozen rows write to null block 0 via the decode
        core's ``active`` mask and their token column repeats the frozen
        input id, which the host-side emitter ignores. The graph compiles
        ONCE per (B, window): every input shape is fixed, and the loop
        body reuses the SAME traced core as the per-step path, so greedy
        outputs are bit-identical to k sequential per-step decodes."""
        from ...core import state as _state
        from ...models.llama import greedy_tokens_in_graph

        core = self._make_decode_core(model)

        def window_pure(param_arrays, ids, positions, active, budget,
                        eos_ids, tables, k_pools, v_pools, k_scales,
                        v_scales):
            import jax
            import jax.numpy as jnp

            quantized = len(k_scales) > 0
            ks_in = k_scales if quantized else [None] * len(k_pools)
            vs_in = v_scales if quantized else [None] * len(v_pools)
            old = [p._data for p in params]
            try:
                for p, a in zip(params, param_arrays):
                    p._data = a
                with _state.trace_guard():
                    def one(t, ids, positions, active, budget, toks,
                            kps, vps, kss, vss):
                        lg, kps, vps, kss, vss = core(
                            ids, positions, tables, kps, vps, kss, vss,
                            active=active)
                        nxt = greedy_tokens_in_graph(lg)
                        # frozen rows repeat their input id; the emitter
                        # never reads past a row's budget anyway
                        emitted = jnp.where(active, nxt, ids[:, 0])
                        toks = jax.lax.dynamic_update_slice(
                            toks, emitted[:, None], (0, t))
                        stepped = active.astype(jnp.int32)
                        positions = positions + stepped
                        budget = budget - stepped
                        done = (emitted == eos_ids) | (budget <= 0)
                        active = active & ~done
                        ids = emitted[:, None]
                        return (ids, positions, active, budget, toks,
                                kps, vps, kss, vss)

                    toks0 = jnp.zeros((ids.shape[0], window), jnp.int32)
                    # step 0 outside the loop fixes the carry avals
                    (ids_c, pos_c, act_c, bud_c, toks, kps, vps, kss,
                     vss) = one(0, ids, positions, active, budget, toks0,
                                k_pools, v_pools, ks_in, vs_in)
                    if not quantized:
                        kss, vss = [], []

                    def body(t, carry):
                        (ids_c, pos_c, act_c, bud_c, toks, kps, vps,
                         kss, vss) = carry
                        (ids_c, pos_c, act_c, bud_c, toks, kps, vps,
                         kss, vss) = one(
                            t, ids_c, pos_c, act_c, bud_c, toks, kps,
                            vps,
                            kss if quantized else [None] * len(kps),
                            vss if quantized else [None] * len(vps))
                        if not quantized:
                            kss, vss = [], []
                        return (ids_c, pos_c, act_c, bud_c, toks, kps,
                                vps, kss, vss)

                    (ids_c, pos_c, act_c, bud_c, toks, kps, vps, kss,
                     vss) = jax.lax.fori_loop(
                        1, window, body,
                        (ids_c, pos_c, act_c, bud_c, toks, kps, vps,
                         kss, vss))
            finally:
                for p, a in zip(params, old):
                    p._data = a
            return toks, kps, vps, kss, vss

        return window_pure

    def _make_catchup_fn(self, model, params):
        """Fused ragged draft catch-up (ISSUE 16 perf satellite): one
        ``fori_loop`` graph that replays ``F`` feed tokens through the
        shared decode core — ``(param_arrays, ids [B, F],
        positions [B, F], tables, pools...) -> (last logits [B, V],
        pools...)`` — replacing ``F`` sequential dispatches of the
        single-token draft decode with ONE. Graph size is O(layers),
        independent of ``F``, so the doubling-ladder buckets stay cheap
        to compile. Rows shorter than ``F`` left-pad by repeating their
        first (token, position) feed: rewriting the same token at the
        same position is a deterministic no-op, so padded replays are
        bit-identical to the unfused loop."""
        from ...core import state as _state

        core = self._make_decode_core(model)

        def catchup_pure(param_arrays, ids, positions, tables,
                         k_pools, v_pools, k_scales, v_scales):
            import jax

            quantized = len(k_scales) > 0
            ks_in = k_scales if quantized else [None] * len(k_pools)
            vs_in = v_scales if quantized else [None] * len(v_pools)
            old = [p._data for p in params]
            try:
                for p, a in zip(params, param_arrays):
                    p._data = a
                with _state.trace_guard():
                    def one(t, kps, vps, kss, vss):
                        ids_t = jax.lax.dynamic_slice_in_dim(
                            ids, t, 1, axis=1)
                        pos_t = jax.lax.dynamic_slice_in_dim(
                            positions, t, 1, axis=1)[:, 0]
                        return core(ids_t, pos_t, tables, kps, vps,
                                    kss, vss)

                    # step 0 outside the loop fixes the carry avals
                    lg, kps, vps, kss, vss = one(0, k_pools, v_pools,
                                                 ks_in, vs_in)
                    if not quantized:
                        kss, vss = [], []

                    def body(t, carry):
                        kps, vps, kss, vss, _ = carry
                        lg, kps, vps, kss, vss = one(
                            t, kps, vps,
                            kss if quantized else [None] * len(kps),
                            vss if quantized else [None] * len(vps))
                        if not quantized:
                            kss, vss = [], []
                        return (kps, vps, kss, vss, lg)

                    kps, vps, kss, vss, lg = jax.lax.fori_loop(
                        1, ids.shape[1], body, (kps, vps, kss, vss, lg))
            finally:
                for p, a in zip(params, old):
                    p._data = a
            return lg, kps, vps, kss, vss

        return catchup_pure

    def _make_verify_fn(self, model, params):
        """Pure speculative verify over ``model``: ``(param_arrays,
        ids [B, K+1], positions [B], tables [B, P], draft_toks [B, K],
        k_pools, v_pools, k_scales, v_scales) -> (accept_counts [B],
        next_tokens [B], pools, scale pools)``. ``ids[:, 0]`` is each
        request's last committed token at absolute position
        ``positions``; one batched multi-query paged-attention step
        scores all K+1 positions, writes their K/V, and counts in-graph
        how many draft tokens match the target's greedy argmax (the
        accept rule that keeps outputs bit-exact)."""
        from ...core import state as _state
        from ...core.tensor import Tensor

        block_size = self.block_size
        _head = self._head_fn(model)
        _arr = self._arr

        def verify_pure(param_arrays, ids, positions, tables, draft_toks,
                        k_pools, v_pools, k_scales, v_scales):
            import jax
            import jax.numpy as jnp

            from ...ops import manipulation as M
            from .kv_cache import quantize_kv_rows
            from .paged_attention import paged_multiquery_attention

            quantized = len(k_scales) > 0
            ks_in = k_scales if quantized else [None] * len(k_pools)
            vs_in = v_scales if quantized else [None] * len(v_pools)
            old = [p._data for p in params]
            try:
                for p, a in zip(params, param_arrays):
                    p._data = a
                with _state.trace_guard():
                    bsz, t_q = ids.shape
                    x = model.llama.embed_tokens(Tensor._wrap(ids))
                    cos_t = _arr(model.llama.rope_cos)
                    sin_t = _arr(model.llama.rope_sin)
                    pos_grid = (positions[:, None]
                                + jnp.arange(t_q, dtype=jnp.int32)[None])
                    c = cos_t[pos_grid][:, :, None, :]
                    sn = sin_t[pos_grid][:, :, None, :]
                    new_k, new_v, new_ks, new_vs = [], [], [], []
                    for layer, kp, vp, ksc, vsc in zip(model.llama.layers,
                                                       k_pools, v_pools,
                                                       ks_in, vs_in):
                        attn = layer.self_attn
                        h = layer.input_layernorm(x)
                        q = M.reshape(attn.q_proj(h),
                                      [bsz, t_q, attn.num_heads,
                                       attn.head_dim])
                        k = M.reshape(attn.k_proj(h),
                                      [bsz, t_q, attn.num_kv_heads,
                                       attn.head_dim])
                        v = M.reshape(attn.v_proj(h),
                                      [bsz, t_q, attn.num_kv_heads,
                                       attn.head_dim])

                        def rope(t):
                            a = _arr(t)
                            d2 = a.shape[-1] // 2
                            a1, a2 = a[..., :d2], a[..., d2:]
                            cc = c.astype(a.dtype)
                            ss = sn.astype(a.dtype)
                            return jnp.concatenate(
                                [a1 * cc - a2 * ss, a2 * cc + a1 * ss], -1)

                        qa, ka, va = rope(q), rope(k), _arr(v)
                        blk = tables[jnp.arange(bsz)[:, None],
                                     pos_grid // block_size]
                        off = pos_grid % block_size
                        if quantized:
                            qk, sk = quantize_kv_rows(ka)  # [B,T,Hkv,D]
                            qv, sv = quantize_kv_rows(va)
                        for i in range(bsz):
                            for t in range(t_q):
                                if quantized:
                                    kp = jax.lax.dynamic_update_slice(
                                        kp, qk[i:i + 1, t:t + 1],
                                        (blk[i, t], off[i, t], 0, 0))
                                    vp = jax.lax.dynamic_update_slice(
                                        vp, qv[i:i + 1, t:t + 1],
                                        (blk[i, t], off[i, t], 0, 0))
                                    ksc = jax.lax.dynamic_update_slice(
                                        ksc, sk[i:i + 1, t:t + 1],
                                        (blk[i, t], off[i, t], 0))
                                    vsc = jax.lax.dynamic_update_slice(
                                        vsc, sv[i:i + 1, t:t + 1],
                                        (blk[i, t], off[i, t], 0))
                                else:
                                    kp = jax.lax.dynamic_update_slice(
                                        kp,
                                        ka[i:i + 1, t:t + 1].astype(
                                            kp.dtype),
                                        (blk[i, t], off[i, t], 0, 0))
                                    vp = jax.lax.dynamic_update_slice(
                                        vp,
                                        va[i:i + 1, t:t + 1].astype(
                                            vp.dtype),
                                        (blk[i, t], off[i, t], 0, 0))
                        out = paged_multiquery_attention(
                            qa, kp, vp, tables, positions + t_q, positions,
                            scale=1.0 / math.sqrt(attn.head_dim),
                            k_scale=ksc, v_scale=vsc)
                        attn_out = attn.o_proj(
                            M.reshape(Tensor._wrap(out), [bsz, t_q, -1]))
                        x = x + attn_out
                        x = x + layer.mlp(layer.post_attention_layernorm(x))
                        new_k.append(kp)
                        new_v.append(vp)
                        if quantized:
                            new_ks.append(ksc)
                            new_vs.append(vsc)
                    h = model.llama.norm(x)
                    logits = _arr(_head(h))          # [B, K+1, V]
                    tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    # in-graph accept: 1s until the first draft/target
                    # mismatch; next token = target argmax at the first
                    # rejected position (or the bonus position on full
                    # accept) — exactly sequential greedy, verified at once
                    eq = (tgt[:, :t_q - 1] == draft_toks).astype(jnp.int32)
                    acc = jnp.cumprod(eq, axis=1)
                    counts = jnp.sum(acc, axis=1)
                    nxt = jnp.take_along_axis(
                        tgt, counts[:, None], axis=1)[:, 0]
            finally:
                for p, a in zip(params, old):
                    p._data = a
            return counts, nxt, new_k, new_v, new_ks, new_vs

        return verify_pure

    def _build_jits(self):
        from ...distributed.plan import compile_step_with_plan

        # process-spanning mesh: pin EVERY output replicated (a single
        # PartitionSpec leaf is a prefix pytree covering all outputs).
        # Logits/tokens must be replicated so every rank's host fetch
        # reads the same value from its addressable shard; pools ride
        # along replicated, which costs an allgather on the sharded
        # attention writes but keeps the engine's rebind/donate contract
        # rank-agnostic.
        mp_out = None
        if self._mp:
            from jax.sharding import PartitionSpec
            mp_out = PartitionSpec()
        # scale pools donate beside the payload pools (empty pytrees on
        # the fp path — a zero-leaf donation is a no-op)
        self._prefill_jit = compile_step_with_plan(
            self._make_chunk_fn(self.model, self._params), self._plan,
            name=self._prefill_name, donate_argnums=(5, 6, 7, 8),
            out_specs=mp_out)
        self._decode_jit = compile_step_with_plan(
            self._make_decode_fn(self.model, self._params), self._plan,
            name=self._decode_name, donate_argnums=(4, 5, 6, 7),
            out_specs=mp_out)
        if self._in_graph:
            self._window_jit = compile_step_with_plan(
                self._make_window_fn(self.model, self._params,
                                     self._decode_window),
                self._plan, name=self._window_name,
                donate_argnums=(7, 8, 9, 10), out_specs=mp_out)
        if self.draft_model is not None:
            self._draft_prefill_jit = compile_step_with_plan(
                self._make_chunk_fn(self.draft_model, self._draft_params),
                self._plan, name=self._draft_prefill_name,
                donate_argnums=(5, 6, 7, 8))
            self._draft_decode_jit = compile_step_with_plan(
                self._make_decode_fn(self.draft_model, self._draft_params),
                self._plan, name=self._draft_decode_name,
                donate_argnums=(4, 5, 6, 7))
            self._verify_jit = compile_step_with_plan(
                self._make_verify_fn(self.model, self._params), self._plan,
                name=self._verify_name, donate_argnums=(5, 6, 7, 8))

    def _catchup_jit(self, F):
        """The fused catch-up executable for feed-length bucket ``F``
        (compiled on first use per rung; the fori_loop body makes each
        rung's graph O(layers), so the ladder stays cheap)."""
        jit = self._catchup_jits.get(F)
        if jit is None:
            from ...distributed.plan import compile_step_with_plan
            jit = compile_step_with_plan(
                self._make_catchup_fn(self.draft_model,
                                      self._draft_params),
                self._plan, name=f"{self._draft_decode_name}_catchup{F}",
                donate_argnums=(4, 5, 6, 7))
            self._catchup_jits[F] = jit
        return jit

    # ------------------------------------------------------------------
    # the scheduler tick
    # ------------------------------------------------------------------
    def _tables(self):
        """Device block-table array for the decode-ready slots, cached
        against the scheduler's table version + slot readiness (ISSUE 11
        satellite: steady-state decode re-uploads nothing). Slots that are
        empty OR still mid-prefill map to the null block: the decode graph
        writes a K/V row for EVERY batch row, and an inactive row's write
        must land in the null block — pointing it at a prefilling
        request's real blocks would corrupt its just-written pages."""
        sched = self.scheduler
        mask = tuple(r is not None and not r.prefilling
                     for r in sched.slots)
        key = (sched.version, mask)
        if key != self._tables_version:
            lists = [(r.blocks if ok else [])
                     for ok, r in zip(mask, sched.slots)]
            tbl = self.cache.table_array(lists, self.max_pages)
            if self._mp:
                tbl = self._g(np.asarray(tbl))
            self._tables_dev = tbl
            self._tables_version = key
        return self._tables_dev

    def _drain_cow(self):
        """Execute queued copy-on-write page copies (device-side) before
        the next pool write can touch the replaced blocks."""
        for src, dst in self.scheduler.pending_cow:
            self.cache.copy_block(src, dst)
            if self.draft_model is not None:
                self.draft_cache.copy_block(src, dst)
        self.scheduler.pending_cow.clear()

    def _run_chunk(self, req, start, take, outputs):
        """One block-aligned prefill chunk: materialize ``take`` tokens of
        ``req`` starting at ``start`` in the pool(s); on the final chunk,
        sample the first output token from the chunk's last-position
        logits."""
        import jax.numpy as jnp

        staged = getattr(req, "_staged", None)
        if staged is None or staged[2] != req.prefill_upto:
            self._stage_request(req)  # re-prefill after eviction
            staged = req._staged
        ids_dev, bucket, _true_len = staged
        # chunk length: always a LADDER RUNG (one compiled graph per rung
        # — an arbitrary C = bucket - start remainder would compile a
        # fresh executable per distinct prefix-match offset, the
        # recompile-per-shape cliff). When no rung covering ``take`` fits
        # the staged room, cap this chunk at the largest rung that does;
        # the remainder continues next step (progress >= one block).
        room = bucket - start
        C = None
        for b in self.prefill_buckets:
            if b >= take and b <= room:
                C = b
                break
        if C is None:
            C = max(b for b in self.prefill_buckets if b <= room)
            take = min(take, C)
        ids_chunk = ids_dev[:, start:start + C]
        tables_row = np.zeros(self.max_pages, np.int32)
        nblk = min(len(req.blocks), self.max_pages)
        tables_row[:nblk] = req.blocks[:nblk]
        tables_dev = self._g(tables_row)
        start_a, upto_a = np.int32(start), np.int32(start + take)
        if self._mp:
            # scalars too: a host scalar beside global-mesh arrays would
            # make jit refuse the mixed-device call (ids_chunk is a view
            # of the staged ids, already replicated on the global mesh)
            start_a, upto_a = self._g(start_a), self._g(upto_a)
        cache = self.cache
        (logits, cache.k, cache.v, cache.k_scale, cache.v_scale) = \
            self._prefill_jit(
                [p._data for p in self._params], ids_chunk,
                start_a, upto_a, tables_dev,
                cache.k, cache.v, cache.k_scale, cache.v_scale)
        if self.draft_model is not None:
            # mirror every target chunk into the draft pools: the draft
            # proposes continuations over the same block tables, so its
            # cache must hold the same prefix
            dc = self.draft_cache
            (_, dc.k, dc.v, dc.k_scale, dc.v_scale) = \
                self._draft_prefill_jit(
                    [p._data for p in self._draft_params], ids_chunk,
                    np.int32(start), np.int32(start + take), tables_dev,
                    dc.k, dc.v, dc.k_scale, dc.v_scale)
            req.draft_cached = start + take
        req.num_cached = start + take
        _M_PREFILL_CHUNKS.inc(instance=self._name)
        # QoS accounting (ISSUE 17): prefill work charges the tenant's
        # quota/vtime as it is SERVED, chunk by chunk
        self.scheduler.note_served(req, take)
        if self.prefix_cache is not None:
            # publish the identity of every full block now materialized so
            # later admissions (and this request's own re-prefill after an
            # eviction) can share them
            self.prefix_cache.register(req.tokens, req.blocks,
                                       req.num_cached, tenant=req.tenant)
        if req.num_cached >= req.prefill_upto:
            req.prefilling = False
            self.stats_extra["prefills"] += 1
            _M_PREFILLS.inc(instance=self._name)
            # the _emit below fetches logits (the existing sync point);
            # the prefill span closes right after it
            outputs.extend(self._emit(req, self._fetch(logits)[0]))
            req.t_decode_start = time.perf_counter_ns()
            _obs_trace.add_complete(
                "request.prefill",
                getattr(req, "_t_admit", req.t_queue_start),
                req.t_decode_start, cat="request", tid=req.rid,
                args={"rid": req.rid, "engine": self._name,
                      "bucket": bucket, "true_len": req.prefill_upto})

    def step(self):
        """One engine tick: drain ingest, admit, advance chunked prefills
        under the token budget, one decode (or speculative verify) for all
        decode-ready slots. Returns the ``StepOutput`` tokens produced."""
        import jax.numpy as jnp

        self._ensure_open()
        if self._decode_jit is None:
            self._build_jits()
        sched = self.scheduler
        if self._ingest is not None:
            # block (briefly) only when the scheduler would otherwise spin
            # empty while requests are in flight on the ingest thread
            for req in self._ingest.drain(wait=not sched.has_work()):
                # a request cancelled/expired while still on the ingest
                # thread is already FINISHED — queueing it would let
                # pick_prefills admit a dead request
                if req.finished:
                    continue
                if not hasattr(req, "_staged"):  # ingest thread died
                    self._stage_request(req)
                sched.waiting.append(req)
        outputs = []
        # deadline scan BEFORE admission/decode: an expired request must
        # never be admitted or decoded one last time, and its freed
        # blocks/slot are available to this very step's admissions
        self._expire_deadlines(outputs)
        if not sched.has_work():
            return outputs
        self.stats_extra["steps"] += 1

        # -- admission ---------------------------------------------------
        for slot, req in sched.pick_prefills():
            # queued->running transition: the span closes here, at a point
            # where the host is already doing admission bookkeeping
            req._t_admit = time.perf_counter_ns()
            _obs_trace.add_complete(
                "request.queued", req.t_queue_start, req._t_admit,
                cat="request", tid=req.rid,
                args={"rid": req.rid, "engine": self._name,
                      "evictions": req.evictions})
            if req.preloaded is not None:
                # disaggregated handoff OR tier revival: imported pages
                # land in the freshly allocated blocks before this step
                # decodes
                self._adopt_preloaded(req)
        self._drain_revives()

        # -- chunked prefill (budgeted; interleaves with decode below) ---
        for req, start, take in sched.prefill_work(
                self.max_prefill_tokens_per_step):
            self._run_chunk(req, start, take, outputs)

        if self.prefill_only:
            # disaggregated prefill worker: decode-ready requests wait
            # for export_kv_pages + cancel; nothing decodes here
            self._update_gauges()
            return outputs

        # -- decode ------------------------------------------------------
        sched.ensure_decode_room(
            extra=self._spec_k,
            extra_for=(self._window_extra if self._decode_window > 1
                       else None))
        self._drain_cow()
        ready = [(i, r) for i, r in enumerate(sched.slots)
                 if r is not None and not r.prefilling]
        if ready:
            sampled = any(r.sampling.do_sample for _, r in ready)
            if self._spec_k:
                self._spec_step(ready, outputs)
            elif self._in_graph and not sampled:
                self._window_step(ready, outputs)
            else:
                if self._in_graph and not self._warned_do_sample:
                    self._warned_do_sample = True
                    warnings.warn(
                        f"{self._name}: do_sample=True requests keep the "
                        "host sampling path (per-request numpy RNG); "
                        "device-resident decode degrades to per-step "
                        "host sampling while any is in the batch",
                        RuntimeWarning)
                B = self.max_batch_size
                ids = np.zeros((B, 1), np.int32)
                positions = np.zeros(B, np.int32)
                for i, req in ready:
                    ids[i, 0] = req.last_token
                    positions[i] = req.num_cached
                c = self.cache
                (logits, c.k, c.v, c.k_scale, c.v_scale) = \
                    self._decode_jit(
                        [p._data for p in self._params], self._g(ids),
                        self._g(positions), self._tables(),
                        c.k, c.v, c.k_scale, c.v_scale)
                logits = self._fetch(logits)
                _M_HOST_SYNCS.inc(instance=self._name)
                _M_FETCH_BYTES.inc(logits.nbytes, instance=self._name)
                for i, req in ready:
                    req.num_cached += 1
                    outputs.extend(self._emit(req, logits[i]))
        self._maybe_autosave_store()
        self._update_gauges()
        return outputs

    def _window_extra(self, req):
        """Lookahead positions ``ensure_decode_room`` must reserve for
        ``req`` before a fused window: the window writes at most
        ``min(k, tokens remaining)`` new positions, the first of which
        the base room check already covers."""
        remaining = req.sampling.max_new_tokens - len(req.output_tokens)
        return max(min(self._decode_window, remaining) - 1, 0)

    def _window_step(self, ready, outputs):
        """Device-resident decode for all decode-ready slots (ISSUE 18):
        one fused ``decode_steps_per_sync``-step dispatch, one ``[B, k]``
        int32 token fetch, then batched host-side emission. Greedy only —
        ``step`` routes batches containing ``do_sample`` requests to the
        per-step host path."""
        import jax.numpy as jnp

        B, k = self.max_batch_size, self._decode_window
        ids = np.zeros((B, 1), np.int32)
        positions = np.zeros(B, np.int32)
        active = np.zeros(B, np.bool_)
        budget = np.zeros(B, np.int32)
        eos_ids = np.full(B, -1, np.int32)
        for i, req in ready:
            ids[i, 0] = req.last_token
            positions[i] = req.num_cached
            active[i] = True
            remaining = (req.sampling.max_new_tokens
                         - len(req.output_tokens))
            budget[i] = min(k, remaining)
            if req.sampling.eos_token_id is not None:
                eos_ids[i] = req.sampling.eos_token_id
        c = self.cache
        (toks, c.k, c.v, c.k_scale, c.v_scale) = self._window_jit(
            [p._data for p in self._params], self._g(ids),
            self._g(positions), self._g(active),
            self._g(budget), self._g(eos_ids), self._tables(),
            c.k, c.v, c.k_scale, c.v_scale)
        toks = self._fetch(toks)
        _M_HOST_SYNCS.inc(instance=self._name)
        _M_FETCH_BYTES.inc(toks.nbytes, instance=self._name)
        for i, req in ready:
            self._emit_window(req, toks[i], outputs)

    def _drain_revives(self):
        """Land this step's host-tier prefix hits (queued by the
        scheduler's ``match_with_tier``) in their freshly allocated
        blocks and publish the chain identities so the NEXT admission
        shares them device-side. A hash that vanished from the tier
        between match and drain (LRU pressure from a same-step spill)
        degrades to prefilling that span — and everything after it, since
        a chain with a hole is no chain."""
        sched = self.scheduler
        if not sched.pending_revive:
            return
        # gather each request's revivable span first, then land it as ONE
        # batched import: a functional pool update copies the whole pool,
        # so importing block-by-block would cost O(span * pool) instead
        # of O(pool)
        spans = {}  # rid -> (req, [(block, h, pages), ...])
        dead = set()  # rids whose chain broke mid-revive
        for req, block, h in sched.pending_revive:
            if req.finished:
                # aborted between match and drain (deadline expiry): its
                # blocks are already freed, so indexing them would throw.
                # ``Scheduler.abort`` purges these entries and their tier
                # pins itself; this is belt-and-braces for direct aborts.
                self.kv_tier.pop_prefix(h)
                continue
            idx = req.blocks.index(block)
            if req.rid in dead:
                req.num_cached = min(req.num_cached, idx * self.block_size)
                self.kv_tier.pop_prefix(h)  # unreachable behind the hole
                continue
            pages = self.kv_tier.pop_prefix(h)
            if pages is None:
                dead.add(req.rid)
                req.num_cached = min(req.num_cached, idx * self.block_size)
                continue
            spans.setdefault(req.rid, (req, []))[1].append((block, h,
                                                            pages))
        sched.pending_revive.clear()
        for req, parts in spans.values():
            t0 = time.perf_counter()
            blocks = [b for b, _, _ in parts]
            merged = dict(parts[0][2])
            merged["covered"] = len(parts) * self.block_size
            # each part's seal was verified at pop_prefix; the merged
            # span is a fresh in-memory dict, not a stored payload
            merged.pop("crc", None)
            if len(parts) > 1:
                for key in ("k", "v", "k_scale", "v_scale"):
                    if key in merged:
                        merged[key] = np.concatenate(
                            [p[key] for _, _, p in parts], axis=1)
            self.cache.import_request_pages(blocks, merged)
            for b, h, _ in parts:
                self.prefix_cache.adopt(b, h, tenant=req.tenant)
            nbytes = sum(int(v.nbytes) for v in merged.values()
                         if isinstance(v, np.ndarray))
            _M_REVIVES.inc(len(parts), instance=self._name)
            _M_REVIVE_BYTES.inc(nbytes, instance=self._name)
            _H_REVIVE_MS.observe((time.perf_counter() - t0) * 1e3,
                                 instance=self._name)

    def _update_gauges(self):
        # utilization gauges: free-list arithmetic the host already holds
        usable = max(self.cache.num_blocks - 1, 1)
        _G_KV_UTIL.set(1.0 - self.cache.allocator.num_free / usable,
                       instance=self._name)
        _G_OCCUPANCY.set(len(self.scheduler.running) / self.max_batch_size,
                         instance=self._name)
        if self.cache.quantized:
            _G_QUANT_BLOCKS.set(usable - self.cache.allocator.num_free,
                                instance=self._name)

    # ------------------------------------------------------------------
    # speculative decoding
    # ------------------------------------------------------------------
    def _draft_propose(self, ready, tables):
        """Catch the draft pools up to every request's committed tokens,
        then propose ``spec_k`` greedy draft tokens per request. Returns
        drafts [B, K] (rows of non-ready slots are zeros/ignored)."""
        import jax.numpy as jnp

        B, K = self.max_batch_size, self._spec_k
        toks = {r.rid: r.tokens for _, r in ready}
        feeds = {}
        F = 1
        for _, r in ready:
            lo = min(r.draft_cached, r.num_tokens - 1)
            fs = list(range(lo, r.num_tokens))
            feeds[r.rid] = fs
            F = max(F, len(fs))
        if self._fuse_catchup and F > 1:
            # fused catch-up (ISSUE 16 perf satellite): bucket F up to
            # the next power of two — the extra left-pad steps rewrite
            # the first feed in place, a deterministic no-op — and replay
            # the whole ragged window in ONE fori_loop dispatch instead
            # of F sequential single-token dispatches
            Fb = 1 << (F - 1).bit_length()
            for rid, fs in feeds.items():
                feeds[rid] = [fs[0]] * (Fb - len(fs)) + fs
            ids = np.zeros((B, Fb), np.int32)
            pos = np.zeros((B, Fb), np.int32)
            for i, r in ready:
                for t, j in enumerate(feeds[r.rid]):
                    ids[i, t] = toks[r.rid][j]
                    pos[i, t] = j
            dc = self.draft_cache
            (logits, dc.k, dc.v, dc.k_scale, dc.v_scale) = \
                self._catchup_jit(Fb)(
                    [p._data for p in self._draft_params],
                    jnp.asarray(ids), jnp.asarray(pos), tables,
                    dc.k, dc.v, dc.k_scale, dc.v_scale)
        else:
            for rid, fs in feeds.items():
                # left-pad by repeating the first feed: re-writing the
                # same token at the same position is a deterministic
                # no-op, so the ragged catch-up runs as F uniform batched
                # steps
                feeds[rid] = [fs[0]] * (F - len(fs)) + fs
            logits = None
            for t in range(F):
                ids = np.zeros((B, 1), np.int32)
                pos = np.zeros(B, np.int32)
                for i, r in ready:
                    j = feeds[r.rid][t]
                    ids[i, 0] = toks[r.rid][j]
                    pos[i] = j
                dc = self.draft_cache
                (logits, dc.k, dc.v, dc.k_scale, dc.v_scale) = \
                    self._draft_decode_jit(
                        [p._data for p in self._draft_params],
                        jnp.asarray(ids), jnp.asarray(pos), tables,
                        dc.k, dc.v, dc.k_scale, dc.v_scale)
        prev = np.asarray(logits)
        _M_HOST_SYNCS.inc(instance=self._name)
        _M_FETCH_BYTES.inc(prev.nbytes, instance=self._name)
        drafts = np.zeros((B, K), np.int32)
        for kstep in range(K):
            for i, r in ready:
                drafts[i, kstep] = int(prev[i].argmax())
            if kstep + 1 < K:
                ids = np.zeros((B, 1), np.int32)
                pos = np.zeros(B, np.int32)
                for i, r in ready:
                    ids[i, 0] = drafts[i, kstep]
                    pos[i] = r.num_tokens + kstep
                dc = self.draft_cache
                (prev, dc.k, dc.v, dc.k_scale, dc.v_scale) = \
                    self._draft_decode_jit(
                        [p._data for p in self._draft_params],
                        jnp.asarray(ids), jnp.asarray(pos), tables,
                        dc.k, dc.v, dc.k_scale, dc.v_scale)
                prev = np.asarray(prev)
                _M_HOST_SYNCS.inc(instance=self._name)
                _M_FETCH_BYTES.inc(prev.nbytes, instance=self._name)
        for _, r in ready:
            # positions 0 .. num_tokens+K-2 now hold draft K/V
            r.draft_cached = r.num_tokens + K - 1
        return drafts

    def _spec_step(self, ready, outputs):
        """One speculative decode step for the decode-ready slots: draft
        proposes K tokens, one multi-query verify scores K+1 positions,
        accepted tokens emit in order (bit-exact vs sequential greedy),
        rollback rewinds cached lengths and frees over-allocated tail
        blocks on rejection."""
        import jax.numpy as jnp

        B, K = self.max_batch_size, self._spec_k
        tables = self._tables()
        drafts = self._draft_propose(ready, tables)
        _M_SPEC_PROPOSED.inc(K * len(ready), instance=self._name)
        ids_v = np.zeros((B, K + 1), np.int32)
        pos_v = np.zeros(B, np.int32)
        n_old = {}
        for i, r in ready:
            ids_v[i, 0] = r.last_token
            ids_v[i, 1:] = drafts[i]
            pos_v[i] = r.num_cached
            n_old[r.rid] = r.num_tokens
        c = self.cache
        (counts, nxt, c.k, c.v, c.k_scale, c.v_scale) = self._verify_jit(
            [p._data for p in self._params], jnp.asarray(ids_v),
            jnp.asarray(pos_v), tables, jnp.asarray(drafts[:, :K]),
            c.k, c.v, c.k_scale, c.v_scale)
        counts = np.asarray(counts)
        nxt = np.asarray(nxt)
        _M_HOST_SYNCS.inc(instance=self._name)
        _M_FETCH_BYTES.inc(counts.nbytes + nxt.nbytes,
                           instance=self._name)
        accepted = 0
        for i, r in ready:
            a = int(counts[i])
            emitted = [int(drafts[i, j]) for j in range(a)] + [int(nxt[i])]
            m = 0
            for tok in emitted:
                outputs.extend(self._emit_token(r, tok))
                m += 1
                if r.finished:
                    break
            accepted += min(a, m)
            if r.finished:
                continue
            # rollback: positions past the kept tokens hold rejected-draft
            # K/V — masked by context_lens until overwritten. Rewind the
            # cached lengths and trim lookahead blocks the shorter window
            # no longer needs.
            n0 = n_old[r.rid]
            r.num_cached = r.num_tokens - 1
            r.draft_cached = min(n0 + min(min(a, m), K - 1), r.num_tokens)
            self.scheduler.trim_to_capacity(r, extra=K)
        _M_SPEC_ACCEPTED.inc(accepted, instance=self._name)
        prop = _M_SPEC_PROPOSED.value(instance=self._name)
        if prop:
            _G_SPEC_RATIO.set(
                _M_SPEC_ACCEPTED.value(instance=self._name) / prop,
                instance=self._name)

    # ------------------------------------------------------------------
    # token emission
    # ------------------------------------------------------------------
    def _emit(self, req, row):
        """Sample the next token for ``req`` from logits ``row`` [V] and
        commit it. Returns [StepOutput]."""
        s = req.sampling
        if self.capture_logits:
            # last sampled-from logits row, kept for the quantization
            # tolerance tests (bounded logit delta vs the fp32 engine) and
            # as a logprobs hook; [V] f32, overwritten per emission,
            # dropped with the request at release(). Opt-in (ISSUE 18):
            # the copy is a [V] f32 D2H pinned per live request.
            req.last_logits = np.asarray(row)
        tok = int(self._sample_next_tokens(
            row[None], do_sample=s.do_sample, temperature=s.temperature,
            top_k=s.top_k, top_p=s.top_p, rng=req._rng)[0])
        return self._emit_token(req, tok)

    def _emit_window(self, req, toks, outputs):
        """Commit one fused window's tokens for ``req`` (``toks`` is the
        request's ``[k]`` int32 row from the window fetch) in a single
        batched pass: the accept scan mirrors the in-graph EOS-freeze
        (stop after eos or the max_new_tokens budget), QoS charges ONCE
        for the whole window, and the single window-boundary clock read
        is spread over the accepted tokens as m observations of Δt/m so
        ITL percentiles stay per-token comparable (see DESIGN_DECISIONS
        "Device-resident decode"). Appends StepOutputs to ``outputs``."""
        s = req.sampling
        accepted = []
        for t in toks:
            accepted.append(int(t))
            if len(req.output_tokens) + len(accepted) >= s.max_new_tokens:
                break
            if s.eos_token_id is not None and int(t) == s.eos_token_id:
                break
        m = len(accepted)
        req.output_tokens.extend(accepted)
        req.num_cached += m
        self.stats_extra["tokens_out"] += m
        # QoS accounting (ISSUE 17): the tenant's quota/vtime charge moves
        # to the window boundary — one charge of m tokens
        self.scheduler.note_served(req, m)
        now = time.perf_counter_ns()
        _M_TOKENS.inc(m, instance=self._name)
        spread = m
        if req.t_first_token is None:
            # first emission happens in decode only for imported requests
            # (disagg handoff / tier revival); TTFT lands on the first
            # token, ITL on the rest
            req.t_first_token = now
            if req.t_submit is not None:
                _H_TTFT.observe((now - req.t_submit) / 1e6,
                                instance=self._name)
            spread = m - 1
        if spread > 0 and req.t_last_token is not None:
            dt_ms = (now - req.t_last_token) / 1e6 / spread
            for _ in range(spread):
                _H_ITL.observe(dt_ms, instance=self._name)
        req.t_last_token = now
        done = req.should_finish()
        if done:
            self.scheduler.finish(req)
            start = req.t_decode_start or req.t_first_token or now
            _obs_trace.add_complete(
                "request.decode", start, now, cat="request", tid=req.rid,
                args={"rid": req.rid, "engine": self._name,
                      "tokens": len(req.output_tokens),
                      "finish_reason": req.finish_reason()})
        for j, tok in enumerate(accepted):
            last = j == m - 1
            outputs.append(StepOutput(
                req.rid, int(tok), done and last,
                req.finish_reason() if done and last else None))

    def _emit_token(self, req, tok):
        """Commit one already-chosen token (sampled host-side, or accepted
        by the speculative verify): append it, observe latency metrics,
        finish bookkeeping. Returns [StepOutput]."""
        req.output_tokens.append(int(tok))
        self.stats_extra["tokens_out"] += 1
        # QoS accounting (ISSUE 17): each emitted token charges the
        # tenant's quota and advances its fair-queueing virtual time
        self.scheduler.note_served(req, 1)
        # latency observation at the emission point — the host just
        # fetched logits/verify results anyway, so the clock read is free
        now = time.perf_counter_ns()
        _M_TOKENS.inc(instance=self._name)
        if req.t_first_token is None:
            req.t_first_token = now
            if req.t_submit is not None:
                _H_TTFT.observe((now - req.t_submit) / 1e6,
                                instance=self._name)
        elif req.t_last_token is not None:
            _H_ITL.observe((now - req.t_last_token) / 1e6,
                           instance=self._name)
        req.t_last_token = now
        done = req.should_finish()
        if done:
            self.scheduler.finish(req)
            start = req.t_decode_start or req.t_first_token or now
            _obs_trace.add_complete(
                "request.decode", start, now, cat="request", tid=req.rid,
                args={"rid": req.rid, "engine": self._name,
                      "tokens": len(req.output_tokens),
                      "finish_reason": req.finish_reason()})
        return [StepOutput(req.rid, int(tok), done,
                           req.finish_reason() if done else None)]

    def stream(self):
        """Yield ``StepOutput`` s until the engine drains. Raises
        :class:`EngineClosedError` (instead of silently yielding nothing
        or hanging on a joined ingest thread) when the engine is
        closed."""
        self._ensure_open()
        while self.has_work():
            yield from self.step()

    def generate(self, prompts, sampling: SamplingParams | None = None,
                 deadline=None):
        """Convenience batch API: submit every prompt, run to completion,
        return the full token arrays (prompt + generated) in order.
        With ``deadline`` set, a request the deadline kills raises
        :class:`RequestTimeoutError` after the batch drains (partial
        outputs are only reachable through ``stream()``)."""
        self._ensure_open()
        rids = []
        try:
            for p in prompts:
                rids.append(self.add_request(
                    p, dataclasses.replace(sampling) if sampling else None,
                    deadline=deadline))
        except BaseException:
            # a mid-batch admission failure (e.g. the deadline expiring
            # between prompts) must not orphan the already-admitted
            # requests in the queue — they would decode to completion on
            # the NEXT stream() and leak bookkeeping forever
            for r in rids:
                self.cancel(r)
                self.release(r)
            raise
        for _ in self.stream():
            pass
        timed_out = [r for r in rids
                     if self._requests[r].abort_reason == "timeout"]
        if timed_out:
            for r in rids:
                self.release(r)
            raise RequestTimeoutError(
                f"{len(timed_out)} of {len(rids)} requests hit the "
                f"deadline mid-generation: rids {timed_out}",
                rid=timed_out[0], deadline=deadline)
        outs = [self.output_tokens(r) for r in rids]
        for r in rids:
            self.release(r)
        return outs

    # ------------------------------------------------------------------
    # weights + teardown
    # ------------------------------------------------------------------
    def audit_weights(self):
        """Weight integrity re-audit (ISSUE 20): re-hash the live
        parameters and compare against the fingerprint captured at
        construction / last ``reload_weights``. Returns True when they
        match; False — counting
        ``serving_weight_audit_failures_total`` — when the weights
        changed IN PLACE (silent corruption; the caller's degrade is
        ``reload_weights`` from the artifact + a suspicion charge). The
        first call on an engine built without ``weight_audit=True``
        captures the reference instead of comparing."""
        fp = weights_fingerprint(self.model)
        self._weight_audits += 1
        if self._weight_audit_ref is None:
            self._weight_audit_ref = fp
            return True
        if fp != self._weight_audit_ref:
            _M_WEIGHT_AUDIT_FAIL.inc(instance=self._name)
            return False
        return True

    def reload_weights(self, source):
        """Hot-reload weights without recompiling: from a
        ``CheckpointManager`` (prefers ``latest_healthy_step()``, falls
        back to ``latest_valid_step()``), a checkpoint step directory, or
        a state-dict file path. Returns the restored step (or None)."""
        try:
            step = self._reload_weights_impl(source)
        finally:
            if self._plan is not None:
                # restored host arrays must go back to the plan's layouts
                # or the next step would recompile for replicated inputs
                self._plan.apply_to_model(self.model)
        if self._weight_audit_ref is not None or self._weight_audit:
            # a reload legitimately changes the fingerprint: re-anchor
            # the audit reference at the freshly loaded weights
            self._weight_audit_ref = weights_fingerprint(self.model)
        if self._store_path is not None:
            fp = weights_fingerprint(self.model)
            if fp != self._store_fingerprint:
                # different weights: every cached chain (device-registered,
                # host-resident, on disk) would decode garbage — drop them
                # all, then try the store again in case a shard for the NEW
                # fingerprint was published by a peer or a prior run
                self.prefix_cache.invalidate()
                self.kv_tier.drop_prefixes()
                self._store_fingerprint = fp
                self._store_saved_chains = -1
                self._load_prefix_store()
        return step

    def _reload_weights_impl(self, source):
        from ...distributed.checkpoint import load_state_dict
        from ...distributed.checkpoint.manager import CheckpointManager

        if isinstance(source, CheckpointManager):
            step = source.latest_healthy_step()
            if step is None:
                step = source.latest_valid_step()
            if step is None:
                raise FileNotFoundError(
                    "reload_weights: no committed checkpoint in "
                    f"{source.root}")
            if self._plan is not None:
                # group rejoin gate (ISSUE 19): a checkpoint recorded
                # under a DIFFERENT sharding plan must not be committed
                # to this engine's layouts — raise PlanMismatchError
                # (typed) instead of silently serving re-sharded weights
                # the rest of the fleet does not have
                CheckpointManager._check_plan(
                    source.plan_fingerprint(step), self._plan, step)
            load_state_dict(self.model.state_dict(), source.step_dir(step))
            return step
        import os

        from ...framework import io as _fio

        path = str(source)
        if os.path.isdir(path):
            load_state_dict(self.model.state_dict(), path)
            return None
        if is_llama_artifact(path):
            # serving artifact (possibly the ISSUE-14 int8 format):
            # dequantized to the live params' dtype, so the hot-swap
            # never changes an executable's input avals — no recompile
            self.model.set_state_dict(load_llama_state_dict(path))
            return None
        self.model.set_state_dict(_fio.load(path))
        return None

    def stats(self):
        d = dict(self.stats_extra)
        d.update(self.scheduler.stats)
        d["blocks_free"] = self.cache.allocator.num_free
        d["blocks_high_water"] = self.cache.allocator.high_water
        d["waiting"] = len(self.scheduler.waiting)
        d["running"] = len(self.scheduler.running)
        d["prefill_stats_row"] = self._prefill_name
        d["decode_stats_row"] = self._decode_name
        return d

    def metrics(self):
        """Engine-owned observability snapshot (ISSUE 10 public surface):
        lifecycle counters, latency histogram summaries (count/mean/
        p50/p99, ms), prefix-cache/chunk/speculative counters and
        utilization gauges for THIS engine instance, read from
        ``paddle.observability.metrics``. This is what
        ``scripts/bench_serving.py`` reports TTFT / inter-token
        percentiles from — engine-measured, not bench-side timing."""
        inst = self._name
        prop = _M_SPEC_PROPOSED.value(instance=inst)
        return {
            "instance": inst,
            "admitted": int(_M_ADMITTED.value(instance=inst)),
            "evictions": int(_M_EVICTIONS.value(instance=inst)),
            "finished": int(_M_FINISHED.value(instance=inst)),
            "queued_on_exhaustion": int(
                _M_QUEUED_EXH.value(instance=inst)),
            "prefills": int(_M_PREFILLS.value(instance=inst)),
            "prefill_chunks": int(_M_PREFILL_CHUNKS.value(instance=inst)),
            "prefix_blocks_reused": int(
                _M_PREFIX_REUSED.value(instance=inst)),
            "cow_copies": int(_M_COW.value(instance=inst)),
            "spec_proposed": int(prop),
            "spec_accepted": int(_M_SPEC_ACCEPTED.value(instance=inst)),
            "spec_accept_ratio": (
                float(_G_SPEC_RATIO.value(instance=inst)) if prop
                else None),
            "deadline_expired": int(_M_DEADLINE.value(instance=inst)),
            "tokens_out": int(_M_TOKENS.value(instance=inst)),
            "ttft_ms": _H_TTFT.summary(instance=inst),
            "itl_ms": _H_ITL.summary(instance=inst),
            "kv_block_utilization": _G_KV_UTIL.value(instance=inst),
            "decode_batch_occupancy": _G_OCCUPANCY.value(instance=inst),
            "kv_dtype": self.kv_dtype,
            "kv_bytes_saved": int(_M_KV_SAVED.value(instance=inst)),
            "quantized_blocks_in_use": (
                int(_G_QUANT_BLOCKS.value(instance=inst))
                if self.cache.quantized else None),
            # KV tiering + prefix store (ISSUE 16) — zeros when the tier
            # is off so consumers never need to key-guard
            "kv_spills": int(_M_SPILLS.value(instance=inst)),
            "kv_revives": int(_M_REVIVES.value(instance=inst)),
            "kv_spill_bytes": int(_M_SPILL_BYTES.value(instance=inst)),
            "kv_revive_bytes": int(_M_REVIVE_BYTES.value(instance=inst)),
            "kv_host_evictions": int(_M_HOST_EVICT.value(instance=inst)),
            "kv_host_blocks": int(_G_HOST_BLOCKS.value(instance=inst)),
            "kv_spill_ms": _H_SPILL_MS.summary(instance=inst),
            "kv_revive_ms": _H_REVIVE_MS.summary(instance=inst),
            "prefix_store_saved": int(_M_STORE_SAVED.value(instance=inst)),
            "prefix_store_loaded": int(
                _M_STORE_LOADED.value(instance=inst)),
            # reason-labeled since ISSUE 20: the plain key stays the
            # all-reasons sum so existing consumers keep working
            "prefix_store_rejected": sum(
                self._store_rejected_by_reason().values()),
            "prefix_store_rejected_by_reason":
                self._store_rejected_by_reason(),
            # multi-tenant QoS (ISSUE 17) — zeros when QoS is unused
            "quota_throttled": int(_M_THROTTLED.value(instance=inst)),
            "batch_yields": int(_M_BATCH_YIELD.value(instance=inst)),
            "tenant_tokens": self._tenant_token_counts(),
            # device-resident decode (ISSUE 18): decode-loop round-trips
            # and the bytes they pulled (prefill fetches excluded)
            "host_syncs": int(_M_HOST_SYNCS.value(instance=inst)),
            "decode_fetch_bytes": int(_M_FETCH_BYTES.value(instance=inst)),
            # serving integrity (ISSUE 20) — zeros when checksums / the
            # weight audit are off
            "kv_pages_verified": int(
                _M_PAGES_VERIFIED.value(instance=inst)),
            "kv_pages_rejected": int(
                _M_PAGES_REJECTED.value(instance=inst)),
            "weight_audits": int(self._weight_audits),
            "weight_audit_failures": int(
                _M_WEIGHT_AUDIT_FAIL.value(instance=inst)),
        }

    def _remove_tenant_series(self):
        """Remove THIS instance's tenant-labeled series. The extra
        ``tenant`` label means the plain ``remove(instance=)`` sweep in
        ``reset_metrics``/``close`` cannot reach them — iterate the live
        label sets instead. The reason-labeled store-rejected counter
        (ISSUE 20) needs the same treatment."""
        for m in (_M_TENANT_TOKENS, _M_STORE_REJECTED):
            for labels in list(m.labels()):
                d = dict(labels)
                if d.get("instance") == self._name:
                    m.remove(**d)

    def _store_rejected_by_reason(self):
        """Per-reason store-rejection counts for THIS instance (ISSUE
        20) — iterated from live label sets, like the tenant tokens."""
        out = {}
        for labels in _M_STORE_REJECTED.labels():
            d = dict(labels)
            if d.get("instance") == self._name:
                out[d.get("reason", "corrupt")] = int(
                    _M_STORE_REJECTED.value(**d))
        return out

    def _tenant_token_counts(self):
        """Per-tenant served-token counts for THIS instance — iterated
        from live label sets because the ``tenant`` label is only known
        at serve time, not declaration time."""
        out = {}
        for labels in _M_TENANT_TOKENS.labels():
            d = dict(labels)
            if d.get("instance") == self._name:
                out[d.get("tenant", "default")] = int(
                    _M_TENANT_TOKENS.value(**d))
        return out

    def reset_metrics(self):
        """Drop THIS instance's registry series (latency histograms and
        lifecycle counters restart from empty). Benchmarks call it at the
        start of a timed window so warm-phase observations never pollute
        the reported percentiles; a production engine has no reason to."""
        for m in _SERVING_METRICS:
            m.remove(instance=self._name)
        self._remove_tenant_series()
        if self.cache.quantized and not self._closed:
            # bytes saved is a construction-time constant of THIS pool,
            # not window activity — republish it so a benchmark window
            # reset doesn't erase the capacity accounting
            _M_KV_SAVED.inc(self._kv_bytes_saved, instance=self._name)
        if self.kv_tier is not None and not self._closed:
            # host occupancy is current state, not window activity
            _G_HOST_BLOCKS.set(self.kv_tier.host_blocks_in_use,
                               instance=self._name)

    def reset_block_high_water(self):
        """Re-anchor the allocator's high-water mark at the current
        in-use block count — the window-local form benchmarks want
        (replaces reaching into ``cache.allocator`` privates)."""
        alloc = self.cache.allocator
        alloc.high_water = (self.cache.num_blocks - 1) - alloc.num_free

    def close(self):
        """Tear the engine down (ISSUE 12 satellite, mirroring
        ``DevicePrefetcher.close``): join the ingest thread, abort every
        live request so the scheduler's blocks return to the allocator,
        drop request bookkeeping, and remove THIS instance's registry
        series — so a process that constructs engines in a loop (tests,
        notebooks, a supervisor restarting replicas in-process) does not
        grow the metrics registry forever. Idempotent; after close,
        ``add_request``/``step``/``stream``/``generate`` raise
        :class:`EngineClosedError` instead of hanging on the joined
        ingest thread."""
        if self._closed:
            return
        if self._store_path is not None:
            # persist the warm prefix chains BEFORE teardown frees their
            # blocks; a failed save keeps the previous store intact and
            # never blocks the close
            try:
                self.save_prefix_store()
            except OSError as e:
                warnings.warn(f"{self._name}: prefix store save on close "
                              f"failed: {e}", RuntimeWarning)
        self._closed = True
        if self._ingest is not None:
            self._ingest.close()
            # anything still staged on the (now joined) ingest thread
            # was never admitted — no blocks to free, just bookkeeping
            self._ingest.drain()
        for req in list(self.scheduler.running):
            self.scheduler.abort(req, "closed")
        for req in list(self.scheduler.waiting):
            self.scheduler.abort(req, "closed")
        self._requests.clear()
        if self.kv_tier is not None:
            self.kv_tier.close()
        self.reset_metrics()
        if self._was_training:
            self.model.train()
        if self.draft_model is not None and self._draft_was_training:
            self.draft_model.train()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ----------------------------------------------------------------------
# llama serving artifacts (consumed by inference.create_predictor)
# ----------------------------------------------------------------------

ARTIFACT_QMAX = 127.0


def quantize_state_dict(state_dict, qmax=ARTIFACT_QMAX):
    """Per-channel int8 quantization of a weights state dict (ISSUE 14
    artifact format): every float array with >= 2 dims is packed as int8
    codes + a float32 per-channel scale row (abs-max over all axes
    except the LAST — the output channel of every ``Linear`` here), 1-D
    params (norms, biases) pass through untouched. Returns
    ``(packed, scales)`` where ``scales`` holds ONLY the quantized
    names, each scale being the DEQUANT MULTIPLIER (``absmax / qmax`` —
    dequant is a single ``codes * scale``). The quantization math is
    the quantization package's shared
    :func:`~paddle_tpu.quantization.base.per_channel_int8`, so the
    artifact path and the PTQ convert path can never drift."""
    from ...quantization.base import per_channel_int8

    packed, scales = {}, {}
    for name, val in state_dict.items():
        arr = np.asarray(val.numpy() if hasattr(val, "numpy") else val)
        if arr.ndim >= 2 and arr.dtype.kind == "f":
            codes, absmax = per_channel_int8(arr, qmax=qmax)
            packed[name] = codes
            scales[name] = (absmax / qmax).astype(np.float32)
        else:
            packed[name] = arr
    return packed, scales


def dequantize_state_dict(packed, scales, dtype=np.float32):
    """Inverse of :func:`quantize_state_dict`: codes x scale back to
    ``dtype`` host arrays, passthrough entries untouched."""
    out = {}
    for name, arr in packed.items():
        arr = np.asarray(arr.numpy() if hasattr(arr, "numpy") else arr)
        if name in scales:
            s = np.asarray(scales[name].numpy()
                           if hasattr(scales[name], "numpy")
                           else scales[name])
            out[name] = (arr.astype(np.float32) * s).astype(dtype)
        else:
            out[name] = arr
    return out


def save_llama_artifact(model, path, quantize=None):
    """Persist a llama model as a serving artifact: ``<path>.llamacfg.json``
    (the LlamaConfig) + ``<path>.pdiparams`` (weights). The engine-backed
    predictor (``Config.enable_llm_engine``) detects the sidecar config and
    rebuilds the model around it.

    ``quantize="int8"`` (ISSUE 14) writes the QUANTIZED artifact format:
    ``<path>.pdiparams`` holds packed int8 weight tensors (per-channel
    abs-max, ~4x smaller — the replica-boot / fleet-transfer win), the
    scales live in the ``<path>.qscales.pdiparams`` sidecar, and
    ``<path>.quant.json`` records the scheme. Loaders dequantize back to
    the model dtype, so a running ``LLMEngine.reload_weights`` hot-swap
    sees same-shape/same-dtype arrays and never recompiles."""
    import json
    import os

    from ...framework.io import save as fsave

    if quantize not in (None, "int8"):
        raise ValueError(f"quantize must be None or 'int8'; got "
                         f"{quantize!r}")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".llamacfg.json", "w") as f:
        json.dump(dataclasses.asdict(model.config), f)
    if quantize == "int8":
        packed, scales = quantize_state_dict(model.state_dict())
        fsave(packed, path + ".pdiparams")
        fsave(scales, path + ".qscales.pdiparams")
        with open(path + ".quant.json", "w") as f:
            json.dump({"scheme": "int8_per_channel",
                       "qmax": ARTIFACT_QMAX,
                       "quantized_tensors": sorted(scales)}, f)
    else:
        fsave(model.state_dict(), path + ".pdiparams")
        # a resave over a previously-quantized path must not leave a
        # stale scheme sidecar claiming the fp weights are codes
        for ext in (".quant.json", ".qscales.pdiparams"):
            try:
                os.remove(path + ext)
            except OSError:
                pass


def is_llama_artifact(path):
    import os

    if path.endswith(".pdmodel"):
        path = path[: -len(".pdmodel")]
    return os.path.exists(path + ".llamacfg.json")


def is_quantized_artifact(path):
    import os

    if path.endswith(".pdmodel"):
        path = path[: -len(".pdmodel")]
    return os.path.exists(path + ".quant.json")


def load_llama_state_dict(path):
    """Host-array weights of an artifact, dequantizing the int8 format
    when its ``.quant.json`` sidecar is present (the
    ``LLMEngine.reload_weights`` hot-swap entry: same shapes and dtypes
    as the live params, so nothing recompiles)."""
    import json

    from ...framework.io import load as fload

    if path.endswith(".pdmodel"):
        path = path[: -len(".pdmodel")]
    if is_quantized_artifact(path):
        with open(path + ".quant.json") as f:
            meta = json.load(f)
        if meta.get("scheme") != "int8_per_channel":
            raise ValueError(
                f"unknown quantized-artifact scheme {meta.get('scheme')!r} "
                f"in {path}.quant.json")
        packed = fload(path + ".pdiparams", return_numpy=True)
        scales = fload(path + ".qscales.pdiparams", return_numpy=True)
        return dequantize_state_dict(packed, scales)
    return fload(path + ".pdiparams")


def load_llama_artifact(path):
    """Rebuild the model from :func:`save_llama_artifact` output
    (quantized artifacts are dequantized into the fresh model's
    dtype)."""
    import json

    from ...models.llama import LlamaConfig, LlamaForCausalLM

    if path.endswith(".pdmodel"):
        path = path[: -len(".pdmodel")]
    with open(path + ".llamacfg.json") as f:
        cfg = LlamaConfig(**json.load(f))
    model = LlamaForCausalLM(cfg)
    model.set_state_dict(load_llama_state_dict(path))
    model.eval()
    return model
