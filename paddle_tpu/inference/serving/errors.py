"""Typed serving errors (ISSUE 12): the fault-tolerant fleet's contract
is that NOTHING fails silently — a request that cannot be served gets a
typed error naming why, and a fleet that cannot stay up raises instead
of flapping forever.

* :class:`RequestTimeoutError` — the request's deadline expired: at
  admission (rejected before any allocator state moved) or mid-stream
  (blocks freed, slot recycled, the partial stream ends with this).
* :class:`FleetOverloadedError` — the router's bounded admission queue
  is full; shedding with a typed error replaces unbounded queue growth.
* :class:`EngineClosedError` — ``LLMEngine``/``Router`` used after
  ``close()``: a typed raise instead of a hang on a dead ingest thread.
* :class:`ReplicaCrashLoopError` — a replica exhausted its leaky-bucket
  :class:`~paddle_tpu.distributed.launch.controllers.collective.RestartBudget`
  (the SAME budget/backoff machinery training supervision uses); it
  subclasses the launcher's ``CrashLoopError`` so one except-clause
  handles crash loops from either side of the house.
* :class:`KVTransferError` — the disaggregated prefill→decode KV-page
  handoff (ISSUE 15) failed past its retry budget: corrupt frames or
  failed deliveries were re-driven (the prefill re-runs elsewhere, never
  decoded-on-garbage) until the budget ran out — the streaming
  ``StreamReadError`` idiom applied to the transfer channel.
* :class:`TenantQuotaExceededError` — a tenant blew through its
  token-rate quota at the router (ISSUE 17): hard rejection with a
  ``retry_after_s`` hint so the abuser backs off instead of hammering.
* :class:`KVIntegrityError` — a KV page failed its CRC32 at a read-back
  boundary (ISSUE 20): the page was corrupted AT REST (host tier, prefix
  store, transfer payload) after it was sealed. The degrade rule is
  re-prefill, never serve-the-page — so this error names corruption that
  was CAUGHT, not tokens that went wrong.
* :class:`DeadlineInfeasibleError` — SLO-aware placement (ISSUE 17)
  determined the deadline cannot be met (estimated queue wait + prefill
  cost exceed the remaining budget); subclasses
  :class:`RequestTimeoutError` so existing expiry handling catches it,
  but fires BEFORE any work is admitted.

Backoff contract (ISSUE 17): every load-rejection error
(:class:`FleetOverloadedError`, :class:`TenantQuotaExceededError`,
:class:`DeadlineInfeasibleError`) carries a machine-readable
``retry_after_s`` estimated from the current queue drain rate, so
clients retry politely instead of contributing to the overload.
"""

from __future__ import annotations

from ...distributed.launch.controllers.collective import CrashLoopError

__all__ = ["RequestTimeoutError", "FleetOverloadedError",
           "EngineClosedError", "ReplicaCrashLoopError",
           "KVTransferError", "TenantQuotaExceededError",
           "DeadlineInfeasibleError", "KVIntegrityError"]


class RequestTimeoutError(TimeoutError):
    """A request's deadline expired. ``rid`` names the request (None when
    raised at admission before an id was assigned); ``deadline`` is the
    absolute ``time.time()`` deadline that passed."""

    def __init__(self, msg, rid=None, deadline=None):
        super().__init__(msg)
        self.rid = rid
        self.deadline = deadline


class FleetOverloadedError(RuntimeError):
    """The fleet's bounded admission queue is full — the request was shed
    at submit time (load shedding: a typed error now beats an unbounded
    queue that times everyone out later). ``queue_depth`` records the
    bound that was hit; ``retry_after_s`` estimates when capacity should
    free up (from the queue drain rate), or None when unknown."""

    def __init__(self, msg, queue_depth=None, retry_after_s=None):
        super().__init__(msg)
        self.queue_depth = queue_depth
        self.retry_after_s = retry_after_s


class TenantQuotaExceededError(RuntimeError):
    """One tenant exhausted its token-rate quota (ISSUE 17) — the
    request was rejected at submit so the quota bounds the ABUSER's
    throughput, not everyone's. ``tenant`` names the offender;
    ``retry_after_s`` says when the leaky bucket drains enough to admit
    again (machine-readable, so well-behaved clients back off)."""

    def __init__(self, msg, tenant=None, retry_after_s=None):
        super().__init__(msg)
        self.tenant = tenant
        self.retry_after_s = retry_after_s


class DeadlineInfeasibleError(RequestTimeoutError):
    """SLO-aware placement rejection (ISSUE 17): the estimated queue
    wait plus prefill cost already exceed the request's remaining
    deadline budget, so admitting it would only burn decode slots on
    work guaranteed to expire mid-stream. Subclasses
    :class:`RequestTimeoutError` — callers that handle expiry handle
    this too — but is raised BEFORE any allocator state moves.
    ``retry_after_s`` estimates when the queue drains enough for the
    same deadline budget to become feasible."""

    def __init__(self, msg, rid=None, deadline=None, retry_after_s=None):
        super().__init__(msg, rid=rid, deadline=deadline)
        self.retry_after_s = retry_after_s


class EngineClosedError(RuntimeError):
    """The engine/router was used after ``close()``. Typed so servers can
    distinguish a lifecycle bug from a serving failure."""


class ReplicaCrashLoopError(CrashLoopError):
    """One replica kept dying until its restart budget ran out
    (``max_restarts`` within the rolling window). Carries the launcher
    ``CrashLoopError`` fields (``exit_code``, ``restarts``) plus the
    ``replica`` id, so the operator knows WHICH slot is poisoned."""

    def __init__(self, msg, replica=None, exit_code=1, restarts=0):
        super().__init__(msg, exit_code=exit_code, restarts=restarts)
        self.replica = replica


class KVIntegrityError(RuntimeError):
    """A KV page payload failed CRC32 verification at a read-back
    boundary (ISSUE 20): host-tier revive, ``import_request_pages``, or
    prefix-store load. The page was sealed with per-block checksums at
    its write boundary, so a mismatch means the bytes changed AT REST —
    silent data corruption caught before a single wrong token decoded.
    ``key`` names the tier/store entry (or request) whose page failed;
    ``block`` is the index of the first mismatching block within the
    payload (None when the sidecar itself is malformed)."""

    def __init__(self, msg, key=None, block=None):
        super().__init__(msg)
        self.key = key
        self.block = block


class KVTransferError(RuntimeError):
    """The KV-page handoff between a prefill and a decode worker failed
    past its retry budget (ISSUE 15). Every transient failure (corrupt
    frame, failed delivery) re-drives the prefill — partial pages are
    discarded atomically, never decoded — so this error means the
    transfer channel itself is persistently broken. ``gid`` names the
    fleet request, ``retries`` how many re-drives were burned."""

    def __init__(self, msg, gid=None, retries=0):
        super().__init__(msg)
        self.gid = gid
        self.retries = retries
