"""paddle.inference — deployment facade over exported StableHLO programs.

Reference: paddle/fluid/inference/api/analysis_predictor.h:100
(AnalysisPredictor) + python/paddle/inference/__init__.py (Config,
create_predictor, Predictor/Tensor handles). The reference deserializes a
Program and runs it through the analysis/IR-pass pipeline; TPU-native, the
artifact IS a compiled-ready serialized StableHLO module (jit.save), XLA is
the IR-pass pipeline, and a Predictor is a thin handle-based session around
``jax.export.deserialize(...).call``. Graph-level config knobs
(switch_ir_optim, enable_memory_optim, …) are accepted for API parity and
recorded; XLA performs those optimizations unconditionally.
"""

from __future__ import annotations

import os
import threading

import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PrecisionType", "PlaceType", "get_version",
           "LLMEnginePredictor", "serving"]


def __getattr__(name):
    # lazy: the serving engine pulls jax at import; the facade should not
    if name == "serving":
        import importlib

        mod = importlib.import_module(".serving", __name__)
        globals()["serving"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"  # accepted; maps to the default accelerator
    XPU = "xpu"
    CUSTOM = "custom"
    TPU = "tpu"


def get_version():
    from .. import __version__

    return __version__


class Config:
    """reference analysis_config — model path + device/precision options."""

    def __init__(self, prog_file=None, params_file=None, model_dir=None):
        if model_dir is not None and prog_file is None:
            prog_file = os.path.join(model_dir, "model")
        self._prog_file = prog_file
        self._params_file = params_file
        self._device = None  # None = default backend
        self._device_id = 0
        self._precision = PrecisionType.Float32
        self._ir_optim = True
        self._memory_optim = True
        self._cpu_math_threads = 1
        self._enable_profile = False
        self._llm_engine = False
        self._llm_engine_kwargs = {}

    # ---- model paths ----------------------------------------------------
    def set_model(self, prog_file, params_file=None):
        self._prog_file = prog_file
        self._params_file = params_file

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    # ---- device ---------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        # "gpu" in reference terms = the accelerator; here: default backend
        self._device = None
        self._device_id = device_id
        self._precision = precision

    def enable_xpu(self, *a, **k):
        self._device = None

    def enable_custom_device(self, device_type, device_id=0):
        self._device = device_type
        self._device_id = device_id

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device != "cpu"

    def gpu_device_id(self):
        return self._device_id

    # ---- optimization knobs (XLA does these; recorded for parity) -------
    def switch_ir_optim(self, x=True):
        self._ir_optim = bool(x)

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, x=True):
        self._memory_optim = bool(x)

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = int(n)

    def enable_profile(self):
        self._enable_profile = True

    # ---- LLM serving engine (ISSUE 7 satellite) -------------------------
    def enable_llm_engine(self, x=True, **engine_kwargs):
        """Route llama serving artifacts (``serving.save_llama_artifact``
        output, detected by the ``.llamacfg.json`` sidecar) through the
        paged-KV continuous-batching ``serving.LLMEngine`` instead of the
        StableHLO replay path. ``engine_kwargs`` forward to ``LLMEngine``
        (``num_blocks``, ``block_size``, ``max_batch_size``, …). For any
        other artifact the knob is accepted-and-recorded like the other
        graph knobs: ``create_predictor`` still returns the plain
        StableHLO :class:`Predictor`."""
        self._llm_engine = bool(x)
        if engine_kwargs:
            self._llm_engine_kwargs.update(engine_kwargs)

    def llm_engine_enabled(self):
        return self._llm_engine

    def summary(self):
        return (f"prog_file: {self._prog_file}\n"
                f"device: {self._device or 'default'}\n"
                f"precision: {self._precision}\n"
                f"ir_optim: {self._ir_optim} (performed by XLA)\n"
                f"llm_engine: {self._llm_engine}")


class Tensor:
    """In/out handle (reference paddle_infer::Tensor)."""

    def __init__(self, name, spec=None):
        self._name = name
        self._spec = spec
        self._value = None

    def name(self):
        return self._name

    def copy_from_cpu(self, data):
        self._value = np.asarray(data)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def share_external_data(self, data):
        self._value = data

    def shape(self):
        if self._value is not None:
            return list(np.asarray(self._value).shape)
        return list(self._spec[0]) if self._spec else None

    def reshape(self, shape):
        pass  # shapes are taken from the bound data

    def type(self):
        return self._spec[1] if self._spec else None


class Predictor:
    """reference analysis_predictor.h:100 — handle-based run session over
    the deserialized StableHLO executable."""

    def __init__(self, config):
        import jax

        from ..jit import load as jit_load

        self._config = config
        if config.prog_file() is None:
            raise ValueError("Config has no model path; use "
                             "Config(prog_file) or set_model()")
        path = config.prog_file()
        if path.endswith(".pdmodel"):
            path = path[: -len(".pdmodel")]
        self._layer = jit_load(path)
        if config._device == "cpu":
            cpu = jax.devices("cpu")[0]
            self._layer._consts = [jax.device_put(np.asarray(c), cpu)
                                   for c in self._layer._consts]
        specs = self._layer._specs
        self._inputs = {}
        for i, (shape, dtype, name) in enumerate(specs):
            name = name or f"x{i}"
            self._inputs[name] = Tensor(name, (shape, dtype))
        # placeholder handle so every advertised output name is fetchable
        # even before the first run() (its value stays None until then)
        self._outputs = {"out0": Tensor("out0")}
        self._lock = threading.Lock()

    # ---- handles --------------------------------------------------------
    def get_input_names(self):
        return list(self._inputs)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_names(self):
        return list(self._outputs)

    def get_output_handle(self, name):
        return self._outputs[name]

    # ---- execution ------------------------------------------------------
    def run(self, inputs=None):
        """Execute; returns the list of output numpy arrays (and fills the
        output handles). ``inputs`` may be passed positionally like the
        reference's ``predictor.run([x, y])``."""
        import jax.numpy as jnp

        if inputs is not None:
            for h, arr in zip(self._inputs.values(), inputs):
                h.copy_from_cpu(np.asarray(arr))
        args = [jnp.asarray(h._value) for h in self._inputs.values()]
        with self._lock:
            out = self._layer._exported.call(self._layer._consts, *args)
        outs = [np.asarray(o) for o in out]
        fresh = {}
        for i, o in enumerate(outs):
            t = Tensor(f"out{i}")
            t._value = o
            fresh[f"out{i}"] = t
        self._outputs = fresh or {"out0": Tensor("out0")}
        return outs

    def clone(self):
        """Per-thread clone sharing the loaded program + weights (the
        reference clones the executor, sharing the program)."""
        import copy

        c = copy.copy(self)
        c._inputs = {n: Tensor(n, h._spec) for n, h in self._inputs.items()}
        c._outputs = {"out0": Tensor("out0")}
        c._lock = threading.Lock()
        return c

    def try_shrink_memory(self):
        pass


class LLMEnginePredictor:
    """Predictor-shaped front over ``serving.LLMEngine`` — what
    ``create_predictor`` returns for a llama serving artifact when
    ``Config.enable_llm_engine()`` is set.

    The handle API maps onto generation: bind int32 token ids of shape
    ``[B, S]`` (zero-padded rows allowed via the optional ``seq_lens``
    handle) to ``input_ids``, ``run()`` submits every row as a request,
    drives the engine to completion, and fills one output handle per row
    with that row's prompt+generated tokens. The engine itself is exposed
    as ``.engine`` for streaming/continuous use — the handle API is the
    batch convenience."""

    def __init__(self, config):
        import dataclasses

        from .serving import LLMEngine, load_llama_artifact
        from .serving.scheduler import SamplingParams

        self._config = config
        path = config.prog_file()
        if path is None:
            raise ValueError("Config has no model path; use "
                             "Config(prog_file) or set_model()")
        kwargs = dict(config._llm_engine_kwargs)
        # sampling knobs (max_new_tokens, eos_token_id, …) split off from
        # the engine-construction knobs by SamplingParams' field names
        fields = {f.name for f in dataclasses.fields(SamplingParams)}
        samp = {k: kwargs.pop(k) for k in list(kwargs) if k in fields}
        self._sampling = SamplingParams(**samp) if samp else None
        self.engine = LLMEngine(load_llama_artifact(path), **kwargs)
        self._inputs = {"input_ids": Tensor("input_ids", ([-1, -1], "int32")),
                        "seq_lens": Tensor("seq_lens", ([-1], "int32"))}
        # placeholder handle so every advertised output name is fetchable
        # even before the first run() (one handle per row appears after)
        self._outputs = {"out0": Tensor("out0")}

    def get_input_names(self):
        return list(self._inputs)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_names(self):
        return list(self._outputs)

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs=None):
        import dataclasses as _dc

        if inputs is not None:
            self._inputs["input_ids"].copy_from_cpu(np.asarray(inputs[0]))
            if len(inputs) > 1:
                self._inputs["seq_lens"].copy_from_cpu(np.asarray(inputs[1]))
        ids = np.asarray(self._inputs["input_ids"]._value)
        if ids.ndim == 1:
            ids = ids[None]
        lens_h = self._inputs["seq_lens"]._value
        if lens_h is not None:
            lens = np.asarray(lens_h).reshape(-1)
            if lens.shape[0] != ids.shape[0]:
                raise ValueError(
                    f"seq_lens has {lens.shape[0]} entries for "
                    f"{ids.shape[0]} input rows")
        else:
            lens = np.full(ids.shape[0], ids.shape[1])
        prompts = [ids[i, :int(lens[i])] for i in range(ids.shape[0])]
        outs = self.engine.generate(
            prompts, _dc.replace(self._sampling) if self._sampling else None)
        # seq_lens describes THIS batch only — clear it so the next run's
        # (possibly unpadded, differently-sized) batch is not silently
        # truncated by stale lengths
        self._inputs["seq_lens"]._value = None
        fresh = {}
        for i, o in enumerate(outs):
            t = Tensor(f"out{i}")
            t._value = np.asarray(o)
            fresh[f"out{i}"] = t
        self._outputs = fresh or {"out0": Tensor("out0")}
        return outs

    def try_shrink_memory(self):
        pass

    def close(self):
        self.engine.close()


def create_predictor(config: Config):
    if config._llm_engine:
        from . import serving

        path = config.prog_file()
        if path is not None and serving.is_llama_artifact(path):
            return LLMEnginePredictor(config)
        # non-llama artifact: the knob is recorded, the StableHLO
        # replay path serves it (same contract as the other graph knobs)
    return Predictor(config)


class DataType:
    """reference paddle_infer.DataType enum."""

    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6
    FLOAT64 = 7
    BOOL = 8


def get_num_bytes_of_data_type(dtype):
    sizes = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
             DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2, DataType.FLOAT64: 8, DataType.BOOL: 1}
    return sizes[dtype]


def get_trt_compile_version():
    """No TensorRT on TPU builds (the XLA compiler is the deployment
    compiler); version triple is all-zero like reference CPU builds."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name):
    """PHI kernels collapse into XLA ops here; the name maps through."""
    return op_name


class XpuConfig:
    """Kunlun XPU deploy knobs — accepted, inert (no XPU backend)."""

    def __init__(self, **kwargs):
        for k, v in kwargs.items():
            setattr(self, k, v)


class PredictorPool:
    """reference paddle_infer.PredictorPool: N predictors over one config
    (thread serving). Predictor.clone() shares the executable, so the pool
    is a thin list."""

    def __init__(self, config, size=1):
        first = create_predictor(config)
        self._preds = [first] + [first.clone() for _ in range(size - 1)]

    def retrive(self, idx):  # reference spells it this way
        return self._preds[idx]

    retrieve = retrive


__all__ += ["DataType", "get_num_bytes_of_data_type",
            "get_trt_compile_version", "get_trt_runtime_version",
            "convert_to_mixed_precision", "XpuConfig", "PredictorPool",
            "_get_phi_kernel_name"]


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Offline precision-rewrite pass: fp32 exported model -> mixed
    precision artifact.

    Reference: python/paddle/inference/wrapper.py:79 (the
    analysis-pass-layer mixed-precision rewrite). TPU-native: the artifact
    is serialized StableHLO + weight arrays (jit.save). The pass stores the
    weights at ``mixed_precision`` (halving artifact size and HBM weight
    residency) and re-exports the program as
    ``call(cast_fp32(weights_lp), *inputs)`` — XLA fuses the up-casts into
    the consuming matmuls, which on TPU execute through the MXU's native
    bf16 path anyway, so bf16 weights + f32 accumulation is exactly the
    mixed-precision execution the reference pass builds per-op. I/O dtypes
    are unchanged (``keep_io_types`` accepted for parity; the exported
    signature already pins them). op-level black/white lists are N/A at the
    whole-program level and are accepted-but-recorded.
    """
    import pickle
    import shutil

    import jax
    import jax.export  # noqa: F401  (submodule not auto-imported)
    import jax.numpy as jnp
    import ml_dtypes  # noqa: F401  (np.dtype("bfloat16") resolution)

    if mixed_precision in ("int8", PrecisionType.Int8):
        raise NotImplementedError(
            "int8 conversion lives in paddle.quantization (PTQ); "
            "convert_to_mixed_precision handles float16/bfloat16")
    lp = np.dtype(getattr(ml_dtypes, "bfloat16")
                  if mixed_precision in ("bfloat16", PrecisionType.Bfloat16)
                  else np.float16)

    path = model_file
    if path.endswith(".pdmodel"):
        path = path[: -len(".pdmodel")]
    with open(path + ".pdmodel", "rb") as f:
        payload = pickle.load(f)
    exported = jax.export.deserialize(payload["stablehlo"])

    consts = [np.asarray(c) for c in payload["consts"]]
    lp_consts = [c.astype(lp) if np.issubdtype(c.dtype, np.floating) else c
                 for c in consts]
    orig_dtypes = [c.dtype for c in consts]

    def mixed_call(lp_consts_, *inputs):
        full = [jnp.asarray(c).astype(d) if np.issubdtype(d, np.floating)
                else jnp.asarray(c)
                for c, d in zip(lp_consts_, orig_dtypes)]
        return exported.call(full, *inputs)

    # exported.in_avals is FLAT (consts leaves + input leaves); the real
    # inputs are the trailing len(specs) entries
    n_inputs = len(payload["specs"])
    in_avals = list(exported.in_avals)[len(consts):]
    assert len(in_avals) == n_inputs, (len(in_avals), n_inputs)
    lp_avals = [jax.ShapeDtypeStruct(c.shape, c.dtype) for c in lp_consts]
    mixed_exported = jax.export.export(jax.jit(mixed_call))(
        lp_avals, *in_avals)

    out_base = mixed_model_file
    if out_base.endswith(".pdmodel"):
        out_base = out_base[: -len(".pdmodel")]
    new_payload = dict(payload)
    new_payload["stablehlo"] = mixed_exported.serialize()
    new_payload["consts"] = lp_consts
    new_payload["mixed_precision"] = str(lp)
    with open(out_base + ".pdmodel", "wb") as f:
        pickle.dump(new_payload, f, protocol=4)
    src_params = (path + ".pdiparams" if params_file is None else params_file)
    if mixed_params_file and os.path.exists(src_params):
        shutil.copyfile(src_params, mixed_params_file)


