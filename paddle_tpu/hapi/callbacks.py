"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import numbers
import os
import time

import numpy as np

__all__ = ["Callback", "DivergenceSentinel", "ProgBarLogger",
           "ModelCheckpoint", "LRScheduler", "EarlyStopping",
           "ReduceLROnPlateau", "config_callbacks"]


class Callback:
    """Base callback (ref callbacks.py Callback): every hook is a no-op."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)

        return call


def _fmt(v):
    if isinstance(v, numbers.Number):
        return f"{v:.4f}"
    if isinstance(v, (list, tuple, np.ndarray)):
        return "[" + ", ".join(_fmt(x) for x in np.ravel(v)) + "]"
    if hasattr(v, "__float__"):
        # deferred device scalar (hapi lazy loss): the device→host fetch
        # happens here, at the logging boundary. Non-scalar values (a
        # multi-element Tensor in a custom metric) keep the str() fallback.
        try:
            return f"{float(v):.4f}"
        except (TypeError, ValueError):
            return str(v)
    return str(v)


class ProgBarLogger(Callback):
    """Per-step/epoch console logging (ref callbacks.py ProgBarLogger)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose and self.params.get("verbose", 1):
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def _line(self, step, logs):
        items = [f"step {step + 1}" + (f"/{self.steps}" if self.steps else "")]
        for k, v in (logs or {}).items():
            items.append(f"{k}: {_fmt(v)}")
        return " - ".join(items)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose >= 2 and (step + 1) % self.log_freq == 0:
            print(self._line(step, logs))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(self._line(self.params.get("last_step", 0), logs)
                  + f" - {dt:.2f}s")

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = [f"{k}: {_fmt(v)}" for k, v in (logs or {}).items()]
            print("Eval - " + " - ".join(items))


class ModelCheckpoint(Callback):
    """Periodic save (ref callbacks.py ModelCheckpoint).

    Every save goes through the atomic checkpoint writer (``paddle.save``:
    tmp → fsync → rename), so a crash mid-epoch-save never tears an
    existing checkpoint. With ``keep_last_n`` the epoch saves are managed
    by :class:`paddle.CheckpointManager` instead of loose files: each epoch
    lands in a committed ``step_{epoch}/`` directory and only the newest N
    are retained (the newest committed one is never deleted)."""

    def __init__(self, save_freq=1, save_dir=None, keep_last_n=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.keep_last_n = keep_last_n
        self._manager = None

    def _get_manager(self):
        if self._manager is None:
            from ..distributed.checkpoint.manager import CheckpointManager

            self._manager = CheckpointManager(self.save_dir,
                                              keep_last_n=self.keep_last_n)
        return self._manager

    def on_epoch_end(self, epoch, logs=None):
        if not (self.save_dir and (epoch + 1) % self.save_freq == 0):
            return
        if self.keep_last_n is None:
            self.model.save(os.path.join(self.save_dir, str(epoch)))
        else:
            self._get_manager().save(
                epoch,
                writer=lambda d: self.model.save(os.path.join(d, "model")))

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LR scheduler (ref callbacks.py LRScheduler)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        assert by_step != by_epoch, "exactly one of by_step/by_epoch"
        self.by_step = by_step

    def _step(self):
        opt = getattr(self.model, "_optimizer", None)
        sched = getattr(opt, "_learning_rate", None)
        if hasattr(sched, "step"):
            sched.step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            self._step()

    def on_epoch_end(self, epoch, logs=None):
        if not self.by_step:
            self._step()


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (ref callbacks.py)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.stopped_epoch = 0

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = (self.baseline if self.baseline is not None
                     else (np.inf if self.mode == "min" else -np.inf))
        self.model.stop_training = False

    def _better(self, cur):
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.ravel(cur)[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.params.get("save_dir"):
                self.model.save(os.path.join(self.params["save_dir"],
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: no {self.monitor} improvement "
                          f"in {self.wait} evals (best {self.best:.5f})")


class ReduceLROnPlateau(Callback):
    """Scale LR down when the monitored metric plateaus (ref callbacks.py)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.wait = 0
        self.cooldown_counter = 0
        self.best = np.inf if self.mode == "min" else -np.inf

    def _better(self, cur):
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.ravel(cur)[0])
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._better(cur):
            self.best = cur
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = self.model._optimizer
                old = opt.get_lr()
                new = max(old * self.factor, self.min_lr)
                if old - new > 1e-12:
                    opt.set_lr(new)
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr {old:.2e} -> {new:.2e}")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class DivergenceSentinel(Callback):
    """hapi face of the divergence sentinel
    (:class:`paddle.incubate.TrainingSentinel`): the same window-level
    loss-spike detector ``FusedTrainStep.drive`` runs, driven from the
    ``fit`` loop's lazy per-batch losses. Losses are buffered as device
    values and materialized once per ``window`` steps (ONE host sync per
    window — the per-step loop stays sync-free), judged, and the response
    ladder runs per ``FLAGS_sentinel_action``:

    - ``warn`` — RuntimeWarning naming the window and z-score.
    - ``skip`` — hapi's fit has no resumable-cursor contract to skip
      batches with, so this degrades to ``warn`` (use
      ``FusedTrainStep.drive`` for true bad-window skip).
    - ``rollback`` — needs ``manager=`` (a :class:`CheckpointManager`
      whose steps a :class:`ModelCheckpoint(keep_last_n=...)` writes, or
      any manager the caller saves through): restores model(+optimizer)
      from ``latest_healthy_step()``, drops the poisoned newer steps, and
      continues — budgeted; exhaustion raises
      :class:`~paddle_tpu.core.exceptions.TrainDivergenceError`. The data
      stream is NOT rewound (hapi batches are not resumable), so the
      poisoned batches' region is simply trained past.
    - ``raise`` — typed ``TrainDivergenceError`` at the first verdict.

    ``manager`` also receives the health bookkeeping
    (``note_window``): a committed step becomes a rollback target only
    ``FLAGS_sentinel_healthy_windows`` clean windows after it was
    written. ``Model.fit`` auto-appends this callback whenever
    ``FLAGS_sentinel_action`` != 'none' and none was passed."""

    def __init__(self, sentinel=None, window=None, manager=None):
        super().__init__()
        self.sentinel = sentinel
        self.window = window
        self.manager = manager
        self._buf = []

    def on_train_begin(self, logs=None):
        from ..core.flags import flag_value
        from ..incubate.sentinel import TrainingSentinel

        if self.sentinel is None:
            # flags are read at fit time, not construction time, so
            # set_flags between building callbacks and fitting works
            self.sentinel = TrainingSentinel()
        if self.window is None:
            self.window = int(flag_value("metric_fetch_interval", 10))
        self._buf = []

    def on_train_batch_end(self, step, logs=None):
        loss = (logs or {}).get("loss")
        if loss is None or self.sentinel is None or not self.sentinel.armed:
            return
        # keep the device handle lazy; materialize per-window, not per-step
        self._buf.append(getattr(loss, "_data", loss))
        if len(self._buf) >= self.window:
            self._judge(step)

    def on_epoch_end(self, epoch, logs=None):
        if self._buf and self.sentinel is not None and self.sentinel.armed:
            self._judge(self.params.get("last_step", -1))

    def _judge(self, step):
        import warnings

        import jax.numpy as jnp

        from ..incubate.sentinel import make_window

        buf, self._buf = self._buf, []
        losses = np.asarray(jnp.stack(
            [jnp.asarray(v, jnp.float32) for v in buf]))  # one host sync
        win = make_window(
            losses, non_finite=int((~np.isfinite(losses)).sum()),
            step=step)
        verdict = self.sentinel.observe(win)
        # same contract as FusedTrainStep._sentinel_check: no rank
        # responds alone
        spiked = self.sentinel.agree_verdict(verdict["verdict"] == "spike")
        if self.manager is not None and hasattr(self.manager,
                                                "note_window"):
            self.manager.note_window(clean=not spiked,
                                     k=self.sentinel.healthy_windows)
        if not spiked:
            return
        why, where = self.sentinel.describe(verdict)
        action = self.sentinel.action
        if action == "raise":
            self.sentinel.raise_divergence(
                f"divergence detected ({why}) at {where}")
        warnings.warn(
            f"divergence sentinel: spike verdict ({why}) at {where} — "
            f"responding with FLAGS_sentinel_action={action}"
            + (" (skip degrades to warn under hapi fit: no resumable "
               "batch cursor)" if action == "skip" else ""),
            RuntimeWarning, stacklevel=2)
        if action != "rollback":
            return
        if self.manager is None:
            self.sentinel.raise_divergence(
                "FLAGS_sentinel_action=rollback under hapi fit needs "
                "DivergenceSentinel(manager=a CheckpointManager) whose "
                "steps a ModelCheckpoint(keep_last_n=...) writes")
        healthy = self.manager.latest_healthy_step()
        admit = self.sentinel.agree_rollback(healthy)
        if healthy is None:
            self.sentinel.raise_divergence(
                "no HEALTHY checkpoint to roll back to (a step is tagged "
                "healthy only after FLAGS_sentinel_healthy_windows clean "
                "windows pass beyond it)")
        self.sentinel.acquire_rollback(admit=admit)
        d = self.manager.step_dir(healthy)
        if os.path.exists(os.path.join(d, "model.pdparams")):
            # the ModelCheckpoint(keep_last_n=...) layout: hapi-pickled
            # model(+optimizer) inside the committed step dir
            self.model.load(os.path.join(d, "model"))
        else:
            self.manager.auto_resume(
                model=self.model.network,
                optimizer=getattr(self.model, "_optimizer", None),
                step=healthy)
        self.manager.drop_steps_after(healthy)
        if self.sentinel.lr_cooldown < 1.0:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None and hasattr(opt, "set_lr"):
                try:
                    opt.set_lr(opt.get_lr() * self.sentinel.lr_cooldown)
                except RuntimeError:
                    # scheduler-driven LR: set_lr is rejected by design —
                    # the schedule owns the rate; cooldown is a
                    # drive()-path feature there (_lr_scale)
                    pass
        # re-baseline: the restored (earlier, higher-loss) trajectory must
        # not read as the next spike
        self.sentinel.notify_rollback()


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "epochs": epochs, "steps": steps, "verbose": verbose,
        "metrics": metrics or [], "save_dir": save_dir,
    })
    return lst
