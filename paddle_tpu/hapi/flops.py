"""paddle.flops + paddle.summary standalone entry points.

Reference: python/paddle/hapi/dynamic_flops.py (hook-based per-layer FLOP
counting over a dummy forward; flops() at :28) and hapi/model_summary.py
(summary() at :28). Here the counting hooks ride the existing
``register_forward_post_hook`` layer machinery; per-op counting beyond the
registered layer types matches the reference's behavior of counting only
known layers.
"""

from __future__ import annotations

import numpy as np

__all__ = ["flops", "summary"]


def _count_linear(layer, inp, out):
    batch = int(np.prod(out.shape[:-1]))
    return batch * layer._in_features * layer._out_features


def _count_conv(layer, inp, out):
    # out elems * kernel volume * cin/groups (MACs)
    kernel = int(np.prod(layer.weight.shape[2:]))
    cin = layer.weight.shape[1]  # already cin/groups
    return int(np.prod(out.shape)) * kernel * cin


def _count_norm(layer, inp, out):
    return 2 * int(np.prod(out.shape))


def _layer_flops(layer, inp, out, custom_ops):
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import _ConvNd
    from ..nn.layer.norm import BatchNorm2D, GroupNorm, LayerNorm, RMSNorm

    if custom_ops and type(layer) in custom_ops:
        return int(custom_ops[type(layer)](layer, inp, out))
    if isinstance(layer, Linear):
        return _count_linear(layer, inp, out)
    if isinstance(layer, _ConvNd):
        return _count_conv(layer, inp, out)
    if isinstance(layer, (LayerNorm, RMSNorm, GroupNorm, BatchNorm2D)):
        return _count_norm(layer, inp, out)
    return 0


def flops(net, input_size=None, inputs=None, custom_ops=None,
          print_detail=False):
    """Total multiply-accumulate count of one forward pass.

    ``input_size``: shape list/tuple for a synthetic float32 input, or pass
    ``inputs`` (a Tensor or tuple of Tensors) directly.
    """
    import paddle_tpu as paddle

    if inputs is None:
        if input_size is None:
            raise ValueError("flops() needs input_size or inputs")
        inputs = paddle.to_tensor(
            np.zeros(tuple(input_size), np.float32))
    if not isinstance(inputs, (tuple, list)):
        inputs = (inputs,)

    total = {"flops": 0}
    rows = []
    handles = []

    def make_hook(lyr):
        def hook(layer, inp, out):
            first = out[0] if isinstance(out, (tuple, list)) else out
            n = _layer_flops(layer, inp, first, custom_ops)
            total["flops"] += n
            if n and print_detail:
                rows.append((type(layer).__name__, n))
            return out

        return hook

    for _, sub in net.named_sublayers():
        handles.append(sub.register_forward_post_hook(make_hook(sub)))
    was_training = getattr(net, "training", False)
    net.eval()
    try:
        net(*inputs)
    finally:
        for h in handles:
            remove = getattr(h, "remove", None)
            if remove:
                remove()
        if was_training:
            net.train()
    if print_detail:
        for name, n in rows:
            print(f"  {name}: {n:,}")
        print(f"Total FLOPs (MACs): {total['flops']:,}")
    return total["flops"]


def summary(net, input_size=None, dtypes=None, input=None):
    """Parameter-count summary (reference hapi/model_summary.py:28)."""
    n_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    lines = [f"{type(net).__name__}:"]
    for name, sub in net.named_sublayers():
        cnt = sum(int(np.prod(p.shape))
                  for p in sub.parameters(include_sublayers=False))
        if cnt:
            lines.append(f"  {name} ({type(sub).__name__}): {cnt:,}")
    lines.append(f"Total params: {n_params:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": n_params, "trainable_params": trainable}
