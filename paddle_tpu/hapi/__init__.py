"""paddle.hapi — high-level Keras-style API (reference: python/paddle/hapi/)."""

from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
    ReduceLROnPlateau,
)
from .model import DeferredScalar, Model  # noqa: F401
