"""hapi Model — the Keras-style high-level loop.

Reference: python/paddle/hapi/model.py (``Model`` :1054, ``fit`` :1756,
``prepare`` :1676). The reference maintains parallel dygraph/static adapter
classes; here there is one path — eager steps over the jit-cached dispatch
layer — so train_batch is already a compiled XLA program after the first
step. Data flows host numpy -> device per batch (the TPU input pipeline).
"""

from __future__ import annotations

import os
import warnings

import numpy as np

from .. import amp as _amp
from ..core.tensor import Tensor
from ..framework.io import load as _load, save as _save
from ..io import DataLoader, Dataset
from ..metric import Metric
from .callbacks import config_callbacks

__all__ = ["Model", "DeferredScalar"]


class DeferredScalar:
    """Lazy device scalar returned by ``train_batch``/``eval_batch``: holds
    the device value and materializes (ONE host round-trip — ~8–15 ms over
    the axon tunnel, PERF.md) only when converted via ``float()`` /
    ``numpy()`` / formatting. Until then it rides through logs dicts and
    callback plumbing without forcing a per-step device→host sync; the
    logging boundary (``log_freq``) is where conversion actually happens."""

    __slots__ = ("_data",)

    def __init__(self, value):
        self._data = value._data if isinstance(value, Tensor) else value

    def numpy(self):
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(np.asarray(self._data))

    def item(self):
        return float(self)

    def __format__(self, spec):
        return format(float(self), spec)

    def __repr__(self):
        return repr(float(self))

    # arithmetic/comparison compatibility with the plain float these APIs
    # used to return — each materializes (the caller chose the boundary)
    def __add__(self, o):
        return float(self) + o

    def __radd__(self, o):
        return o + float(self)

    def __sub__(self, o):
        return float(self) - o

    def __rsub__(self, o):
        return o - float(self)

    def __mul__(self, o):
        return float(self) * o

    def __rmul__(self, o):
        return o * float(self)

    def __truediv__(self, o):
        return float(self) / o

    def __rtruediv__(self, o):
        return o / float(self)

    def __neg__(self):
        return -float(self)

    def __abs__(self):
        return abs(float(self))

    def __lt__(self, o):
        return float(self) < o

    def __le__(self, o):
        return float(self) <= o

    def __gt__(self, o):
        return float(self) > o

    def __ge__(self, o):
        return float(self) >= o

    def __eq__(self, o):
        return float(self) == o

    def __ne__(self, o):
        return float(self) != o

    __hash__ = None  # mutable-ish device handle; hash like a list, not a float


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _tensorize(batch):
    out = []
    for b in _to_list(batch):
        out.append(b if isinstance(b, Tensor) else Tensor(np.asarray(b)))
    return out


class Model:
    """paddle.Model(network) -> prepare/fit/evaluate/predict/save/load."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._scaler = None
        self._plan = None
        self._planned_step = None
        self._planned_disabled = False
        self._planned_fallback_warned = False
        self.stop_training = False

    # -- setup -----------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, plan=None):
        """ref model.py:1676.

        ``plan``: a :class:`paddle_tpu.distributed.plan.Plan`. The
        network's parameters are committed to the plan's layouts and
        ``fit``/``train_batch`` route each update through a
        ``FusedTrainStep(plan=...)`` — i.e. the hapi loop compiles through
        the same ``compile_step_with_plan`` layer as fused training and
        serving (ROADMAP item 3). The planned fused path needs a prepared
        ``loss``; prepared Metrics or an AMP level fall back to the eager
        step (with the plan's parameter placement still applied) because
        metric update needs the forward outputs on the host."""
        self._optimizer = optimizer
        self._loss = loss
        self._plan = plan
        self._planned_step = None
        self._planned_disabled = False
        self._planned_fallback_warned = False
        if plan is not None:
            plan.apply_to_model(self.network)
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            assert isinstance(m, Metric), (
                f"metrics must be paddle.metric.Metric, got {type(m)}")
        if amp_configs:
            level = (amp_configs.get("level", "O1")
                     if isinstance(amp_configs, dict) else str(amp_configs))
            self._amp_level = level
            if level in ("O1", "O2"):
                self._scaler = _amp.GradScaler()
        else:
            self._amp_level = None
        return self

    # -- single-batch APIs ----------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        """ref model.py train_batch — one fwd/bwd(/step); returns
        ([loss], [metric results]). The loss is a :class:`DeferredScalar`
        — a lazy device value that materializes on ``float()`` — so a
        tight loop over train_batch does not pay a device→host round-trip
        per step (fetch happens at the logging boundary)."""
        assert self._optimizer is not None, "call prepare() first"
        self.network.train()
        inputs = _tensorize(inputs)
        labels = _tensorize(labels)

        if self._plan is not None:
            if not update:
                # gradient accumulation mixes eager grad state with the
                # fused step's in-graph update — incoherent. Before the
                # fused step ever runs, the session degrades to the eager
                # path; once it HAS run, its Adam moments and step count
                # live inside the fused step and an eager fallback would
                # silently discard them (bias correction restarting from
                # zero) — that is an error, not a degrade
                if self._planned_step is not None:
                    raise RuntimeError(
                        "Model.prepare(plan=...): train_batch(update="
                        "False) after planned steps have run would "
                        "discard the optimizer moments/step count held "
                        "by the fused planned step. prepare() without "
                        "plan= for gradient accumulation, or keep "
                        "update=True under the plan")
                self._planned_disabled = True
            step = self._planned_train_step(len(labels))
            if step is not None:
                loss = step(*inputs, *labels)
                return [DeferredScalar(loss)], []

        if self._amp_level in ("O1", "O2"):
            with _amp.auto_cast(level=self._amp_level):
                outs = self.network(*inputs)
            loss = self._compute_loss(outs, labels)
            scaled = self._scaler.scale(loss)
            scaled.backward()
            if update:
                self._scaler.step(self._optimizer)
                self._scaler.update()
                self._optimizer.clear_grad()
        else:
            outs = self.network(*inputs)
            loss = self._compute_loss(outs, labels)
            loss.backward()
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
        metrics = self._update_metrics(outs, labels)
        return [DeferredScalar(loss)], metrics

    def _planned_train_step(self, n_labels):
        """The ``FusedTrainStep(plan=...)`` the planned fit path
        dispatches through — built once, so the whole hapi loop compiles
        through ``compile_step_with_plan`` like fused training and the
        serving engine. Returns ``None`` (eager fallback, parameters
        still on the plan's layouts) when the prepared config cannot take
        the fused route: AMP, prepared Metrics (they need the forward
        outputs host-side), no prepared loss, or gradient accumulation."""
        if (self._planned_disabled or self._amp_level is not None
                or self._loss is None or self._metrics):
            pending = getattr(self, "_pending_opt_state", None)
            if pending is not None:
                # a Model.load stash destined for the fused step, but the
                # eager path owns optimizer state from here on — hand it
                # over (or say loudly why we can't) instead of silently
                # training with zeroed moments/step count
                self._pending_opt_state = None
                if self._fused_opt_format(pending):
                    warnings.warn(
                        "Model.load restored optimizer state in the "
                        "fused planned-step format, but this session "
                        "takes the eager fallback (AMP/metrics/gradient "
                        "accumulation) — the restored moments/step "
                        "count CANNOT be applied to the eager optimizer "
                        "and it starts fresh",
                        RuntimeWarning, stacklevel=3)
                else:
                    self._optimizer.set_state_dict(pending)
            if not self._planned_fallback_warned:
                self._planned_fallback_warned = True
                warnings.warn(
                    "Model.prepare(plan=...): the fused planned step "
                    "needs a prepared loss and no AMP/metrics/gradient "
                    "accumulation; falling back to the eager step "
                    "(parameters stay on the plan's layouts)",
                    RuntimeWarning, stacklevel=3)
            return None
        if self._planned_step is None:
            from ..incubate.fused_train_step import FusedTrainStep
            from ..nn.layer.layers import Layer

            net, loss_layer, k = self.network, self._loss, int(n_labels)

            class _NetLoss(Layer):
                """network + prepared loss as ONE forward so the fused
                step differentiates end to end (the label rides as the
                trailing ``k`` call arguments)."""

                def __init__(self):
                    super().__init__()
                    self.net = net
                    self.loss = loss_layer

                def forward(self, *args):
                    outs = self.net(*(args[:len(args) - k] if k else args))
                    labels = list(args[len(args) - k:]) if k else []
                    return loss_layer(*(_to_list(outs) + labels))

            # scoped("net."): _NetLoss prefixes every parameter name with
            # "net.", so rule tables anchored at the network root
            # ("llama.layers.*") would silently stop matching in the
            # fused step's in/out sharding pins — the scoped view strips
            # the prefix before rule matching (same mesh/fingerprint)
            self._planned_step = FusedTrainStep(
                _NetLoss(), self._optimizer, step_lr_scheduler=False,
                plan=self._plan.scoped("net."))
            self._planned_n_labels = k
            pending = getattr(self, "_pending_opt_state", None)
            if pending is not None:
                # optimizer state from Model.load that arrived before
                # this step existed (moments keyed "m1.net.<param>"
                # match because _NetLoss prefixes the SAME "net." path)
                if not self._fused_opt_format(pending):
                    # a plain-optimizer .pdopt (saved without a planned
                    # step): its "<tensor>_moment1" keys mean nothing to
                    # the fused step — say so instead of silently
                    # restoring nothing
                    warnings.warn(
                        "Model.load restored optimizer state in the "
                        "plain-optimizer format; the fused planned step "
                        "cannot adopt it and moments/step count start "
                        "fresh", RuntimeWarning, stacklevel=3)
                self._planned_step.set_state_dict(pending)
                self._pending_opt_state = None
        if self._planned_n_labels != n_labels:
            raise ValueError(
                f"planned train_batch was compiled for "
                f"{self._planned_n_labels} label(s), got {n_labels}")
        return self._planned_step

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = _tensorize(inputs)
        labels = _tensorize(labels)
        outs = self.network(*inputs)
        loss = self._compute_loss(outs, labels)
        metrics = self._update_metrics(outs, labels)
        return ([DeferredScalar(loss)] if loss is not None else [], metrics)

    def predict_batch(self, inputs):
        self.network.eval()
        outs = self.network(*_tensorize(inputs))
        return [o.numpy() for o in _to_list(outs)]

    def _compute_loss(self, outs, labels):
        if self._loss is None:
            out0 = _to_list(outs)[0]
            return out0 if out0.ndim == 0 or out0.size == 1 else None
        return self._loss(*(_to_list(outs) + labels))

    def _update_metrics(self, outs, labels):
        results = []
        pred = _to_list(outs)[0]
        for m in self._metrics:
            inp = m.compute(pred, *labels)
            if not isinstance(inp, (list, tuple)):
                inp = (inp,)
            m.update(*inp)
            results.append(m.accumulate())
        return results

    def _metric_logs(self, prefix=""):
        logs = {}
        for m in self._metrics:
            names = m.name()
            vals = m.accumulate()
            if isinstance(names, str):
                names, vals = [names], [vals]
            elif not isinstance(vals, (list, tuple)):
                vals = [vals]
            for n, v in zip(names, vals):
                logs[prefix + n] = v
        return logs

    def _reset_metrics(self):
        for m in self._metrics:
            m.reset()

    def _split_batch(self, batch):
        """Split a collated batch into (inputs, labels) by the prepared
        loss: the last element is the label. Raise clearly when a loss is
        prepared but the dataset yields no label slot."""
        if self._loss is None:
            return batch, []
        if len(batch) < 2:
            raise ValueError(
                "a loss was prepared, so each batch must be (inputs..., "
                f"label); the dataset yielded {len(batch)} element(s)")
        return batch[:-1], batch[-1:]

    def _as_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        from ..io.streaming import StreamingDataset

        # a StreamingDataset already yields collated BATCHES (its own
        # batch_size, sharding and resume cursor) — wrapping it in a
        # DataLoader would re-batch batches; pass it through like a
        # loader so fit() streams it via the DevicePrefetcher unchanged
        if data is None or isinstance(data, (DataLoader, StreamingDataset)):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    # -- loops -----------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, prefetch=True):
        """ref model.py:1756.

        Host–device overlap: train batches stream through a
        ``paddle.io.DevicePrefetcher`` (``prefetch=False`` disables) so
        host batch production + H2D transfer overlap the step's compute,
        and per-step losses stay lazy (:class:`DeferredScalar`) so the
        loop pays a device→host round-trip only at logging boundaries
        (``log_freq``; prepared Metrics still fetch per step — metric
        update is host-side accumulation by contract).

        Graceful preemption: a SIGTERM received while fitting stops at the
        next batch boundary, runs ``on_train_end`` callbacks (so a
        configured ModelCheckpoint saves), and raises
        ``SystemExit(123)`` — the elastic launcher's clean-preemption
        contract (relaunch without consuming restart budget)."""
        assert self._optimizer is not None, "call prepare() first"
        loader = self._as_loader(train_data, batch_size, shuffle,
                                 num_workers, drop_last)
        eval_loader = self._as_loader(eval_data, batch_size, False,
                                      num_workers, False)
        stream = loader
        if prefetch and loader is not None:
            from ..io.prefetch import DevicePrefetcher

            if not isinstance(loader, DevicePrefetcher):
                stream = DevicePrefetcher(
                    loader,
                    name=f"hapi.fit[{type(self.network).__name__}]"
                         ".prefetch")
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        # divergence sentinel (FLAGS_sentinel_action != 'none'): fit
        # exposes the same window-level spike detector drive() runs, as a
        # callback — an explicitly passed DivergenceSentinel wins
        from ..core.flags import flag_value
        from .callbacks import DivergenceSentinel, ModelCheckpoint

        callbacks = list(callbacks or [])
        if (str(flag_value("sentinel_action", "none")) != "none"
                and not any(isinstance(c, DivergenceSentinel)
                            for c in callbacks)):
            # a managed ModelCheckpoint in the same run provides the
            # rollback target store — without it, action=rollback would
            # escalate to raise at the first spike
            manager = None
            for c in callbacks:
                if isinstance(c, ModelCheckpoint) and c.save_dir \
                        and c.keep_last_n is not None:
                    manager = c._get_manager()
                    break
            callbacks.append(DivergenceSentinel(window=log_freq,
                                                manager=manager))
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            log_freq=log_freq, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=self._metrics)

        from ..distributed.launch import heartbeat as _hb

        self.stop_training = False
        cbks.on_train_begin()
        it = 0
        logs = {}
        # graceful preemption: a scheduler SIGTERM stops the loop at the
        # next batch boundary, runs the callbacks' end-of-training hooks
        # (ModelCheckpoint saves), and exits with the clean-preemption code
        # the elastic launcher relaunches budget-free
        with _hb.trap_preemption() as _preempt:
            try:
                from ..observability import trace as _obs_trace

                for epoch in range(epochs):
                    # epoch boundaries are host-side control flow — an
                    # allowed span site (ISSUE 10: spans only where the
                    # host already blocks); batches inside stay span-free
                    _epoch_span = _obs_trace.span(
                        "hapi.epoch", cat="train", args={"epoch": epoch})
                    try:
                        cbks.on_epoch_begin(epoch)
                        self._reset_metrics()
                        logs = {}
                        for step, batch in enumerate(stream):
                            cbks.on_train_batch_begin(step)
                            batch = _to_list(batch)
                            ins, labs = self._split_batch(batch)
                            update = (step + 1) % \
                                accumulate_grad_batches == 0
                            losses, _ = self.train_batch(ins, labs,
                                                         update=update)
                            logs = {"loss": losses[0],
                                    **self._metric_logs()}
                            cbks.set_params({**cbks.callbacks[0].params,
                                             "last_step": step})
                            cbks.on_train_batch_end(step, logs)
                            it += 1
                            # feed the launcher's hang watchdog (no-op
                            # when unsupervised: one env lookup)
                            _hb.write(step=it)
                            if _preempt.triggered:
                                self.stop_training = True
                                break
                            if num_iters is not None and it >= num_iters:
                                break
                        cbks.on_epoch_end(epoch, logs)
                    finally:
                        # the failing epoch must still land in the trace
                        _epoch_span.end()

                    if eval_loader is not None and not _preempt.triggered \
                            and (epoch + 1) % eval_freq == 0:
                        with _obs_trace.span("hapi.eval", cat="train",
                                             args={"epoch": epoch}):
                            self._run_eval(eval_loader, cbks)
                    if self.stop_training:
                        break
                    if num_iters is not None and it >= num_iters:
                        break
            finally:
                # a consumer abandoning iteration (error, num_iters cap,
                # preemption) must not leak the prefetcher's staging
                # thread — close() drains and joins it
                if stream is not loader and hasattr(stream, "close"):
                    stream.close()
            cbks.on_train_end(logs)
            if _preempt.triggered:
                raise SystemExit(_hb.PREEMPT_EXIT_CODE)
        return self

    def _run_eval(self, loader, cbks):
        self._reset_metrics()
        cbks.on_eval_begin()
        losses = []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            batch = _to_list(batch)
            ins, labs = self._split_batch(batch)
            l, _ = self.eval_batch(ins, labs)
            losses.extend(l)
            cbks.on_eval_batch_end(step)
        # lazy eval losses materialize HERE, at the eval logging boundary —
        # stacked on device first so the whole eval pays ONE host
        # round-trip, not one per batch
        if losses:
            import jax.numpy as jnp

            stacked = np.asarray(jnp.stack(
                [jnp.asarray(l._data if isinstance(l, DeferredScalar)
                             else float(l), jnp.float32) for l in losses]))
            eval_loss = {"eval_loss": float(stacked.mean())}
        else:
            eval_loss = {}
        logs = {**eval_loss, **self._metric_logs("eval_")}
        # EarlyStopping monitors unprefixed names too
        logs.update({k[len("eval_"):]: v for k, v in logs.items()
                     if k.startswith("eval_")})
        cbks.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        """ref model.py evaluate — returns dict of eval metrics."""
        loader = self._as_loader(eval_data, batch_size, False, num_workers,
                                 False)
        cbks = config_callbacks(callbacks, model=self, epochs=1,
                                steps=None, verbose=verbose,
                                metrics=self._metrics)
        return self._run_eval(loader, cbks)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        """ref model.py predict — list (per output) of per-batch arrays."""
        loader = self._as_loader(test_data, batch_size, False, num_workers,
                                 False)
        # datasets often yield (x, label) even for predict; feed only as many
        # leading elements as the network's forward takes (the reference
        # resolves this via its `inputs` specs)
        import inspect

        try:
            sig = inspect.signature(self.network.forward)
            npos = len([p for p in sig.parameters.values()
                        if p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD)
                        and p.default is p.empty])
        except (TypeError, ValueError):
            npos = None
        outputs = None
        for batch in loader:
            batch = _to_list(batch)
            if npos:
                batch = batch[:npos]
            outs = self.predict_batch(batch)
            if outputs is None:
                outputs = [[] for _ in outs]
            for slot, o in zip(outputs, outs):
                slot.append(o)
        if outputs is None:
            return []
        if stack_outputs:
            return [np.concatenate(slot) for slot in outputs]
        return outputs

    # -- persistence / introspection -------------------------------------
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            # while a planned fit trains, the moments / bias-correction
            # step live in the FusedTrainStep (in-graph, donated), not in
            # the wrapped optimizer's accumulators — the step object is
            # the authoritative optimizer state (same contract as
            # CheckpointManager.save(optimizer=fused_step))
            pending = getattr(self, "_pending_opt_state", None)
            if self._planned_step is not None:
                sd = self._planned_step.state_dict()
            elif pending is not None:
                # loaded under a plan but no planned batch has run yet:
                # the restored state is still in the stash — round-trip
                # it instead of writing the fresh optimizer's empty state
                sd = pending
            else:
                sd = self._optimizer.state_dict()
            _save(sd, path + ".pdopt")

    @staticmethod
    def _fused_opt_format(sd):
        """Whether an optimizer state dict is in the FusedTrainStep
        format ("step_count" / "m1.<param>" keys) vs the plain-optimizer
        one ("<tensor>_moment1" / "global_step")."""
        return "step_count" in sd or any(
            k.startswith(("m1.", "m2.")) for k in sd)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        self.network.set_state_dict(_load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            sd = _load(opt_path)
            if self._planned_step is not None:
                if not self._fused_opt_format(sd):
                    # same mismatch the pre-build stash path warns on:
                    # the fused step silently matches none of the plain
                    # "<tensor>_moment1" keys
                    warnings.warn(
                        "Model.load: optimizer state is in the plain-"
                        "optimizer format; the fused planned step "
                        "cannot adopt it and moments/step count start "
                        "fresh", RuntimeWarning, stacklevel=2)
                self._planned_step.set_state_dict(sd)
            elif self._plan is not None:
                # planned checkpoint restored before the first planned
                # batch built the fused step: stash it —
                # _planned_train_step applies it on construction
                self._pending_opt_state = sd
            else:
                if self._fused_opt_format(sd):
                    # fourth cross-format path: a planned save's
                    # "m1.net.*"/"step_count" keys mean nothing to the
                    # plain optimizer — warn like the mirror cases
                    warnings.warn(
                        "Model.load: optimizer state is in the fused "
                        "planned-step format; the plain optimizer "
                        "cannot adopt it and moments/step count start "
                        "fresh", RuntimeWarning, stacklevel=2)
                self._optimizer.set_state_dict(sd)
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        n_params = sum(int(np.prod(p.shape))
                       for p in self.network.parameters())
        trainable = sum(int(np.prod(p.shape))
                        for p in self.network.parameters()
                        if not p.stop_gradient)
        lines = [f"{type(self.network).__name__}:"]
        for name, sub in self.network.named_sublayers():
            cnt = sum(int(np.prod(p.shape))
                      for p in sub.parameters(include_sublayers=False))
            if cnt:
                lines.append(f"  {name} ({type(sub).__name__}): {cnt:,}")
        lines.append(f"Total params: {n_params:,}")
        lines.append(f"Trainable params: {trainable:,}")
        text = "\n".join(lines)
        print(text)
        return {"total_params": n_params, "trainable_params": trainable}
