"""Regularizers (reference: python/paddle/regularizer.py). Only the decay
coefficient matters — optimizers read ``_coeff`` and fold L2 into the jitted
update (L1 applied via sign term)."""

__all__ = ["L1Decay", "L2Decay"]


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def __repr__(self):
        return f"L2Decay({self._coeff})"


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
        self._l1 = True

    def __repr__(self):
        return f"L1Decay({self._coeff})"
