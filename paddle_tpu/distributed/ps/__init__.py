"""Parameter-server equivalent: SPMD-sharded sparse embedding tables.

Reference: the brpc parameter-server stack —
``paddle/fluid/distributed/ps/service/brpc_ps_server.h:1`` (servers),
``paddle/fluid/distributed/ps/table/memory_sparse_table.cc:1`` (sparse tables),
``python/paddle/distributed/ps/the_one_ps.py:1`` (python orchestration),
``python/paddle/static/nn/common.py`` ``sparse_embedding`` (user API).

TPU-native redesign (SURVEY.md §7.1 "PS / sparse tables"): there are no
separate server processes — the embedding table is a normal parameter
row-sharded over a mesh axis (SparseCore-style). A lookup is a plain gather
with the table sharded on dim 0; GSPMD compiles it to exactly the PS
pull protocol: each device gathers the rows it owns (masked local gather) and
an all-reduce combines partial rows across table shards — verified in
tests/test_deepfm.py by inspecting the compiled HLO. The gradient transposes
to a local scatter-add, which is the PS push. Sync/async/geo modes collapse:
SPMD training is synchronous by construction.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...nn import functional as F
from ...nn.initializer import Uniform
from ...nn.layer.layers import Layer

__all__ = ["SparseEmbedding", "sparse_embedding"]


def _table_mesh(mesh, axis):
    """Resolve (mesh, axis-names tuple) for table sharding."""
    if mesh is None:
        from ..fleet.fleet import fleet_singleton

        try:
            mesh = fleet_singleton.get_hybrid_communicate_group().mesh
        except Exception:
            return None, ()
    if isinstance(axis, str):
        axis = (axis,)
    axis = tuple(a for a in axis if a in mesh.shape and mesh.shape[a] > 1)
    return mesh, axis


class SparseEmbedding(Layer):
    """Row-sharded embedding table — the ``sparse_embedding`` /
    ``memory_sparse_table`` analog.

    ``axis`` names the mesh axes the vocab dim shards over (default the data
    axis: in PS deployments the table is partitioned across the same hosts
    that hold the data shards). On a 1-wide axis or without a mesh this is a
    plain Embedding.
    """

    def __init__(self, num_embeddings, embedding_dim, axis=("dp",),
                 padding_idx=None, weight_attr=None, mesh=None, name=None,
                 entry=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self._entry = entry
        mesh, axes = _table_mesh(mesh, axis)
        self._mesh = mesh
        self._axes = axes
        # pad the row count up to a shard multiple so arbitrary vocab sizes
        # (criteo's 1000001) still shard; ids never index the pad rows, and
        # their grads stay zero
        rows = num_embeddings
        nshards = 1
        if mesh is not None and axes:
            nshards = int(np.prod([mesh.shape[a] for a in axes]))
            rows = -(-num_embeddings // nshards) * nshards
        scale = 1.0 / np.sqrt(embedding_dim)
        self.weight = self.create_parameter(
            [rows, embedding_dim], attr=weight_attr,
            default_initializer=Uniform(-scale, scale))
        if nshards > 1:
            spec = (axes if len(axes) > 1 else axes[0], None)
            sharding = NamedSharding(mesh, P(*spec))
            self.weight._data = jax.device_put(self.weight._data, sharding)
            self.weight._placement = (mesh, spec)

        if entry is not None:
            self._init_entry(entry)

    # ---- admission filtering (scoped-down CTR accessor) ----------------
    # Reference: paddle/fluid/distributed/ps/table/ctr_accessor.cc — the PS
    # table admits a sparse feature into training by show-count threshold
    # (CountFilterEntry) or by probability on first sight (ProbabilityEntry);
    # un-admitted rows serve their init values and take no updates. Here the
    # same gate is a per-row admitted mask: forward counts the batch's ids
    # eagerly, and a gradient hook on the table zeroes un-admitted rows, so
    # the scatter-add push skips them and they stay at init.
    def _init_entry(self, entry):
        import jax.numpy as jnp

        rows = self.weight.shape[0]
        kind = getattr(entry, "_name", None)
        if kind not in ("count_filter_entry", "probability_entry"):
            raise TypeError(
                "entry must be a CountFilterEntry or ProbabilityEntry, got "
                f"{type(entry).__name__}")
        self._entry_kind = kind
        self._counts = jnp.zeros((rows,), jnp.int32)
        self._admitted = jnp.zeros((rows,), jnp.bool_)
        self.weight.register_hook(self._mask_grad)

    def _mask_grad(self, grad):
        mask = self._admitted.astype(grad._data.dtype)
        from ...core.tensor import Tensor

        return Tensor._wrap(grad._data * mask[:, None])

    def _observe(self, x):
        import jax
        import jax.numpy as jnp

        ids = (x._data if hasattr(x, "_data") else jnp.asarray(x)) \
            .reshape(-1).astype(jnp.int32)
        if self._entry_kind == "count_filter_entry":
            self._counts = self._counts.at[ids].add(1)
            self._admitted = self._counts >= self._entry._count
        else:  # probability_entry: draw once, on first sight
            from ...core import rng

            first_seen = (self._counts == 0).take(ids)
            self._counts = self._counts.at[ids].add(1)
            draw = jax.random.bernoulli(
                rng.DEFAULT_GENERATOR.next_key(),
                self._entry._probability, ids.shape)
            newly = jnp.zeros_like(self._admitted).at[ids].max(
                jnp.logical_and(first_seen, draw))
            self._admitted = jnp.logical_or(self._admitted, newly)

    def forward(self, x):
        if self._entry is not None and self.training:
            from ...core import state

            if state.in_trace():
                # the count/admit gate is eager host-side state, and the
                # grad hook rides the eager tape — a traced/fused step
                # (to_static, fused_train_step) bypasses BOTH. Never
                # silently: train filtered tables with the eager loop.
                import warnings

                warnings.warn(
                    "SparseEmbedding admission filtering (entry=...) is "
                    "BYPASSED inside a traced/fused train step: id counting "
                    "and the gradient gate only run in the eager loop. "
                    "Train this table eagerly, or drop the entry filter.",
                    stacklevel=2)
            else:  # counting is an eager host-side gate
                self._observe(x)
        self._note_lookup(x)
        # plain gather; GSPMD turns it into masked local gather + all-reduce
        # when the table is sharded (the PS pull)
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def _note_lookup(self, x):
        """Record the batch's row ids for the eager lazy-Adam path
        (``Adam(lazy_mode=True)`` gathers only these rows of the dense
        autograd gradient — see ops/sparse_grad.py). Inside a trace the
        capture mechanism owns id tracking instead."""
        from ...core import state
        from ...ops import sparse_grad

        if self.training and not state.in_trace() \
                and not self.weight.stop_gradient:
            sparse_grad.note_eager_lookup(self.weight, x)

    def pooled(self, x, mode="sum"):
        """Fused lookup+pool over the trailing field axis
        (``F.embedding_bag``): returns ``[..., dim]`` without ever
        materializing the ``[..., F, dim]`` per-field intermediate —
        DeepFM's first-order term uses this so its ``[B, F, 1]`` tensor
        never exists."""
        if mode not in ("sum", "mean"):
            raise ValueError(
                f"pooled mode must be 'sum' or 'mean', got {mode!r}")
        if self._entry is not None and self.training:
            # admission filtering needs the eager forward (count gate +
            # grad hook); pool its output with the SAME padding semantics
            # as F.embedding_bag — padding rows are zero in the sum and
            # excluded from the mean's denominator
            rows = self.forward(x)
            out = rows.sum(-2)
            if mode == "sum":
                return out
            if self._padding_idx is None:
                return out / float(x.shape[-1])
            keep = (x != self._padding_idx).astype(rows.dtype)
            n = keep.sum(-1, keepdim=True)
            n = n + (n == 0).astype(rows.dtype)  # live-count floor of 1
            return out / n
        self._note_lookup(x)
        return F.embedding_bag(x, self.weight, mode=mode,
                               padding_idx=self._padding_idx)


_FUNCTIONAL_TABLES: dict = {}


def _table_key(name, size, padding_idx):
    """Unnamed calls key on the CALL SITE (filename:lineno), so two distinct
    unnamed embeddings of the same size get distinct tables while the same
    call site reuses its table across training steps — matching the
    reference, where each static-graph sparse_embedding op owns a uniquely
    named parameter."""
    import sys

    if name is None:
        f = sys._getframe(2)
        name = f"{f.f_code.co_filename}:{f.f_lineno}"
    return (name, tuple(int(s) for s in size),
            None if padding_idx is None else int(padding_idx))


def sparse_embedding(input, size, padding_idx=None, param_attr=None,
                     dtype="float32", name=None, **kwargs):
    """Functional facade matching paddle.static.nn.sparse_embedding's
    signature shape. The table persists across calls (see _table_key).
    Prefer the SparseEmbedding layer (whose weight joins
    ``model.parameters()``); for this facade fetch the table via
    ``sparse_embedding.get_table(...)`` and pass its ``.weight`` to the
    optimizer explicitly; ``sparse_embedding.reset()`` clears all tables
    (fresh model)."""
    entry = kwargs.get("entry")
    # the entry filter is part of the table's identity: an entry-less call
    # must not reuse (or silently create) a filtered table
    entry_key = (None if entry is None
                 else (getattr(entry, "_name", type(entry).__name__),
                       getattr(entry, "_count",
                               getattr(entry, "_probability", None))))
    key = _table_key(name, size, padding_idx) + (entry_key,)
    layer = _FUNCTIONAL_TABLES.get(key)
    if layer is None:
        layer = SparseEmbedding(size[0], size[1], padding_idx=padding_idx,
                                weight_attr=param_attr, entry=entry)
        _FUNCTIONAL_TABLES[key] = layer
    return layer(input)


def _get_table(name, size, padding_idx=None):
    return _FUNCTIONAL_TABLES.get(_table_key(name, size, padding_idx))


sparse_embedding.get_table = _get_table
sparse_embedding.reset = _FUNCTIONAL_TABLES.clear
