"""Elastic training manager.

Reference: python/paddle/distributed/fleet/elastic/manager.py (ElasticManager
:126; fault-tolerance levels :176-186) — hosts register in etcd, and on
membership change the manager rewrites the endpoint env and relaunches
trainers; two levels: FAULT_TOLERANCE (fixed np, restart) and ELASTIC
(np range "min:max", scale up/down).

TPU-native redesign: there is no etcd in a TPU deployment — membership is
owned by the cluster scheduler + ``jax.distributed``'s coordination service
(SURVEY §5.3). What the framework must supply is the DECISION layer: given
membership events, decide restart vs rescale and produce the new env. That
logic lives here against a pluggable ``Store`` (an in-memory/file store
locally; the scheduler's API in production), which keeps it unit-testable
without a cluster, exactly like the reference's unit tests fake etcd.
"""

from __future__ import annotations

import json
import os
import time

__all__ = ["ElasticManager", "ElasticStatus", "ELASTIC_AUTO_PARALLEL_EXIT_CODE",
           "MemoryStore", "FileStore"]

ELASTIC_EXIT_CODE = 101
ELASTIC_AUTO_PARALLEL_EXIT_CODE = 102


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


def _expired(ts, ttl, now):
    return ttl is not None and ttl > 0 and now - ts > ttl


class MemoryStore:
    """In-process host registry (test double for the coordination service).
    ``register(host, ttl=…)`` is a lease: a host that stops re-registering
    (heartbeating) within ``ttl`` seconds is pruned on the next ``hosts()``
    read — a dead host expires instead of holding membership forever."""

    def __init__(self):
        self._hosts = {}

    def register(self, host, ttl=None):
        self._hosts[host] = (time.time(), ttl)

    def deregister(self, host):
        self._hosts.pop(host, None)

    def hosts(self):
        now = time.time()
        for h in [h for h, (ts, ttl) in self._hosts.items()
                  if _expired(ts, ttl, now)]:
            del self._hosts[h]
        return sorted(self._hosts)


class FileStore:
    """Shared-filesystem host registry (works across local processes).
    Read-modify-write sequences hold an fcntl lock on a sidecar lockfile so
    concurrent registrations cannot drop each other."""

    def __init__(self, path):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock_path = path + ".lock"
        if not os.path.exists(path):
            with self._locked():
                if not os.path.exists(path):
                    self._write({})

    def _locked(self):
        import contextlib
        import fcntl

        @contextlib.contextmanager
        def cm():
            with open(self._lock_path, "a+") as lf:
                fcntl.flock(lf, fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(lf, fcntl.LOCK_UN)

        return cm()

    def _read(self):
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _write(self, d):
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(d, f)
        os.replace(tmp, self.path)

    def register(self, host, ttl=None):
        with self._locked():
            d = self._read()
            d[host] = [time.time(), ttl]
            self._write(d)

    def deregister(self, host):
        with self._locked():
            d = self._read()
            d.pop(host, None)
            self._write(d)

    @staticmethod
    def _entry(v):
        # pre-TTL files stored a bare timestamp; treat those as no-expiry
        return (v, None) if isinstance(v, (int, float)) else (v[0], v[1])

    def hosts(self):
        d = self._read()
        now = time.time()
        dead = [h for h, v in d.items() if _expired(*self._entry(v), now)]
        if dead:
            # prune-on-read: rewrite under the lock so every reader
            # converges on the same membership
            with self._locked():
                d = self._read()
                for h in list(d):
                    if _expired(*self._entry(d[h]), now):
                        del d[h]
                self._write(d)
        return sorted(h for h, v in d.items()
                      if not _expired(*self._entry(v), now))


def _parse_np(np_spec):
    """'4' -> (4, 4); '2:6' -> (2, 6) (ref manager.py np range parsing)."""
    s = str(np_spec)
    if ":" in s:
        lo, hi = s.split(":")
        return int(lo), int(hi)
    n = int(s)
    return n, n


class ElasticManager:
    """Membership -> decision engine (ref manager.py:126)."""

    def __init__(self, np_spec, host=None, store=None, scale_interval=5,
                 host_ttl=None):
        self.min_np, self.max_np = _parse_np(np_spec)
        self.elastic = self.min_np != self.max_np  # level 2 vs FAULT_TOLERANCE
        self.host = host or os.environ.get("POD_IP", "127.0.0.1")
        self.store = store or MemoryStore()
        self.scale_interval = scale_interval
        # host_ttl turns registration into a lease: a host that stops
        # heartbeating (re-calling register()) within host_ttl seconds is
        # expired from hosts() on read, so watch() sees the membership
        # shrink and decides RESTART/HOLD/ERROR — a dead host can no longer
        # hold its slot forever (ref manager.py etcd lease TTL)
        self.host_ttl = host_ttl
        self.np = self.max_np if not self.elastic else self.min_np
        self._last_hosts = None

    # ---- membership -----------------------------------------------------
    def register(self):
        self.store.register(self.host, ttl=self.host_ttl)

    heartbeat = register  # lease renewal is just re-registration

    def exit(self, completed=True):
        self.store.deregister(self.host)

    def hosts(self):
        return self.store.hosts()

    # ---- decisions ------------------------------------------------------
    def ready(self):
        """Enough hosts to launch? (ref manager.py wait for np hosts)."""
        return len(self.hosts()) >= self.min_np

    def watch(self):
        """One membership poll -> ElasticStatus. RESTART means the caller
        must rewrite env (``new_env``) and relaunch trainers."""
        hosts = self.hosts()
        n = len(hosts)
        if self._last_hosts is None:
            self._last_hosts = hosts
        if hosts == self._last_hosts:
            return ElasticStatus.HOLD
        if n < self.min_np:
            # below quorum: hold for FT level (host may come back), error
            # for a shrink below the floor in elastic mode
            self._last_hosts = hosts
            return (ElasticStatus.HOLD if not self.elastic
                    else ElasticStatus.ERROR)
        if not self.elastic:
            # fixed np: a replaced host is a plain restart at the same np
            self._last_hosts = hosts
            return ElasticStatus.RESTART
        # elastic: rescale into [min, max]
        self.np = min(n, self.max_np)
        self._last_hosts = hosts
        return ElasticStatus.RESTART

    def new_env(self, base_env=None, port=8471):
        """Env block for the relaunch at the current membership (the
        reference rewrites PADDLE_TRAINERS / DISTRIBUTED_TRAINER_ENDPOINTS)."""
        hosts = self.hosts()[:self.np]
        env = dict(base_env or {})
        env.update({
            "PADDLE_TRAINERS_NUM": str(len(hosts)),
            "PADDLE_TRAINERS": ",".join(hosts),
            "DISTRIBUTED_TRAINER_ENDPOINTS": ",".join(
                f"{h}:{port}" for h in hosts),
            "PADDLE_MASTER": hosts[0] if hosts else "",
            "MASTER_ADDR": hosts[0] if hosts else "",
            "MASTER_PORT": str(port),
        })
        return env
